#!/usr/bin/env python3
"""Generate small synthetic Avro datasets for the example scripts.

Creates under DATA_DIR (default ./example-data):
  glm/train, glm/validate      — logistic regression TrainingExampleAvro
  game/train, game/validate    — GLMix-shaped data: global features + a
                                 per-user bias, userId in metadataMap

The generating model is y ~ Bernoulli(sigmoid(x.w + bias_user)), so the GAME
run demonstrably beats the fixed effect alone on AUC.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from photon_ml_tpu.io import schemas  # noqa: E402
from photon_ml_tpu.io.avro_codec import write_container  # noqa: E402


def _write(path: Path, records) -> None:
    path.mkdir(parents=True, exist_ok=True)
    write_container(path / "part-00000.avro", schemas.TRAINING_EXAMPLE,
                    records)
    print(f"wrote {len(records)} records to {path}")


def glm_records(rng, n, w):
    d = len(w) - 1
    out = []
    for i in range(n):
        x = rng.normal(0, 1, d)
        z = float(x @ w[:-1] + w[-1])
        out.append({
            "uid": f"u{i}",
            "label": float(rng.random() < 1 / (1 + np.exp(-z))),
            "features": [{"name": f"f{j}", "term": None, "value": float(v)}
                         for j, v in enumerate(x)],
            "weight": None, "offset": None, "metadataMap": None,
        })
    return out


def game_records(rng, n, w, user_bias):
    out = []
    for i in range(n):
        u = int(rng.integers(0, len(user_bias)))
        x = rng.normal(0, 1, len(w))
        z = float(x @ w + user_bias[u])
        out.append({
            "uid": f"r{i}",
            "label": float(rng.random() < 1 / (1 + np.exp(-z))),
            "features": [{"name": f"x{j}", "term": None, "value": float(v)}
                         for j, v in enumerate(x)],
            "weight": None, "offset": None,
            "metadataMap": {"userId": f"user{u}"},
        })
    return out


def game_full_records(rng, n, w, user_bias, user_vecs, item_vecs):
    """Full-GAME shape: global fixed effect + per-user bias + a low-rank
    user x item interaction (the structure a factored/MF coordinate
    recovers), userId AND movieId in metadataMap."""
    out = []
    n_users, n_items = len(user_bias), len(item_vecs)
    for i in range(n):
        u = int(rng.integers(0, n_users))
        m = int(rng.integers(0, n_items))
        x = rng.normal(0, 1, len(w))
        z = float(x @ w + user_bias[u] + user_vecs[u] @ item_vecs[m])
        out.append({
            "uid": f"r{i}",
            "label": float(rng.random() < 1 / (1 + np.exp(-z))),
            "features": [{"name": f"x{j}", "term": None, "value": float(v)}
                         for j, v in enumerate(x)],
            "weight": None, "offset": None,
            "metadataMap": {"userId": f"user{u}", "movieId": f"movie{m}"},
        })
    return out


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-dir", type=Path, default=Path("example-data"))
    p.add_argument("--seed", type=int, default=17)
    p.add_argument("--num-train", type=int, default=2000)
    p.add_argument("--num-validate", type=int, default=600)
    p.add_argument("--num-users", type=int, default=40)
    args = p.parse_args(argv)
    rng = np.random.default_rng(args.seed)

    w_glm = rng.normal(0, 1, 9)  # 8 features + intercept
    _write(args.data_dir / "glm" / "train",
           glm_records(rng, args.num_train, w_glm))
    _write(args.data_dir / "glm" / "validate",
           glm_records(rng, args.num_validate, w_glm))

    w_game = rng.normal(0, 1, 5)
    bias = rng.normal(0, 1.5, args.num_users)
    _write(args.data_dir / "game" / "train",
           game_records(rng, args.num_train, w_game, bias))
    _write(args.data_dir / "game" / "validate",
           game_records(rng, args.num_validate, w_game, bias))

    # Full-GAME dataset (run_game_full.sh): adds movieId + a rank-2
    # user x item interaction for the factored/MF coordinate.
    n_items = max(10, args.num_users // 2)
    uvecs = rng.normal(0, 0.7, (args.num_users, 2))
    ivecs = rng.normal(0, 0.7, (n_items, 2))
    _write(args.data_dir / "game-full" / "train",
           game_full_records(rng, args.num_train, w_game, bias,
                             uvecs, ivecs))
    _write(args.data_dir / "game-full" / "validate",
           game_full_records(rng, args.num_validate, w_game, bias,
                             uvecs, ivecs))


if __name__ == "__main__":
    main()
