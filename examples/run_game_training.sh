#!/usr/bin/env bash
# End-to-end GAME (GLMix) demo: a global fixed effect plus per-user random
# effects trained by block coordinate descent, then batch scoring with the
# saved model — the pipeline of the reference's cli/game/training and
# cli/game/scoring drivers.
set -euo pipefail
cd "$(dirname "$0")/.."

DATA_DIR="${DATA_DIR:-example-data}"
OUT_DIR="${OUT_DIR:-example-out/game}"

[ -d "$DATA_DIR/game/train" ] || python examples/generate_example_data.py --data-dir "$DATA_DIR"
rm -rf "$OUT_DIR"

python -m photon_ml_tpu.cli.game_training_driver \
  --train-input-dirs "$DATA_DIR/game/train" \
  --validate-input-dirs "$DATA_DIR/game/validate" \
  --output-dir "$OUT_DIR/model" \
  --task-type LOGISTIC_REGRESSION \
  --fixed-effect-data-configurations "fixed:global" \
  --fixed-effect-optimization-configurations "fixed:50,1e-7,1.0,1.0,LBFGS,L2" \
  --random-effect-data-configurations "perUser:userId,global,4,-1,-1,-1" \
  --random-effect-optimization-configurations "perUser:30,1e-7,1.0,1.0,LBFGS,L2" \
  --updating-sequence fixed,perUser \
  --num-iterations 3 \
  --evaluators AUC,LOGISTIC_LOSS

python -m photon_ml_tpu.cli.game_scoring_driver \
  --input-dirs "$DATA_DIR/game/validate" \
  --game-model-input-dir "$OUT_DIR/model/best" \
  --output-dir "$OUT_DIR/scores" \
  --evaluators AUC

echo
echo "Outputs:"
find "$OUT_DIR" -maxdepth 3 -name '*.json' | sed 's/^/  /'
