#!/usr/bin/env bash
# End-to-end GLM pipeline demo (the analog of the reference's
# examples/run_photon_ml_driver.sh, without the spark-submit ceremony):
# generate data -> train a lambda-grid with warm starts -> validate ->
# select best -> write text + Avro models + diagnostics report.
set -euo pipefail
cd "$(dirname "$0")/.."

DATA_DIR="${DATA_DIR:-example-data}"
OUT_DIR="${OUT_DIR:-example-out/glm}"

[ -d "$DATA_DIR/glm/train" ] || python examples/generate_example_data.py --data-dir "$DATA_DIR"
rm -rf "$OUT_DIR"

python -m photon_ml_tpu.cli.glm_driver \
  --training-data-directory "$DATA_DIR/glm/train" \
  --validating-data-directory "$DATA_DIR/glm/validate" \
  --output-directory "$OUT_DIR" \
  --task LOGISTIC_REGRESSION \
  --format AVRO \
  --max-num-iterations 80 \
  --regularization-weights 100,10,1,0.1 \
  --regularization-type L2 \
  --optimizer LBFGS \
  --normalization-type STANDARDIZATION \
  --diagnostic-mode VALIDATE \
  --compute-variance true

echo
echo "Outputs in $OUT_DIR:"
find "$OUT_DIR" -maxdepth 2 | sed 's/^/  /'
echo
echo "Best-model coefficients (name\tterm\tcoefficient\tlambda):"
head -5 "$OUT_DIR/best-model/model.txt"
