#!/usr/bin/env bash
# Full-surface GAME demo (reference: cli/game/training DriverTest's
# fixed+random+factored matrix): a TRON-solved fixed effect, an
# elastic-net per-user random effect (OWL-QN path), and a factored
# (matrix-factorization) per-movie coordinate — then batch scoring with
# the saved model. Exercises every solver family and the latent-factor
# model IO (ml/avro/model/ModelProcessingUtils.scala:67-130).
set -euo pipefail
cd "$(dirname "$0")/.."

DATA_DIR="${DATA_DIR:-example-data}"
OUT_DIR="${OUT_DIR:-example-out/game-full}"

[ -d "$DATA_DIR/game-full/train" ] || python examples/generate_example_data.py --data-dir "$DATA_DIR"
rm -rf "$OUT_DIR"

# Build the feature index as PARTITIONED PALDB STORES (the reference's
# FeatureIndexingJob artifact — written by this package's own writer,
# then read back by the training driver: full round-trip interop).
python -m photon_ml_tpu.cli.feature_indexing \
  --data-path "$DATA_DIR/game-full/train" \
  --output-dir "$OUT_DIR/feature-index" \
  --format paldb --partition-num 2 --shard-name global

# Optimizer mini-DSL: maxIter,tol,lambda,downSampleRate,optimizer,regType
#  - fixed:     TRON + L2 (trust-region Newton-CG, TRON.scala defaults)
#  - perUser:   L-BFGS/OWL-QN + ELASTIC_NET (alpha folded via regType)
#  - perMovie:  factored coordinate "reOpt;latentOpt;mfMaxIter,numFactors"
python -m photon_ml_tpu.cli.game_training_driver \
  --train-input-dirs "$DATA_DIR/game-full/train" \
  --validate-input-dirs "$DATA_DIR/game-full/validate" \
  --feature-index-dir "$OUT_DIR/feature-index" \
  --output-dir "$OUT_DIR/model" \
  --task-type LOGISTIC_REGRESSION \
  --fixed-effect-data-configurations "fixed:global" \
  --fixed-effect-optimization-configurations "fixed:15,1e-5,1.0,1.0,TRON,L2" \
  --random-effect-data-configurations "perUser:userId,global,4,-1,-1,-1" \
  --random-effect-optimization-configurations "perUser:30,1e-6,0.5,1.0,LBFGS,ELASTIC_NET,0.5" \
  --factored-random-effect-data-configurations "perMovie:movieId,global,4,-1,-1,-1,IDENTITY" \
  --factored-random-effect-optimization-configurations \
      "perMovie:20,1e-6,1.0,1.0,LBFGS,L2;20,1e-6,1.0,1.0,LBFGS,L2;2,2" \
  --updating-sequence fixed,perUser,perMovie \
  --num-iterations 3 \
  --evaluators AUC,LOGISTIC_LOSS

python -m photon_ml_tpu.cli.game_scoring_driver \
  --input-dirs "$DATA_DIR/game-full/validate" \
  --game-model-input-dir "$OUT_DIR/model/best" \
  --output-dir "$OUT_DIR/scores" \
  --evaluators AUC

echo
echo "Latent-factor artifacts (factored/MF coordinate):"
find "$OUT_DIR/model/best" -name '*latent*' | sed 's/^/  /'
echo "Outputs:"
find "$OUT_DIR" -maxdepth 3 -name '*.json' | sed 's/^/  /'
