"""Event system for external observers
(reference: ml/event/Event.scala:27-60, EventEmitter.scala:24-72,
EventListener.scala:20-31 — listener classes registered by name from CLI
params, ml/Driver.scala:109-118)."""

from __future__ import annotations

import dataclasses
import importlib
import threading
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class Event:
    pass


@dataclasses.dataclass
class PhotonSetupEvent(Event):
    params: Dict[str, Any]


@dataclasses.dataclass
class TrainingStartEvent(Event):
    job_name: str


@dataclasses.dataclass
class TrainingFinishEvent(Event):
    job_name: str
    duration_seconds: float


@dataclasses.dataclass
class PhotonOptimizationLogEvent(Event):
    """Per-λ optimization telemetry (tracker states + metrics)."""

    reg_weight: float
    iterations: int
    converged_reason: str
    final_value: float
    metrics: Optional[Dict[str, float]] = None


class EventListener:
    def on_event(self, event: Event) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class EventEmitter:
    """Thread-safe listener registry mixin."""

    def __init__(self):
        self._listeners: List[EventListener] = []
        self._lock = threading.Lock()

    def register_listener(self, listener: EventListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def register_listener_by_name(self, class_path: str) -> None:
        """Reflective registration, e.g. 'my.module.MyListener'
        (the reference loads listener classes by name the same way)."""
        module, _, cls = class_path.rpartition(".")
        listener = getattr(importlib.import_module(module), cls)()
        if not isinstance(listener, EventListener):
            raise TypeError(f"{class_path} is not an EventListener")
        self.register_listener(listener)

    def send_event(self, event: Event) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for listener in listeners:
            listener.on_event(event)

    def clear_listeners(self) -> None:
        with self._lock:
            for listener in self._listeners:
                listener.close()
            self._listeners.clear()
