"""Compile-only TPU topology access for deviceless Mosaic AOT checks.

The image's local libtpu can build a compile-only PJRT client for an
abstract v5e topology — `jax.jit(...).lower(...).compile()` against its
devices runs the real Mosaic/XLA TPU compiler with no chip and no
tunnel (see dev_scripts/mosaic_aot_check.py and docs/KERNEL.md
§Verification). Shared by bench.py, the AOT gate, and the suite guard
test so the stale-lockfile recovery exists in exactly one place.
"""

from __future__ import annotations

import os

LOCKFILE = "/tmp/libtpu_lockfile"


def v5e_topology(name: str = "v5e:2x2"):
    """Topology description for an abstract v5e slice.

    libtpu takes a process-exclusive lockfile. A stale lock left by a
    dead compile-only process is removed and creation retried ONCE —
    but never when THIS process holds a live TPU backend (an on-chip
    bench run): yanking a live client's lock could corrupt the one-shot
    chip capture, and chip timings supersede the compile-only analysis
    anyway. `jax.default_backend()` is safe here — every caller has
    already initialized the backend (CPU or TPU), so this cannot trip
    the wedged-tunnel init hang.
    """
    import jax
    from jax.experimental import topologies

    try:
        return topologies.get_topology_desc(topology_name=name,
                                            platform="tpu")
    except Exception as e:  # noqa: BLE001
        if ("libtpu_lockfile" not in str(e)
                or jax.default_backend() == "tpu"):
            raise
        try:
            os.remove(LOCKFILE)
        except OSError:
            pass
        return topologies.get_topology_desc(topology_name=name,
                                            platform="tpu")
