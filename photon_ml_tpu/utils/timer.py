"""Stopwatch + measure combinators (reference: ml/util/Timer.scala:32-236)."""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Tuple, TypeVar

T = TypeVar("T")


class Timer:
    def __init__(self):
        self._start: Optional[float] = None
        self._stop: Optional[float] = None

    def start(self) -> "Timer":
        if self._start is not None and self._stop is None:
            raise RuntimeError("timer already running")
        self._start = time.perf_counter()
        self._stop = None
        return self

    def stop(self) -> "Timer":
        if self._start is None or self._stop is not None:
            raise RuntimeError("timer is not running")
        self._stop = time.perf_counter()
        return self

    @property
    def duration_seconds(self) -> float:
        if self._start is None:
            raise RuntimeError("timer never started")
        return (self._stop if self._stop is not None
                else time.perf_counter()) - self._start

    def __enter__(self) -> "Timer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @classmethod
    def measure(cls, fn: Callable[[], T]) -> Tuple[T, float]:
        t = cls().start()
        out = fn()
        t.stop()
        return out, t.duration_seconds


class PhaseTimer:
    """Named phase timings (the driver/estimator stage logs)."""

    def __init__(self):
        self.phases: Dict[str, float] = {}

    def time(self, name: str):
        outer = self

        class _Ctx:
            def __enter__(self):
                self.t = Timer().start()
                return self

            def __exit__(self, *exc):
                outer.phases[name] = outer.phases.get(name, 0.0) + \
                    self.t.stop().duration_seconds

        return _Ctx()
