"""Child-process environments with a forced virtual CPU device count.

Device-count behavior (``--mesh-devices`` on an N-chip host) can only be
exercised by a jax whose TOTAL device count is N, and
``--xla_force_host_platform_device_count`` must land in XLA_FLAGS before
jax initializes — so both the ``multi_device`` pytest fixture
(tests/conftest.py) and the bench ``stream_training.mesh`` children
(bench.py) spawn subprocesses with this environment. One builder keeps
the scrub-and-append rules from drifting between them.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Optional

_FORCE_COUNT_RE = re.compile(
    r"--xla_force_host_platform_device_count=\d+")


def forced_cpu_device_env(n_devices: int,
                          base_env: Optional[Dict[str, str]] = None
                          ) -> Dict[str, str]:
    """A copy of ``base_env`` (default: a snapshot of os.environ) whose
    child jax will see EXACTLY ``n_devices`` virtual CPU devices: any
    inherited device-count force is scrubbed from XLA_FLAGS (the test
    harness pins 8), the new count appended, and the platform pinned
    to cpu."""
    env = dict(os.environ if base_env is None else base_env)
    flags = _FORCE_COUNT_RE.sub("", env.get("XLA_FLAGS", "")).strip()
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count="
        f"{int(n_devices)}").strip()
    env["JAX_PLATFORMS"] = "cpu"
    return env
