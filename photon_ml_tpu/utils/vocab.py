"""Vectorized entity-vocabulary code lookup.

Every scoring path that joins a dataset's entity ids against a model's
vocabulary used to build a ``{str(name): code}`` python dict per call —
O(vocab) interpreted work with a ``str()`` per entry, sitting directly on
the request path (models/device_scoring.py, random_effect.py,
matrix_factorization.py). The replacement is one ``np.argsort`` over the
model vocab plus a ``np.searchsorted`` per query batch: all C loops, and
the serving engine amortizes the sort across requests by passing a
prebuilt ``SortedVocab``.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class SortedVocab:
    """A model vocabulary presorted for repeated searchsorted lookups.

    ``codes_of(names)`` returns, per name, the index of that name in the
    ORIGINAL vocab order (the model's code space), or -1 when absent —
    the reference's missing-join semantics (unknown entities score 0).
    """

    sorted_names: np.ndarray  # unicode, ascending
    order: np.ndarray  # i64: position in sorted_names -> original code

    @classmethod
    def build(cls, vocab) -> "SortedVocab":
        v = np.asarray(vocab)
        v = v.astype(str) if v.dtype.kind != "U" else v
        order = np.argsort(v, kind="stable")
        return cls(sorted_names=v[order], order=order.astype(np.int64))

    def codes_of(self, names) -> np.ndarray:
        q = np.asarray(names)
        q = q.astype(str) if q.dtype.kind != "U" else q
        if self.sorted_names.size == 0 or q.size == 0:
            return np.full(q.shape, -1, np.int64)
        pos = np.searchsorted(self.sorted_names, q)
        pos = np.minimum(pos, len(self.sorted_names) - 1)
        return np.where(self.sorted_names[pos] == q,
                        self.order[pos], -1)


def vocab_code_lookup(vocab, names) -> np.ndarray:
    """For each name in ``names``: its code (index) in ``vocab``, or -1.

    One-shot form of ``SortedVocab`` (sorts per call); equivalent to the
    old dict-based ``{str(n): i}`` lookup for duplicate-free vocabularies.
    """
    return SortedVocab.build(vocab).codes_of(names)
