"""Profiler integration (SURVEY §5: the reference relies on the Spark UI;
the TPU build's counterpart is jax.profiler traces viewable in
XProf/TensorBoard, plus the per-phase wall timers in utils/timer.py)."""

from __future__ import annotations

import contextlib
from typing import Optional


def maybe_trace(trace_dir: Optional[str]):
    """Context manager: a jax.profiler trace written to ``trace_dir`` when
    set, a no-op otherwise. Drivers wrap their train phase with this."""
    if not trace_dir:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.trace(str(trace_dir))
