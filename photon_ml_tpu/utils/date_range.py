"""Date ranges as dataset coordinates (reference: ml/util/DateRange.scala,
ml/util/DateRangeUtils and the daily-directory resolution in
ml/util/IOUtils.getInputPathsWithinDateRange:85-131 — train/validate input
dirs may hold date-partitioned subdirectories `daily/yyyy/MM/dd`)."""

from __future__ import annotations

import dataclasses
import datetime
from pathlib import Path
from typing import List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class DateRange:
    """Inclusive [start, end] date range (ml/util/DateRange.scala)."""

    start: datetime.date
    end: datetime.date

    def __post_init__(self):
        if self.start > self.end:
            raise ValueError(
                f"Invalid range: start date {self.start} comes after end "
                f"date {self.end}")

    def __str__(self) -> str:
        return f"{self.start}-{self.end}"

    def days(self) -> List[datetime.date]:
        n = (self.end - self.start).days
        return [self.start + datetime.timedelta(days=i) for i in range(n + 1)]

    @classmethod
    def from_dates(cls, start: str, end: str,
                   pattern: str = "%Y%m%d") -> "DateRange":
        try:
            s = datetime.datetime.strptime(start, pattern).date()
            e = datetime.datetime.strptime(end, pattern).date()
        except ValueError as exc:
            raise ValueError(
                f"Couldn't parse the date range: {start}-{end}") from exc
        return cls(s, e)

    @classmethod
    def from_string(cls, range_str: str) -> "DateRange":
        """'yyyyMMdd-yyyyMMdd' (DateRange.fromDates(range))."""
        parts = range_str.split("-")
        if len(parts) != 2:
            raise ValueError(
                f"Couldn't parse the date range: {range_str!r} "
                "(expected 'yyyyMMdd-yyyyMMdd')")
        return cls.from_dates(parts[0], parts[1])

    @classmethod
    def from_days_ago(cls, start_days_ago: int, end_days_ago: int,
                      today: Optional[datetime.date] = None) -> "DateRange":
        """Range ending `end_days_ago` before today
        (DateRange.fromDaysAgo)."""
        if start_days_ago < 0 or end_days_ago < 0:
            raise ValueError("days ago cannot be negative")
        today = today or datetime.date.today()
        return cls(today - datetime.timedelta(days=start_days_ago),
                   today - datetime.timedelta(days=end_days_ago))

    @classmethod
    def from_days_ago_string(cls, range_str: str,
                             today: Optional[datetime.date] = None
                             ) -> "DateRange":
        """'start-end' in days ago, e.g. '90-1'
        (GameParams trainDateRangeDaysAgo)."""
        parts = range_str.split("-")
        if len(parts) != 2:
            raise ValueError(
                f"Couldn't parse days-ago range: {range_str!r}")
        try:
            start, end = int(parts[0]), int(parts[1])
        except ValueError as e:
            raise ValueError(
                f"Couldn't parse days-ago range: {range_str!r}") from e
        # Semantic errors (reversed order, negative) propagate untouched.
        return cls.from_days_ago(start, end, today)


def resolve_paths_within_date_range(
    input_dirs: Sequence, date_range: DateRange,
    error_on_missing: bool = False,
) -> List[Path]:
    """For each input dir, collect `<dir>/daily/yyyy/MM/dd` subdirectories
    that exist within the range (IOUtils.getInputPathsWithinDateRange:105-131).
    Raises if a whole input dir yields nothing (or any day is missing with
    error_on_missing)."""
    out: List[Path] = []
    for input_dir in input_dirs:
        daily = Path(input_dir) / "daily"
        found = []
        for day in date_range.days():
            p = daily / f"{day.year:04d}" / f"{day.month:02d}" \
                / f"{day.day:02d}"
            if p.is_dir():
                found.append(p)
            elif error_on_missing:
                raise FileNotFoundError(f"Missing data folder {p}")
        if not found:
            raise FileNotFoundError(
                f"No data folder found between {date_range.start} and "
                f"{date_range.end} in {daily}")
        out.extend(found)
    return out


def resolve_input_dirs(
    input_dirs,
    date_range: Optional[str] = None,
    date_range_days_ago: Optional[str] = None,
    today: Optional[datetime.date] = None,
) -> List[Path]:
    """Driver-facing resolution: with neither range flag the dirs pass
    through unchanged; otherwise daily subdirectories are expanded
    (reference: GameParams trainDateRangeOpt / trainDateRangeDaysAgoOpt,
    applied in cli/game/GAMEDriver). input_dirs: a list, or the raw
    comma-separated CLI string (blank segments dropped)."""
    if isinstance(input_dirs, (str, Path)):
        input_dirs = [s.strip() for s in str(input_dirs).split(",")
                      if s.strip()]
    if not input_dirs:
        raise ValueError("no input directories given")
    if date_range is not None and date_range_days_ago is not None:
        raise ValueError(
            "specify at most one of date-range and date-range-days-ago")
    if date_range is not None:
        rng = DateRange.from_string(date_range)
    elif date_range_days_ago is not None:
        rng = DateRange.from_days_ago_string(date_range_days_ago, today)
    else:
        return [Path(d) for d in input_dirs]
    return resolve_paths_within_date_range(input_dirs, rng)
