"""Job logging to a file + console (reference: ml/util/PhotonLogger.scala:36-506,
which writes leveled logs to an HDFS file per job)."""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Optional

LOG_FILE_NAME = "log-message.txt"


def setup_photon_logger(output_dir: Optional[str] = None,
                        level: int = logging.INFO) -> logging.Logger:
    logger = logging.getLogger("photon_ml_tpu")
    logger.setLevel(level)
    fmt = logging.Formatter(
        "%(asctime)s %(levelname)s [%(name)s] %(message)s")
    if not any(isinstance(h, logging.StreamHandler)
               for h in logger.handlers):
        sh = logging.StreamHandler()
        sh.setFormatter(fmt)
        logger.addHandler(sh)
    if output_dir is not None:
        path = Path(output_dir) / LOG_FILE_NAME
        path.parent.mkdir(parents=True, exist_ok=True)
        # One job, one file: detach (and close) any file handler from a
        # previous job in this process, so runs don't bleed into each
        # other's log-message.txt or leak descriptors across a sweep.
        for h in [h for h in logger.handlers
                  if isinstance(h, logging.FileHandler)]:
            if h.baseFilename != str(path):
                logger.removeHandler(h)
                h.close()
        if not any(isinstance(h, logging.FileHandler) and
                   h.baseFilename == str(path) for h in logger.handlers):
            fh = logging.FileHandler(path)
            fh.setFormatter(fmt)
            logger.addHandler(fh)
    return logger
