"""Runtime retrace guard — the dynamic complement to jaxlint's static
``retrace-hazard`` rule (photon_ml_tpu/analysis, docs/ANALYSIS.md).

jaxlint proves the TREE has no per-call-recompilation patterns; this
module proves a RUN had none: it reads each jitted callable's compile
cache size (``jax.jit`` wrappers expose ``_cache_size()``), so "how many
times did XLA trace this?" becomes an assertable invariant instead of
ad-hoc counter bookkeeping. The serving engine's ExecutableCache and the
coordinate-descent fused step both register their executables here, and
tests assert their compile-count bounds through one shared mechanism
(the ``tracing_guard`` pytest fixture in tests/conftest.py).

Typical use::

    guard = TracingGuard()
    guard.track("step", jitted_step)     # or via ExecutableCache(guard=g)
    ... hot loop ...
    guard.assert_max_retraces(per_fn=1)  # every executable traced once

Names are cumulative: tracking a REPLACEMENT callable under a new name
(as ExecutableCache does on every build) keeps evicted executables'
traces in the total, so an evict-per-call regression cannot hide behind
fresh cache objects.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

__all__ = [
    "RetraceError",
    "TracingGuard",
    "trace_count",
    "assert_max_retraces",
]


class RetraceError(AssertionError):
    """A jitted callable traced (compiled) more often than its budget."""


def trace_count(fn: Callable, default: Optional[int] = None) -> int:
    """Number of traces a ``jax.jit``-wrapped callable has performed —
    its compile-cache size. ``default`` (if given) is returned for
    callables without cache introspection; otherwise TypeError."""
    sizer = getattr(fn, "_cache_size", None)
    if sizer is None:
        if default is not None:
            return default
        raise TypeError(
            f"{fn!r} exposes no jit cache introspection (_cache_size); "
            "pass a jax.jit-wrapped callable, or default= for "
            "best-effort counting")
    return int(sizer())


def assert_max_retraces(fn: Callable, max_traces: int,
                        name: str = "") -> None:
    """Assert a single jitted callable has traced at most ``max_traces``
    times (its total compile count, first trace included)."""
    n = trace_count(fn)
    if n > max_traces:
        label = name or getattr(fn, "__name__", repr(fn))
        raise RetraceError(
            f"{label}: traced {n} times, budget {max_traces} — something "
            "is defeating the jit cache (unstable static args, shifting "
            "shapes/dtypes, or per-call jit construction)")


class TracingGuard:
    """Registry of jitted callables with assertable trace budgets.

    ``track(name, fn)`` is cumulative and name-unique: re-tracking a name
    appends a generation suffix rather than forgetting the old callable,
    so totals count every executable ever built. Per-name budgets given
    at track time are checked by :meth:`verify` (which the pytest
    fixture runs at teardown)."""

    def __init__(self):
        self._fns: Dict[str, Callable] = {}
        self._budgets: Dict[str, int] = {}
        self.total_budget: Optional[int] = None

    def track(self, name: str, fn: Callable,
              max_traces: Optional[int] = None) -> Callable:
        base, n = name, 2
        while name in self._fns:
            name = f"{base}#{n}"
            n += 1
        self._fns[name] = fn
        if max_traces is not None:
            self._budgets[name] = max_traces
        return fn

    def __len__(self) -> int:
        return len(self._fns)

    def counts(self) -> Dict[str, int]:
        """name -> trace count; callables without jit introspection
        (e.g. test doubles) count 0."""
        return {name: trace_count(fn, default=0)
                for name, fn in self._fns.items()}

    def total_traces(self) -> int:
        return sum(self.counts().values())

    def set_budget(self, max_total: int) -> None:
        """Total-trace budget checked by :meth:`verify` (fixture
        teardown) — the declarative form of assert_max_retraces."""
        self.total_budget = max_total

    def assert_max_retraces(self, max_total: Optional[int] = None,
                            per_fn: Optional[int] = None) -> None:
        """``max_total``: bound on the SUM of trace counts (== "at most N
        executables were ever compiled" when entries are single-shape).
        ``per_fn``: bound every tracked callable individually (1 = each
        executable traced exactly at its first call, never again)."""
        counts = self.counts()
        if max_total is not None and sum(counts.values()) > max_total:
            worst = sorted(counts.items(), key=lambda kv: -kv[1])[:8]
            raise RetraceError(
                f"total traces {sum(counts.values())} exceed budget "
                f"{max_total} across {len(counts)} tracked callables "
                f"(worst: {worst}) — a bucket/cache key is not pinning "
                "what it should, or entries are evicted and rebuilt")
        if per_fn is not None:
            over = {k: v for k, v in counts.items() if v > per_fn}
            if over:
                raise RetraceError(
                    f"callables over the per-fn trace budget {per_fn}: "
                    f"{over} — their arguments' shapes/dtypes/statics "
                    "are not stable call-to-call")

    def verify(self) -> None:
        """Check every budget declared via track(..., max_traces=...) and
        set_budget(). No-op when no budgets were declared."""
        counts = self.counts()
        over = {k: (counts.get(k, 0), b)
                for k, b in self._budgets.items() if counts.get(k, 0) > b}
        if over:
            raise RetraceError(
                "tracked callables exceeded their declared trace "
                f"budgets: {over}")
        if self.total_budget is not None:
            self.assert_max_retraces(max_total=self.total_budget)
