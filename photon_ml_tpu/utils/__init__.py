"""Cross-cutting utilities: logging, timing, events."""

from photon_ml_tpu.utils.timer import Timer
from photon_ml_tpu.utils.logging_utils import setup_photon_logger
from photon_ml_tpu.utils.events import (
    Event,
    EventEmitter,
    EventListener,
    PhotonOptimizationLogEvent,
    TrainingFinishEvent,
    TrainingStartEvent,
)
from photon_ml_tpu.utils.tracing_guard import (
    RetraceError,
    TracingGuard,
    assert_max_retraces,
    trace_count,
)

__all__ = [
    "Timer",
    "setup_photon_logger",
    "Event",
    "EventEmitter",
    "EventListener",
    "PhotonOptimizationLogEvent",
    "TrainingStartEvent",
    "TrainingFinishEvent",
    "RetraceError",
    "TracingGuard",
    "assert_max_retraces",
    "trace_count",
]
