"""Mid-training checkpoint/resume for GAME coordinate descent.

The reference has NO mid-training checkpointing (SURVEY.md §5: persistence
is final model save + warm-start only) — this is a deliberate improvement.
State = (coordinate models, linear step counter, histories, best model)
saved every k coordinate updates; a killed run resumes from the last
complete step and reproduces the uninterrupted run bit-for-bit because
per-step PRNG keys are derived by `jax.random.fold_in(base, step)` rather
than sequential splitting.

Format: one pickle per step under <dir>/ckpt-<step>.pkl, written atomically
(tmp + rename) so a crash mid-write never corrupts the latest checkpoint;
device arrays are moved to host numpy first so files are
backend-independent (a TPU run can resume on CPU and vice versa).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import pickle
import re
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import numpy as np

_CKPT_RE = re.compile(r"ckpt-(\d+)\.pkl$")


def config_fingerprint(meta: Any) -> str:
    """Canonical identity hash of a checkpoint's configuration metadata.

    Dicts hash by sorted key (cosmetic insertion-order changes are benign);
    lists/tuples keep order (the coordinate updating sequence is semantic).
    Non-JSON scalars (enums, numpy numbers) fall back to ``str``.
    """
    blob = json.dumps(meta, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


def meta_fingerprints(meta: Any) -> set:
    """All fingerprints under which this metadata is recognized.

    Mapping-valued tags also hash under their legacy flattened string form
    ("k=v;..." sorted by key — what GameEstimator emitted before tags became
    mappings), so checkpoints written before the switch still resume.
    """
    fps = {config_fingerprint(meta)}
    if isinstance(meta, dict) and isinstance(meta.get("tag"), dict):
        legacy = ";".join(f"{k}={v}" for k, v in sorted(meta["tag"].items()))
        fps.add(config_fingerprint({**meta, "tag": legacy}))
    return fps


@dataclasses.dataclass
class CheckpointState:
    """Everything needed to resume CoordinateDescent.run mid-descent."""

    step: int  # number of completed coordinate updates
    models: Dict[str, Any]  # coordinate name -> sub-model (host arrays)
    objective_history: List[float]
    validation_history: List[Dict[str, float]]
    best_metric: Optional[float]
    best_models: Optional[Dict[str, Any]]  # host copy of best GameModel parts
    timings: Dict[str, float]
    # Per-coordinate optimizer trackers accumulated so far, so a resumed
    # result's trackers stay aligned with objective_history.
    trackers: Dict[str, list] = dataclasses.field(default_factory=dict)
    # Identity fingerprint (seed, coordinate names, config tag). Loading
    # into a run whose fingerprint differs is an error — without this a
    # resume could silently continue from a different configuration's state.
    meta: Optional[Dict[str, Any]] = None


def to_host(obj):
    """Recursively replace jax.Array leaves with numpy arrays in
    dataclasses / dicts / lists / tuples. Arrays come back as numpy; jnp
    consumers re-device them lazily on first use."""
    if isinstance(obj, jax.Array):
        return np.asarray(obj)
    if isinstance(obj, np.ndarray) or obj is None or isinstance(
            obj, (str, bytes, int, float, bool)):
        return obj
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        changes = {f.name: to_host(getattr(obj, f.name))
                   for f in dataclasses.fields(obj)}
        return dataclasses.replace(obj, **changes)
    if isinstance(obj, dict):
        return {k: to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(to_host(v) for v in obj)
    return obj


def checkpoint_path(directory, step: int) -> Path:
    return Path(directory) / f"ckpt-{step:08d}.pkl"


def save_checkpoint(directory, state: CheckpointState,
                    keep: int = 2) -> Path:
    """Atomic write + retention of the newest `keep` checkpoints."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    path = checkpoint_path(directory, state.step)
    tmp = path.with_suffix(".tmp")
    with open(tmp, "wb") as f:
        pickle.dump(to_host(state), f, protocol=pickle.HIGHEST_PROTOCOL)
    tmp.rename(path)

    steps = sorted(all_checkpoint_steps(directory))
    for old in steps[:-keep]:
        checkpoint_path(directory, old).unlink(missing_ok=True)
    return path


def all_checkpoint_steps(directory) -> List[int]:
    directory = Path(directory)
    if not directory.is_dir():
        return []
    return [int(m.group(1)) for p in directory.iterdir()
            if (m := _CKPT_RE.search(p.name))]


def latest_checkpoint(directory) -> Optional[Path]:
    steps = all_checkpoint_steps(directory)
    return checkpoint_path(directory, max(steps)) if steps else None


def load_checkpoint(path) -> CheckpointState:
    with open(path, "rb") as f:
        state = pickle.load(f)
    if not isinstance(state, CheckpointState):
        raise ValueError(f"{path} is not a CoordinateDescent checkpoint")
    return state
