"""Math and storage constants (reference: ml/constants/MathConst.scala)."""

HIGH_PRECISION_TOLERANCE = 1e-12
MEDIUM_PRECISION_TOLERANCE = 1e-8
LOW_PRECISION_TOLERANCE = 1e-4
EPSILON = 1e-15

# Classification: scores >= threshold are positive (reference MathConst
# POSITIVE_RESPONSE_THRESHOLD = 0.5).
POSITIVE_RESPONSE_THRESHOLD = 0.5
