"""Optimizer / regularization configuration.

Mirrors the reference's config vocabulary so CLI strings and model-metadata
JSON round-trip compatibly:
- OptimizerType {LBFGS, TRON} (ml/optimization/OptimizerType.scala:17)
- RegularizationType {NONE, L1, L2, ELASTIC_NET} with elastic-net weight
  splitting L1 = alpha*lambda, L2 = (1-alpha)*lambda
  (ml/optimization/RegularizationContext.scala:35-113)
- the six-field "maxIter,tol,lambda,downSampleRate,optimizer,regType" string
  (ml/optimization/GLMOptimizationConfiguration.scala:56-90)
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import Dict, Optional, Tuple


class OptimizerType(str, enum.Enum):
    LBFGS = "LBFGS"
    TRON = "TRON"


class RegularizationType(str, enum.Enum):
    NONE = "NONE"
    L1 = "L1"
    L2 = "L2"
    ELASTIC_NET = "ELASTIC_NET"


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    """Splits a total regularization weight into L1/L2 parts.

    Reference semantics (ml/optimization/RegularizationContext.scala):
    ELASTIC_NET with mixing alpha gives L1 = alpha*lambda, L2 = (1-alpha)*lambda.
    """

    reg_type: RegularizationType = RegularizationType.NONE
    elastic_net_alpha: Optional[float] = None

    def __post_init__(self):
        if self.reg_type == RegularizationType.ELASTIC_NET:
            a = self.elastic_net_alpha
            if a is None or not (0.0 <= a <= 1.0):
                raise ValueError(
                    f"ELASTIC_NET requires alpha in [0, 1], got {a}")
        elif self.elastic_net_alpha is not None:
            raise ValueError(
                f"alpha is only valid for ELASTIC_NET, got {self.reg_type}")

    def l1_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L1:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return self.elastic_net_alpha * reg_weight
        return 0.0

    def l2_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L2:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return (1.0 - self.elastic_net_alpha) * reg_weight
        return 0.0

    def to_json(self) -> Dict:
        return {
            "regularizationType": self.reg_type.value,
            "elasticNetParam": self.elastic_net_alpha,
        }

    @classmethod
    def from_json(cls, d: Dict) -> "RegularizationContext":
        return cls(RegularizationType(d["regularizationType"]),
                   d.get("elasticNetParam"))


# Box constraints: feature index -> (lower, upper).
ConstraintMap = Dict[int, Tuple[float, float]]


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """What the optimizer factory needs (ml/optimization/OptimizerConfig.scala).

    Defaults are per-optimizer in the factory (LBFGS: 100/1e-7,
    TRON: 15/1e-5), so None here means "use the optimizer's default".
    """

    optimizer_type: OptimizerType = OptimizerType.LBFGS
    max_iterations: Optional[int] = None
    tolerance: Optional[float] = None
    constraint_map: Optional[ConstraintMap] = None

    def __post_init__(self):
        if self.max_iterations is not None and self.max_iterations <= 0:
            raise ValueError(
                f"maxIterations must be positive, got {self.max_iterations}")
        if self.tolerance is not None and self.tolerance <= 0:
            raise ValueError(
                f"tolerance must be positive, got {self.tolerance}")

    def resolved(self) -> "OptimizerConfig":
        if self.optimizer_type == OptimizerType.TRON:
            mi, tol = 15, 1e-5
        else:
            mi, tol = 100, 1e-7
        return dataclasses.replace(
            self,
            max_iterations=(
                self.max_iterations if self.max_iterations is not None else mi),
            tolerance=self.tolerance if self.tolerance is not None else tol,
        )

    def to_json(self) -> Dict:
        r = self.resolved()
        return {
            "optimizerType": r.optimizer_type.value,
            "maximumIterations": r.max_iterations,
            "tolerance": r.tolerance,
            "constraintMap": (
                None if r.constraint_map is None
                else {str(k): list(v) for k, v in r.constraint_map.items()}),
        }

    @classmethod
    def from_json(cls, d: Dict) -> "OptimizerConfig":
        cm = d.get("constraintMap")
        return cls(
            OptimizerType(d["optimizerType"]),
            d.get("maximumIterations"),
            d.get("tolerance"),
            None if cm is None else {int(k): tuple(v) for k, v in cm.items()},
        )


@dataclasses.dataclass(frozen=True)
class GLMOptimizationConfiguration:
    """Per-coordinate optimization config.

    String form (CLI + model metadata, reference
    ml/optimization/GLMOptimizationConfiguration.scala:56-90):
      "maxIter,tolerance,regWeight,downSamplingRate,optimizerType,regType[,alpha]"
    """

    max_iterations: int = 20
    tolerance: float = 1e-5
    regularization_weight: float = 0.0
    down_sampling_rate: float = 1.0
    optimizer_type: OptimizerType = OptimizerType.LBFGS
    regularization_context: RegularizationContext = dataclasses.field(
        default_factory=RegularizationContext)

    def __post_init__(self):
        if (self.regularization_weight > 0 and
                self.regularization_context.reg_type ==
                RegularizationType.NONE):
            # Reference semantics: under NONE the weight is simply ignored
            # (RegularizationContext.getL1/L2RegularizationWeight return 0),
            # so config strings like "...,10,...,NONE" and drivers with a
            # default λ grid but --regularization-type NONE must not fail.
            object.__setattr__(self, "regularization_weight", 0.0)
        if not (0.0 < self.down_sampling_rate <= 1.0):
            raise ValueError(
                f"downSamplingRate must be in (0, 1], got "
                f"{self.down_sampling_rate}")
        if self.regularization_weight < 0:
            raise ValueError(
                f"regularization weight must be >= 0, got "
                f"{self.regularization_weight}")
        if self.max_iterations <= 0:
            raise ValueError(
                f"maxIterations must be positive, got {self.max_iterations}")
        if self.tolerance <= 0:
            raise ValueError(f"tolerance must be positive, got {self.tolerance}")

    @classmethod
    def parse(cls, s: str) -> "GLMOptimizationConfiguration":
        parts = [p.strip() for p in s.split(",") if p.strip()]
        if len(parts) not in (6, 7):
            raise ValueError(
                f"expected 'maxIter,tol,regWeight,downSamplingRate,"
                f"optimizerType,regType[,alpha]', got {s!r}")
        alpha = float(parts[6]) if len(parts) == 7 else None
        reg_type = RegularizationType(parts[5].upper())
        if reg_type != RegularizationType.ELASTIC_NET:
            alpha = None
        return cls(
            max_iterations=int(parts[0]),
            tolerance=float(parts[1]),
            regularization_weight=float(parts[2]),
            down_sampling_rate=float(parts[3]),
            optimizer_type=OptimizerType(parts[4].upper()),
            regularization_context=RegularizationContext(reg_type, alpha),
        )

    def to_string(self) -> str:
        base = (f"{self.max_iterations},{self.tolerance},"
                f"{self.regularization_weight},{self.down_sampling_rate},"
                f"{self.optimizer_type.value},"
                f"{self.regularization_context.reg_type.value}")
        if self.regularization_context.reg_type == RegularizationType.ELASTIC_NET:
            base += f",{self.regularization_context.elastic_net_alpha}"
        return base

    def to_json(self) -> Dict:
        return {
            "maxIterations": self.max_iterations,
            "tolerance": self.tolerance,
            "regularizationWeight": self.regularization_weight,
            "downSamplingRate": self.down_sampling_rate,
            "optimizerType": self.optimizer_type.value,
            **self.regularization_context.to_json(),
        }

    @classmethod
    def from_json(cls, d: Dict) -> "GLMOptimizationConfiguration":
        return cls(
            max_iterations=d["maxIterations"],
            tolerance=d["tolerance"],
            regularization_weight=d["regularizationWeight"],
            down_sampling_rate=d.get("downSamplingRate", 1.0),
            optimizer_type=OptimizerType(d["optimizerType"]),
            regularization_context=RegularizationContext(
                RegularizationType(d["regularizationType"]),
                d.get("elasticNetParam")),
        )


@dataclasses.dataclass(frozen=True)
class MFOptimizationConfiguration:
    """Matrix-factorization knobs for factored random effects.

    String form "maxIter,numFactors"
    (reference: ml/optimization/game/MFOptimizationConfiguration.scala:23-50):
    ``max_iterations`` alternations between the per-entity latent solves and
    the projection-matrix refit per coordinate update; ``num_factors`` is the
    latent dimension of the shared projection matrix.
    """

    max_iterations: int = 1
    num_factors: int = 5

    def __post_init__(self):
        if self.max_iterations <= 0:
            raise ValueError(
                f"maxIterations must be positive, got {self.max_iterations}")
        if self.num_factors <= 0:
            raise ValueError(
                f"numFactors must be positive, got {self.num_factors}")

    @classmethod
    def parse(cls, s: str) -> "MFOptimizationConfiguration":
        parts = [p.strip() for p in s.split(",") if p.strip()]
        if len(parts) != 2:
            raise ValueError(
                f"expected 'maxNumberIterations,numFactors', got {s!r}")
        return cls(max_iterations=int(parts[0]), num_factors=int(parts[1]))

    def to_string(self) -> str:
        return f"{self.max_iterations},{self.num_factors}"

    def to_json(self) -> Dict:
        return {"maxIterations": self.max_iterations,
                "numFactors": self.num_factors}

    @classmethod
    def from_json(cls, d: Dict) -> "MFOptimizationConfiguration":
        return cls(d["maxIterations"], d["numFactors"])


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectOptimizationConfiguration:
    """The config triple of a factored random effect (reference:
    FactoredRandomEffectOptimizationProblem — a random-effect problem, a
    latent-factor problem, and the MF knobs). String form joins the three
    with ';': 'reCfg;latentCfg;maxIter,numFactors'."""

    random_effect: GLMOptimizationConfiguration
    latent_factor: GLMOptimizationConfiguration
    mf: MFOptimizationConfiguration

    @classmethod
    def parse(cls, s: str) -> "FactoredRandomEffectOptimizationConfiguration":
        parts = s.split(";")
        if len(parts) != 3:
            raise ValueError(
                "expected 'reOptConfig;latentOptConfig;mfConfig' "
                f"(';'-separated), got {s!r}")
        return cls(GLMOptimizationConfiguration.parse(parts[0]),
                   GLMOptimizationConfiguration.parse(parts[1]),
                   MFOptimizationConfiguration.parse(parts[2]))

    def to_string(self) -> str:
        return (f"{self.random_effect.to_string()};"
                f"{self.latent_factor.to_string()};{self.mf.to_string()}")

    def to_json(self) -> Dict:
        return {"randomEffect": self.random_effect.to_json(),
                "latentFactor": self.latent_factor.to_json(),
                "mf": self.mf.to_json()}

    @classmethod
    def from_json(cls, d: Dict
                  ) -> "FactoredRandomEffectOptimizationConfiguration":
        return cls(GLMOptimizationConfiguration.from_json(d["randomEffect"]),
                   GLMOptimizationConfiguration.from_json(d["latentFactor"]),
                   MFOptimizationConfiguration.from_json(d["mf"]))


def parse_constraint_string(s: str, index_map) -> ConstraintMap:
    """Parse the box-constraint JSON of the reference
    (ml/io/GLMSuite.scala:207-260): a list of
    {"name": ..., "term": ..., "lowerBound": ..., "upperBound": ...}
    with "*" wildcards for name/term. Returns {feature_index: (lb, ub)}.

    ``index_map`` maps feature key -> index and exposes items() for wildcard
    expansion (see photon_ml_tpu/data/index_map.py).
    """
    entries = json.loads(s)
    out: ConstraintMap = {}
    wildcard_all: Optional[Tuple[float, float]] = None
    from photon_ml_tpu.data.index_map import feature_key

    for e in entries:
        name = e["name"]
        term = e.get("term", "")
        lb = float(e.get("lowerBound", float("-inf")))
        ub = float(e.get("upperBound", float("inf")))
        if lb > ub:
            raise ValueError(f"lowerBound > upperBound in constraint {e}")
        if name == "*" and term == "*":
            wildcard_all = (lb, ub)
        elif name == "*" or term == "*":
            for key, idx in index_map.items():
                kname, kterm = key
                if (name == "*" or kname == name) and \
                   (term == "*" or kterm == term):
                    out[idx] = (lb, ub)
        else:
            idx = index_map.get_index(feature_key(name, term))
            if idx is not None and idx >= 0:
                out[idx] = (lb, ub)
    if wildcard_all is not None:
        for key, idx in index_map.items():
            out.setdefault(idx, wildcard_all)
    return out


def constraint_arrays(constraint_map, num_features: int, intercept_id: int = -1):
    """Expand a sparse constraint map into dense (lower, upper) arrays.

    Unconstrained features get (-inf, +inf); the intercept is never
    constrained (reference: GLMSuite constraint handling skips the intercept).
    Returns (None, None) when the map is empty/None.
    """
    import numpy as np

    if not constraint_map:
        return None, None
    lo = np.full(num_features, -np.inf)
    hi = np.full(num_features, np.inf)
    for idx, (lb, ub) in constraint_map.items():
        if idx == intercept_id:
            continue
        lo[idx] = lb
        hi[idx] = ub
    return lo, hi
