"""Solver dispatch: GLMOptimizationConfiguration -> the right minimizer.

Mirrors the reference's optimizer selection
(ml/optimization/OptimizerFactory.scala + GeneralizedLinearOptimizationProblem
construction): TRON for twice-differentiable objectives, OWL-QN whenever the
L1 weight is positive, L-BFGS otherwise. The L2 part always rides inside the
objective; L1 is handled by OWL-QN's orthant machinery (same split as the
reference, where L1 lives in Breeze's OWLQN and L2 in the objective mixins).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops.glm_objective import GLMBatch, GLMObjective
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    OptimizerType,
)
from photon_ml_tpu.optimization.convergence import OptimizerResult
from photon_ml_tpu.optimization.glm_lbfgs import minimize_lbfgs_glm
from photon_ml_tpu.optimization.lbfgs import minimize_lbfgs
from photon_ml_tpu.optimization.owlqn import minimize_owlqn
from photon_ml_tpu.optimization.tron import minimize_tron

Array = jax.Array


def solve_glm(
    objective: GLMObjective,
    batch: GLMBatch,
    config: GLMOptimizationConfiguration,
    coef0: Array,
    lower_bounds: Optional[Array] = None,
    upper_bounds: Optional[Array] = None,
    track_coefficients: bool = False,
) -> OptimizerResult:
    """One GLM solve. Pure: jit/vmap-safe given consistent static config."""
    lam = config.regularization_weight
    rc = config.regularization_context
    l1 = rc.l1_weight(lam)
    l2 = rc.l2_weight(lam)

    # jit-cache discipline: ``objective.value`` is the static fun (stable for
    # a persistent objective instance); the batch AND the l2 weight are
    # traced args, so λ-grid sweeps and repeated coordinate updates reuse one
    # compiled solver.
    fun = objective.value
    l2_arr = jnp.asarray(l2, coef0.dtype)

    if config.optimizer_type == OptimizerType.TRON:
        if not objective.loss.twice_differentiable:
            raise ValueError(
                f"TRON requires a twice-differentiable loss, got "
                f"{objective.loss.name}")
        if l1 > 0:
            raise ValueError("TRON does not support L1 regularization")
        # Note: an exact-Newton fast path for small d (optimization/newton.py)
        # was measured and NOT auto-routed here: batched tiny linalg.solve
        # lowers to slow unrolled LU on TPU (~400ms vs ~0.2ms for the vmapped
        # L-BFGS on the 5k-entity benchmark block), so CG/quasi-Newton wins
        # on device. minimize_newton remains available for explicit use
        # (fast and robust on CPU f64).
        return minimize_tron(
            fun, coef0, args=(batch, l2_arr), max_iter=config.max_iterations,
            tol=config.tolerance, lower_bounds=lower_bounds,
            upper_bounds=upper_bounds, track_coefficients=track_coefficients,
            # Margin-cached GLM Hessian-vector products: one
            # matvec+rmatvec per CG step instead of jvp-of-grad's ~2x.
            make_hvp=objective.make_tron_hvp)
    if l1 > 0:
        if lower_bounds is not None or upper_bounds is not None:
            raise ValueError(
                "box constraints with L1 regularization are not supported")
        return minimize_owlqn(
            fun, coef0, args=(batch, l2_arr), l1_weight=l1,
            max_iter=config.max_iterations, tol=config.tolerance,
            track_coefficients=track_coefficients)
    if lower_bounds is None and upper_bounds is None:
        # Margin-cached fast path: line-search trials cost O(n) instead of a
        # matvec+rmatvec pair (see optimization/glm_lbfgs.py). Box
        # constraints break the affine-margin identity, so bounded solves
        # use the generic projected L-BFGS below.
        return minimize_lbfgs_glm(
            objective, batch, coef0, l2_arr,
            max_iter=config.max_iterations, tol=config.tolerance,
            track_coefficients=track_coefficients)
    return minimize_lbfgs(
        fun, coef0, args=(batch, l2_arr), max_iter=config.max_iterations,
        tol=config.tolerance, lower_bounds=lower_bounds,
        upper_bounds=upper_bounds, track_coefficients=track_coefficients)


