"""TRON: trust-region Newton with truncated conjugate gradient.

TPU-native counterpart of the reference's LIBLINEAR port
(ml/optimization/TRON.scala:153-340): an outer trust-region loop whose inner
CG performs one Hessian-vector product per iteration. In the reference each
Hv product is a distributed treeAggregate; here it is a jvp-of-grad through
the fused GLM objective — under data sharding XLA turns the contraction into
an ICI all-reduce, and under ``vmap`` the whole solver batches over entities.

Trust-region update rules follow LIBLINEAR (sigma1/sigma2/sigma3,
eta0/eta1/eta2); the improvement-failure budget mirrors
TRON.scala's maxNumImprovementFailures=5 (ml/optimization/TRON.scala:258-264).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu import telemetry
from photon_ml_tpu.optimization.convergence import (
    ConvergenceReason,
    OptimizerResult,
    check_solver_finite,
)
from photon_ml_tpu.optimization.lbfgs import _project

Array = jax.Array

# Shared per-outer-iteration telemetry with the streaming L-BFGS
# (optimization/glm_lbfgs.py) — one histogram, one schema.
_H_ITERATION = telemetry.histogram("training.iteration_seconds")
_M_ITERATIONS = telemetry.counter("training.solver_iterations")
# Batched λ-grid: grid rows still iterating (same gauge object as the
# streaming L-BFGS — the registry is get-or-create).
_G_GRID_ACTIVE = telemetry.gauge("training.grid.active_points")

_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0
_CG_XI = 0.1  # inner CG stops at ||r|| <= xi ||g||


def _truncated_cg(hvp, g, delta, max_cg, dtype):
    """Steihaug-Toint truncated CG: approximately solve H s = -g, ||s||<=delta.

    Returns (s, r) with r the final residual -g - H s (needed for the
    predicted-reduction formula). One hvp per iteration — the hot loop
    (reference: TRON.scala:280-340).
    """
    d0 = -g
    s0 = jnp.zeros_like(g)
    r0 = -g
    rtr0 = jnp.vdot(r0, r0)
    stop_norm = _CG_XI * jnp.linalg.norm(g)

    class CGState(NamedTuple):
        s: Array
        r: Array
        d: Array
        rtr: Array
        k: Array
        done: Array

    init = CGState(s0, r0, d0, rtr0, jnp.zeros((), jnp.int32),
                   jnp.linalg.norm(r0) <= stop_norm)

    def cond(st: CGState):
        return jnp.logical_and(~st.done, st.k < max_cg)

    def body(st: CGState):
        hd = hvp(st.d)
        dhd = jnp.vdot(st.d, hd)
        # Guard: non-positive curvature direction -> march to the boundary.
        alpha = st.rtr / jnp.where(dhd > 0, dhd, jnp.asarray(1.0, dtype))
        s_try = st.s + alpha * st.d

        crossed = jnp.logical_or(jnp.linalg.norm(s_try) > delta, dhd <= 0)

        # Boundary intersection: tau >= 0 with ||s + tau d|| = delta.
        std = jnp.vdot(st.s, st.d)
        dd = jnp.vdot(st.d, st.d)
        ss = jnp.vdot(st.s, st.s)
        gap = jnp.maximum(delta * delta - ss, 0.0)
        rad = jnp.sqrt(jnp.maximum(std * std + dd * gap, 0.0))
        safe_dd = jnp.maximum(dd, 1e-30)
        tau = jnp.where(
            std >= 0, gap / jnp.maximum(std + rad, 1e-30), (rad - std) / safe_dd
        )

        step = jnp.where(crossed, tau, alpha)
        s_new = st.s + step * st.d
        r_new = st.r - step * hd

        rtr_new = jnp.vdot(r_new, r_new)
        beta = rtr_new / jnp.maximum(st.rtr, 1e-30)
        d_new = r_new + beta * st.d

        done_new = jnp.logical_or(
            crossed, jnp.sqrt(rtr_new) <= stop_norm
        )
        new = CGState(s_new, r_new, d_new, rtr_new, st.k + 1, done_new)
        return jax.tree.map(lambda a, b: jnp.where(st.done, a, b), st, new)

    final = lax.while_loop(cond, body, init)
    return final.s, final.r


class _TronState(NamedTuple):
    x: Array
    f: Array
    g: Array
    delta: Array
    it: Array  # accepted iterations
    fails: Array  # consecutive improvement failures
    reason: Array
    value_hist: Array
    gnorm_hist: Array
    first: Array  # bool: before first step (delta clamp rule)
    coef_hist: Optional[Array]  # [max_iter+1, d] when tracking, else None


@functools.partial(
    jax.jit,
    static_argnames=("fun", "max_iter", "tol", "max_cg",
                     "max_improvement_failures", "has_bounds",
                     "track_coefficients", "make_hvp"),
)
def _minimize_tron_impl(
    fun, x0, args, lower, upper, *, max_iter, tol, max_cg,
    max_improvement_failures, has_bounds, track_coefficients=False,
    make_hvp=None,
) -> OptimizerResult:
    vg = jax.value_and_grad(fun)
    dtype = x0.dtype
    lo = lower if has_bounds else None
    hi = upper if has_bounds else None

    def proj_grad_norm(x, g):
        # Norm of the projected gradient: ||x - P(x - g)||. Equals ||g|| in
        # the unconstrained case; the right stationarity measure with bounds.
        if not has_bounds:
            return jnp.linalg.norm(g)
        return jnp.linalg.norm(x - _project(x - g, lo, hi))

    x0 = _project(x0, lo, hi)
    f0, g0 = vg(x0, *args)
    gnorm0 = proj_grad_norm(x0, g0)
    f0_scale = jnp.maximum(jnp.abs(f0), jnp.asarray(1e-30, dtype))

    value_hist = jnp.full((max_iter + 1,), jnp.nan, dtype).at[0].set(f0)
    gnorm_hist = jnp.full((max_iter + 1,), jnp.nan, dtype).at[0].set(gnorm0)
    coef_hist = (jnp.full((max_iter + 1, x0.shape[-1]), jnp.nan,
                          dtype).at[0].set(x0)
                 if track_coefficients else None)

    init = _TronState(
        x=x0, f=f0, g=g0, delta=gnorm0,
        it=jnp.zeros((), jnp.int32), fails=jnp.zeros((), jnp.int32),
        reason=jnp.where(
            gnorm0 <= 0.0, int(ConvergenceReason.GRADIENT_CONVERGED),
            int(ConvergenceReason.NOT_CONVERGED)).astype(jnp.int32),
        value_hist=value_hist, gnorm_hist=gnorm_hist,
        first=jnp.ones((), bool), coef_hist=coef_hist,
    )

    def cond(st: _TronState):
        return st.reason == int(ConvergenceReason.NOT_CONVERGED)

    def body(st: _TronState):
        if make_hvp is not None:
            # Caller-specialized product (GLM: margin-cached, exactly one
            # matvec+rmatvec per CG step; curvature weights computed once
            # per outer iteration and hoisted out of the CG loop).
            hvp = make_hvp(st.x, *args)
        else:
            def hvp(v):
                grad_fn = lambda xx: vg(xx, *args)[1]
                return jax.jvp(grad_fn, (st.x,), (v,))[1]

        if has_bounds:
            # Active-set reduction: coordinates pinned at a bound with the
            # gradient pushing outward are frozen; CG runs in the free
            # subspace so the Newton model isn't polluted by directions the
            # projection will clip anyway.
            eps = jnp.asarray(1e-12, dtype)
            active = jnp.logical_or(
                jnp.logical_and(st.x <= lo + eps, st.g > 0),
                jnp.logical_and(st.x >= hi - eps, st.g < 0),
            )
            free = (~active).astype(dtype)
            g_cg = st.g * free
            hvp_cg = lambda v: free * hvp(free * v)
        else:
            g_cg, hvp_cg = st.g, hvp

        s, r = _truncated_cg(hvp_cg, g_cg, st.delta, max_cg, dtype)

        x_try = _project(st.x + s, lo, hi)
        s_real = x_try - st.x
        f_new, g_new = vg(x_try, *args)

        gs = jnp.vdot(st.g, s_real)
        if has_bounds:
            # Projection changed the step; evaluate the quadratic model on the
            # realized step for a consistent predicted reduction.
            prered = -(gs + 0.5 * jnp.vdot(s_real, hvp(s_real)))
        else:
            prered = -0.5 * (gs - jnp.vdot(s_real, r))
        actred = st.f - f_new
        snorm = jnp.linalg.norm(s_real)

        delta = jnp.where(st.first, jnp.minimum(st.delta, snorm), st.delta)

        # LIBLINEAR step-size interpolation for the radius update.
        denom = f_new - st.f - gs
        alpha = jnp.where(
            denom <= 0, _SIGMA3,
            jnp.maximum(_SIGMA1, -0.5 * (gs / jnp.maximum(denom, 1e-30))),
        )
        alpha_s = alpha * snorm
        delta = jnp.where(
            actred < _ETA0 * prered,
            jnp.minimum(jnp.maximum(alpha, _SIGMA1) * snorm, _SIGMA2 * delta),
            jnp.where(
                actred < _ETA1 * prered,
                jnp.maximum(_SIGMA1 * delta,
                            jnp.minimum(alpha_s, _SIGMA2 * delta)),
                jnp.where(
                    actred < _ETA2 * prered,
                    jnp.maximum(_SIGMA1 * delta,
                                jnp.minimum(alpha_s, _SIGMA3 * delta)),
                    jnp.maximum(delta, jnp.minimum(alpha_s, _SIGMA3 * delta)),
                ),
            ),
        )

        accept = jnp.logical_and(actred > _ETA0 * prered, jnp.isfinite(f_new))
        it_new = st.it + jnp.where(accept, 1, 0).astype(jnp.int32)
        fails_new = jnp.where(accept, 0, st.fails + 1).astype(jnp.int32)

        x_acc = jnp.where(accept, x_try, st.x)
        f_acc = jnp.where(accept, f_new, st.f)
        g_acc = jnp.where(accept, g_new, st.g)
        gnorm_acc = proj_grad_norm(x_acc, g_acc)
        f_delta = jnp.abs(st.f - f_acc)

        reason = jnp.where(
            fails_new > max_improvement_failures,
            int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING),
            jnp.where(
                jnp.logical_and(accept, gnorm_acc <= tol * gnorm0),
                int(ConvergenceReason.GRADIENT_CONVERGED),
                jnp.where(
                    jnp.logical_and(accept, f_delta <= tol * f0_scale),
                    int(ConvergenceReason.FUNCTION_VALUES_CONVERGED),
                    jnp.where(
                        it_new >= max_iter,
                        int(ConvergenceReason.MAX_ITERATIONS),
                        int(ConvergenceReason.NOT_CONVERGED)))),
        ).astype(jnp.int32)

        new = _TronState(
            x=x_acc, f=f_acc, g=g_acc, delta=delta, it=it_new,
            fails=fails_new, reason=reason,
            value_hist=jnp.where(
                accept, st.value_hist.at[it_new].set(f_acc), st.value_hist),
            gnorm_hist=jnp.where(
                accept, st.gnorm_hist.at[it_new].set(gnorm_acc),
                st.gnorm_hist),
            first=jnp.zeros((), bool),
            coef_hist=(None if st.coef_hist is None
                       else jnp.where(
                           accept, st.coef_hist.at[it_new].set(x_acc),
                           st.coef_hist)),
        )
        done = ~cond(st)
        return jax.tree.map(lambda a, b: jnp.where(done, a, b), st, new)

    final = lax.while_loop(cond, body, init)
    return OptimizerResult(
        x=final.x, value=final.f, grad_norm=jnp.linalg.norm(final.g),
        iterations=final.it, reason=final.reason,
        value_history=final.value_hist, grad_norm_history=final.gnorm_hist,
        coef_history=final.coef_hist,
    )


@jax.jit
def _stream_cg_step(s, r, d_vec, rtr, hd, delta, stop_norm):
    """One Steihaug-Toint CG step given the (streamed) Hessian product —
    the body of `_truncated_cg` verbatim, as a single [d]-space dispatch;
    the streaming driver makes the loop decisions on host."""
    dtype = s.dtype
    dhd = jnp.vdot(d_vec, hd)
    alpha = rtr / jnp.where(dhd > 0, dhd, jnp.asarray(1.0, dtype))
    s_try = s + alpha * d_vec
    crossed = jnp.logical_or(jnp.linalg.norm(s_try) > delta, dhd <= 0)

    std = jnp.vdot(s, d_vec)
    dd = jnp.vdot(d_vec, d_vec)
    ss = jnp.vdot(s, s)
    gap = jnp.maximum(delta * delta - ss, 0.0)
    rad = jnp.sqrt(jnp.maximum(std * std + dd * gap, 0.0))
    safe_dd = jnp.maximum(dd, 1e-30)
    tau = jnp.where(std >= 0, gap / jnp.maximum(std + rad, 1e-30),
                    (rad - std) / safe_dd)

    step = jnp.where(crossed, tau, alpha)
    s_new = s + step * d_vec
    r_new = r - step * hd
    rtr_new = jnp.vdot(r_new, r_new)
    beta = rtr_new / jnp.maximum(rtr, 1e-30)
    d_new = r_new + beta * d_vec
    done = jnp.logical_or(crossed, jnp.sqrt(rtr_new) <= stop_norm)
    return s_new, r_new, d_new, rtr_new, done


@jax.jit
def _stream_tr_update(f, f_new, g, s, r, delta, first):
    """Trust-region bookkeeping for one outer step — the LIBLINEAR radius
    interpolation of `_minimize_tron_impl` (unbounded branch), verbatim."""
    gs = jnp.vdot(g, s)
    prered = -0.5 * (gs - jnp.vdot(s, r))
    actred = f - f_new
    snorm = jnp.linalg.norm(s)
    delta = jnp.where(first, jnp.minimum(delta, snorm), delta)

    denom = f_new - f - gs
    alpha = jnp.where(
        denom <= 0, _SIGMA3,
        jnp.maximum(_SIGMA1, -0.5 * (gs / jnp.maximum(denom, 1e-30))))
    alpha_s = alpha * snorm
    delta = jnp.where(
        actred < _ETA0 * prered,
        jnp.minimum(jnp.maximum(alpha, _SIGMA1) * snorm, _SIGMA2 * delta),
        jnp.where(
            actred < _ETA1 * prered,
            jnp.maximum(_SIGMA1 * delta,
                        jnp.minimum(alpha_s, _SIGMA2 * delta)),
            jnp.where(
                actred < _ETA2 * prered,
                jnp.maximum(_SIGMA1 * delta,
                            jnp.minimum(alpha_s, _SIGMA3 * delta)),
                jnp.maximum(delta, jnp.minimum(alpha_s, _SIGMA3 * delta)),
            ),
        ),
    )
    accept = jnp.logical_and(actred > _ETA0 * prered, jnp.isfinite(f_new))
    return delta, accept


def minimize_tron_streaming(
    sharded_objective,
    x0: Array,
    l2_weight,
    *,
    max_iter: int = 15,
    tol: float = 1e-5,
    max_cg: int = 20,
    max_improvement_failures: int = 5,
    track_coefficients: bool = False,
    trace_ctx=None,
    convergence_ring=None,
    margins_out=None,
) -> OptimizerResult:
    """Out-of-core TRON: the outer trust-region loop runs on the host;
    each value/gradient evaluation and each inner-CG Hessian-vector
    product is a streaming pass over the shard cache
    (ops/sharded_objective.py — margins + curvature computed once per
    outer iteration, exactly like `GLMObjective.make_tron_hvp`; each CG
    product costs one matvec + one rmatvec per shard). Unsupported here:
    box constraints (use the resident path). Accumulation order is the
    fixed shard order — deterministic, residency-independent, and (via
    the objective's mesh) device-count-independent: per-shard curvature
    stays resident on each shard's mesh device, each CG step broadcasts
    the direction and folds the Hvp partials in fixed shard order, while
    the [d]-space trust-region algebra here runs on the fold device.
    On a 2-D (data x model) mesh the CG direction broadcasts as
    per-column-block SLICES and Hvp partials re-assemble through the
    objective's deterministic model-axis concat; the trust-region state
    here (coefficients, gradient, CG iterates) stays FULL-WIDTH on the
    host/default device — the documented state decision shared with
    `minimize_lbfgs_glm_streaming` — so mesh shapes {1x1, 2x1, 1x2,
    2x2} solve bit-identically with no TRON-side mesh code.

    Spill-tier interaction: margins and curvature (the per-outer-
    iteration row-space state) are never evicted, so the compressed
    (``spill_dtype="bf16"``) and out-of-core (``spill_source=
    "redecode"``) tiers only affect the FEATURE passes — each CG Hvp
    walks `cache.blocks()` and pays the miss path (re-upload + decode,
    or Avro re-decode) per evicted block, so an outer iteration with k
    CG steps costs (k + 2) restore epochs; the trust-region
    accept/reject arithmetic itself touches no features at all.

    Divergence watchdog + ``trace_ctx``: same contract as
    `minimize_lbfgs_glm_streaming` — loss/grad-norm checked for NaN/Inf
    each outer iteration on already-host scalars (typed
    ``SolverDivergedError``, trace-tagged), one ``solver_step`` trace
    event per accepted or rejected outer step. An unaccepted trial with
    non-finite value is NOT a divergence — the trust region shrinks and
    retries, exactly like the fused impl — so only the accepted state
    is checked.

    ``convergence_ring`` / ``margins_out`` — same distribution-
    observability hooks as ``minimize_lbfgs_glm_streaming``: one ring
    entry per ACCEPTED outer iteration (step = ||s||, the trust-region
    step actually taken; all scalars already host-side), and the final
    per-shard margin list for zero-pass training-score sketching."""
    import numpy as np

    sobj = sharded_objective
    x = jnp.asarray(x0)
    dtype = x.dtype
    np_dtype = np.dtype(dtype)
    l2 = jnp.asarray(l2_weight, dtype)

    def host(v):
        return np.asarray(v)[()]

    tol_s = np_dtype.type(tol)
    z_list, f, g = sobj.margins_value_grad(x, l2)
    f_h = host(f)
    gnorm = host(jnp.linalg.norm(g))
    check_solver_finite("streaming-tron", 0, f_h, gnorm, trace_ctx)
    if convergence_ring is not None:
        convergence_ring.append(0, f_h, gnorm, None)
    gnorm0 = gnorm
    f0_scale = np.maximum(np.abs(f_h), np_dtype.type(1e-30))
    delta = jnp.asarray(gnorm0, dtype)

    value_hist = np.full(max_iter + 1, np.nan, np_dtype)
    gnorm_hist = np.full(max_iter + 1, np.nan, np_dtype)
    value_hist[0], gnorm_hist[0] = f_h, gnorm
    coef_hist = (np.full((max_iter + 1, x.shape[-1]), np.nan, np_dtype)
                 if track_coefficients else None)
    if coef_hist is not None:
        coef_hist[0] = np.asarray(x)

    reason = (ConvergenceReason.GRADIENT_CONVERGED if gnorm0 <= 0.0
              else ConvergenceReason.NOT_CONVERGED)
    it = 0
    fails = 0
    first = True
    while reason == ConvergenceReason.NOT_CONVERGED:
        # ``solver_step`` = one trust-region outer iteration (curvature +
        # inner CG + trial evaluation) — same per-iteration telemetry
        # schema as the streaming L-BFGS.
        with telemetry.timed_span("solver_step", histogram=_H_ITERATION,
                                  counter=_M_ITERATIONS):
            if trace_ctx is not None:
                trace_ctx.event("solver_step")
            d2_list = sobj.curvature_list(z_list)

            # -- truncated CG (streamed Hv per step) ----------------------
            s = jnp.zeros_like(g)
            r = -g
            d_vec = -g
            rtr = jnp.vdot(r, r)
            stop_norm = _CG_XI * jnp.linalg.norm(g)
            cg_done = bool(host(jnp.linalg.norm(r) <= stop_norm))
            k = 0
            while not cg_done and k < max_cg:
                hd = sobj.hessian_vector(d_vec, d2_list, l2)
                s, r, d_vec, rtr, done_dev = _stream_cg_step(
                    s, r, d_vec, rtr, hd, delta, stop_norm)
                cg_done = bool(host(done_dev))
                k += 1

            x_try = x + s
            z_try, f_new, g_new = sobj.margins_value_grad(x_try, l2)
            delta, accept_dev = _stream_tr_update(
                f, f_new, g, s, r, delta, jnp.asarray(first))
            first = False
            accept = bool(host(accept_dev))

            if accept:
                it += 1
                fails = 0
                x, z_list, g = x_try, z_try, g_new
                f_new_h = host(f_new)
                f_delta = np.abs(f_h - f_new_h)
                f, f_h = f_new, f_new_h
                gnorm = host(jnp.linalg.norm(g))
                # Watchdog on the ACCEPTED state (host scalars already
                # in hand — no added sync); a rejected non-finite trial
                # is normal trust-region behavior, not divergence.
                check_solver_finite("streaming-tron", it, f_h, gnorm,
                                    trace_ctx)
                value_hist[it], gnorm_hist[it] = f_h, gnorm
                if coef_hist is not None:
                    coef_hist[it] = np.asarray(x)
                if convergence_ring is not None:
                    convergence_ring.append(
                        it, f_h, gnorm, host(jnp.linalg.norm(s)))
                if gnorm <= tol_s * gnorm0:
                    reason = ConvergenceReason.GRADIENT_CONVERGED
                elif f_delta <= tol_s * f0_scale:
                    reason = ConvergenceReason.FUNCTION_VALUES_CONVERGED
                elif it >= max_iter:
                    reason = ConvergenceReason.MAX_ITERATIONS
            else:
                fails += 1
                if fails > max_improvement_failures:
                    reason = ConvergenceReason.OBJECTIVE_NOT_IMPROVING

    if margins_out is not None:
        margins_out[:] = z_list
    return OptimizerResult(
        x=x, value=f, grad_norm=jnp.asarray(gnorm, dtype),
        iterations=jnp.asarray(it, jnp.int32),
        reason=jnp.asarray(int(reason), jnp.int32),
        value_history=jnp.asarray(value_hist),
        grad_norm_history=jnp.asarray(gnorm_hist),
        coef_history=(None if coef_hist is None
                      else jnp.asarray(coef_hist)),
    )


@jax.jit
def _grid_cg_step(s, r, d_vec, rtr, hd, delta, stop_norm):
    """Per-row Steihaug-Toint CG step: `_stream_cg_step` vmapped over
    the grid axis (every array gains a leading [G])."""
    return jax.vmap(_stream_cg_step)(s, r, d_vec, rtr, hd, delta,
                                     stop_norm)


@jax.jit
def _grid_tr_update(f, f_new, g, s, r, delta, first):
    """Per-row LIBLINEAR trust-region update: `_stream_tr_update`
    vmapped over the grid axis (``first`` broadcast — all rows share
    the before-first-step clamp)."""
    return jax.vmap(_stream_tr_update,
                    in_axes=(0, 0, 0, 0, 0, 0, None))(
        f, f_new, g, s, r, delta, first)


def minimize_tron_grid_streaming(
    sharded_objective,
    x0s: Array,
    l2_weights,
    *,
    max_iter: int = 15,
    tol: float = 1e-5,
    max_cg: int = 20,
    max_improvement_failures: int = 5,
    track_coefficients: bool = False,
    trace_ctxs=None,
    convergence_rings=None,
    margins_out=None,
):
    """Batched λ-grid streaming TRON: one curvature pass, one shared CG
    (each Hvp feature pass serves EVERY grid row's iterate), and one
    trial evaluation pass advance all G trust-region solves per outer
    iteration. Coefficients ``[G, d]``, margins/curvature ``[G, rows]``
    per shard, λ row ``[G]``. Returns a list of G
    :class:`OptimizerResult`, row-aligned with the inputs.

    **Masked convergence.** Per-row CG done-masks freeze a row's
    (s, r, d, rtr) once it hits its own Steihaug-Toint stop; the inner
    loop runs until every ACTIVE row is done or ``max_cg`` — so a
    sweep's Hvp pass count is the slowest row's CG depth, not the sum.
    Outer accept/reject, improvement-failure budgets and convergence
    reasons are per row (host numpy masks); finished rows take step 0
    and keep their state bit-identical through `jnp.where` row selects.

    **Bit discipline / observability / divergence** follow
    :func:`~photon_ml_tpu.optimization.glm_lbfgs.minimize_lbfgs_glm_grid_streaming`:
    G=1 delegates to :func:`minimize_tron_streaming` (bitwise gate);
    ``trace_ctxs``/``convergence_rings`` are row-aligned; only ACCEPTED
    states are watchdog-checked, and a non-finite accepted row raises
    :class:`SolverDivergedError` with that row's λ and ``grid_row``.
    """
    import numpy as np

    from photon_ml_tpu.optimization.glm_lbfgs import _grid_select_rows

    sobj = sharded_objective
    x = jnp.asarray(x0s)
    if x.ndim != 2:
        raise ValueError(
            f"x0s must be [G, d] (one coefficient row per grid point), "
            f"got shape {x.shape}")
    G, d = x.shape
    dtype = x.dtype
    np_dtype = np.dtype(dtype)
    l2s = jnp.asarray(l2_weights, dtype)
    if l2s.shape != (G,):
        raise ValueError(
            f"l2_weights must be [G]={G} (one λ per grid row), got "
            f"shape {l2s.shape}")
    ctxs = list(trace_ctxs) if trace_ctxs is not None else [None] * G
    rings = (list(convergence_rings) if convergence_rings is not None
             else [None] * G)
    if len(ctxs) != G or len(rings) != G:
        raise ValueError(
            f"trace_ctxs/convergence_rings must be row-aligned with the "
            f"grid (G={G}), got {len(ctxs)}/{len(rings)}")

    if G == 1:
        # Bitwise gate: the 1-row grid IS the scalar streamed solver.
        holder = [] if margins_out is not None else None
        res = minimize_tron_streaming(
            sobj, x[0], l2s[0], max_iter=max_iter, tol=tol,
            max_cg=max_cg,
            max_improvement_failures=max_improvement_failures,
            track_coefficients=track_coefficients, trace_ctx=ctxs[0],
            convergence_ring=rings[0], margins_out=holder)
        if margins_out is not None:
            margins_out[:] = [z[None] for z in holder]
        return [res]

    tol_s = np_dtype.type(tol)
    l2_h = np.asarray(l2s)
    z_list, f, g = sobj.grid_margins_value_grad(x, l2s)
    f_h = np.asarray(f)
    gnorm = np.asarray(jnp.linalg.norm(g, axis=-1))
    for gi in range(G):
        check_solver_finite("streaming-tron-grid", 0, f_h[gi],
                            gnorm[gi], ctxs[gi], lam=l2_h[gi],
                            grid_row=gi)
        if rings[gi] is not None:
            rings[gi].append(0, f_h[gi], gnorm[gi], None)
    gnorm0 = gnorm.copy()
    f0_scale = np.maximum(np.abs(f_h), np_dtype.type(1e-30))
    delta = jnp.asarray(gnorm0)

    value_hist = np.full((G, max_iter + 1), np.nan, np_dtype)
    gnorm_hist = np.full((G, max_iter + 1), np.nan, np_dtype)
    value_hist[:, 0], gnorm_hist[:, 0] = f_h, gnorm
    coef_hist = (np.full((G, max_iter + 1, d), np.nan, np_dtype)
                 if track_coefficients else None)
    if coef_hist is not None:
        coef_hist[:, 0] = np.asarray(x)

    reasons = [ConvergenceReason.GRADIENT_CONVERGED if gnorm0[gi] <= 0.0
               else ConvergenceReason.NOT_CONVERGED for gi in range(G)]
    active = np.array(
        [r == ConvergenceReason.NOT_CONVERGED for r in reasons])
    its = np.zeros(G, np.int64)
    fails = np.zeros(G, np.int64)
    first = True

    while active.any():
        with telemetry.timed_span("solver_step", histogram=_H_ITERATION,
                                  counter=_M_ITERATIONS):
            _G_GRID_ACTIVE.set(int(active.sum()))
            for gi in np.flatnonzero(active):
                if ctxs[gi] is not None:
                    ctxs[gi].event("solver_step")
            d2_list = sobj.grid_curvature_list(z_list)

            # -- per-row truncated CG: one shared Hvp feature pass per
            # step; rows past their own stop are frozen by row masks,
            # and the loop runs to the slowest ACTIVE row's depth.
            s = jnp.zeros_like(g)
            r = -g
            d_vec = -g
            rtr = jnp.sum(r * r, axis=-1)
            stop_norm = _CG_XI * jnp.linalg.norm(g, axis=-1)
            cg_done = (np.asarray(
                jnp.linalg.norm(r, axis=-1) <= stop_norm) | ~active)
            k = 0
            while not cg_done.all() and k < max_cg:
                hd = sobj.grid_hessian_vector(d_vec, d2_list, l2s)
                s2, r2, d2v, rtr2, done_dev = _grid_cg_step(
                    s, r, d_vec, rtr, hd, delta, stop_norm)
                run = jnp.asarray(~cg_done)
                s = _grid_select_rows(run, s2, s)
                r = _grid_select_rows(run, r2, r)
                d_vec = _grid_select_rows(run, d2v, d_vec)
                rtr = jnp.where(run, rtr2, rtr)
                cg_done |= (~cg_done) & np.asarray(done_dev)
                k += 1

            active_dev = jnp.asarray(active)
            x_try = _grid_select_rows(active_dev, x + s, x)
            z_try, f_new, g_new = sobj.grid_margins_value_grad(
                x_try, l2s)
            delta_new, accept_dev = _grid_tr_update(
                jnp.asarray(f_h), f_new, g, s, r, delta,
                jnp.asarray(first))
            first = False
            delta = jnp.where(active_dev, delta_new, delta)
            accept = np.asarray(accept_dev) & active

            if accept.any():
                acc_dev = jnp.asarray(accept)
                x = _grid_select_rows(acc_dev, x_try, x)
                g = _grid_select_rows(acc_dev, g_new, g)
                z_list = [jnp.where(acc_dev[:, None], zt, z)
                          for zt, z in zip(z_try, z_list)]
                snorm = np.asarray(jnp.linalg.norm(s, axis=-1))
                f_new_h = np.asarray(f_new)
                gnorm_new = np.asarray(jnp.linalg.norm(g, axis=-1))
                f_delta = np.abs(f_h - f_new_h)
                f_h = np.where(accept, f_new_h, f_h)
                gnorm = np.where(accept, gnorm_new, gnorm)
                its[accept] += 1
                fails[accept] = 0
                for gi in np.flatnonzero(accept):
                    # Watchdog on ACCEPTED rows only — a rejected
                    # non-finite trial is normal trust-region behavior.
                    check_solver_finite(
                        "streaming-tron-grid", int(its[gi]), f_h[gi],
                        gnorm[gi], ctxs[gi], lam=l2_h[gi], grid_row=gi)
                    value_hist[gi, its[gi]] = f_h[gi]
                    gnorm_hist[gi, its[gi]] = gnorm[gi]
                    if coef_hist is not None:
                        coef_hist[gi, its[gi]] = np.asarray(x[gi])
                    if rings[gi] is not None:
                        rings[gi].append(int(its[gi]), f_h[gi],
                                         gnorm[gi], float(snorm[gi]))
                    if gnorm[gi] <= tol_s * gnorm0[gi]:
                        reasons[gi] = ConvergenceReason.GRADIENT_CONVERGED
                    elif f_delta[gi] <= tol_s * f0_scale[gi]:
                        reasons[gi] = (
                            ConvergenceReason.FUNCTION_VALUES_CONVERGED)
                    elif its[gi] >= max_iter:
                        reasons[gi] = ConvergenceReason.MAX_ITERATIONS
                    if reasons[gi] != ConvergenceReason.NOT_CONVERGED:
                        active[gi] = False

            rejected = active & ~accept
            fails[rejected] += 1
            for gi in np.flatnonzero(rejected):
                if fails[gi] > max_improvement_failures:
                    reasons[gi] = (
                        ConvergenceReason.OBJECTIVE_NOT_IMPROVING)
                    active[gi] = False
    _G_GRID_ACTIVE.set(0)

    if margins_out is not None:
        margins_out[:] = z_list
    x_np = np.asarray(x)
    return [
        OptimizerResult(
            x=jnp.asarray(x_np[gi]),
            value=jnp.asarray(f_h[gi]),
            grad_norm=jnp.asarray(gnorm[gi]),
            iterations=jnp.asarray(int(its[gi]), jnp.int32),
            reason=jnp.asarray(int(reasons[gi]), jnp.int32),
            value_history=jnp.asarray(value_hist[gi]),
            grad_norm_history=jnp.asarray(gnorm_hist[gi]),
            coef_history=(None if coef_hist is None
                          else jnp.asarray(coef_hist[gi])),
        )
        for gi in range(G)
    ]


def minimize_tron(
    fun: Callable[..., Array],
    x0: Array,
    args: Tuple[Any, ...] = (),
    *,
    max_iter: int = 15,
    tol: float = 1e-5,
    max_cg: int = 20,
    max_improvement_failures: int = 5,
    lower_bounds: Optional[Array] = None,
    upper_bounds: Optional[Array] = None,
    track_coefficients: bool = False,
    make_hvp: Optional[Callable] = None,
) -> OptimizerResult:
    """Minimize twice-differentiable ``fun(x, *args)`` from ``x0``.

    Defaults mirror the reference (maxIter=15, tol=1e-5, <=20 CG iterations,
    <=5 improvement failures; ml/optimization/TRON.scala:258-264).

    ``make_hvp(x, *args) -> (v -> H v)``: optional specialized
    Hessian-vector factory, called once per outer iteration (its
    closed-over precomputations hoist out of the inner CG loop). Defaults
    to jvp-of-grad. Must be a STABLE callable (hashed as a static jit
    argument).
    """
    x0 = jnp.asarray(x0)
    dtype = x0.dtype
    has_bounds = lower_bounds is not None or upper_bounds is not None
    d = x0.shape[-1]
    lo = (jnp.full((d,), -jnp.inf, dtype) if lower_bounds is None
          else jnp.asarray(lower_bounds, dtype))
    hi = (jnp.full((d,), jnp.inf, dtype) if upper_bounds is None
          else jnp.asarray(upper_bounds, dtype))
    return _minimize_tron_impl(
        fun, x0, args, lo, hi, max_iter=max_iter, tol=tol, max_cg=max_cg,
        max_improvement_failures=max_improvement_failures,
        has_bounds=has_bounds, track_coefficients=track_coefficients,
        make_hvp=make_hvp,
    )
