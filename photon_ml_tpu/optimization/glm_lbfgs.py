"""GLM-specialized L-BFGS with margin-cached line search.

The generic L-BFGS (lbfgs.py) evaluates value+gradient at every line-search
trial — each evaluation is a matvec + rmatvec over the full training shard.
For a GLM the margins are AFFINE in the coefficients, so along a search
direction p:

    margins(x + t p) = z + t * zp        (z, zp precomputed n-vectors)
    value(x + t p)   = sum_i w_i l(z_i + t zp_i, y_i)
                       + l2/2 (||x||^2 + 2 t x.p + t^2 ||p||^2)

— every trial is O(n) elementwise work with NO feature contraction, and the
gradient is needed only once per iteration, at the accepted point, via
``GLMObjective.gradient_from_margins`` (one rmatvec). Per-iteration feature
contractions drop from 2 x (1 + #trials) to exactly 2 (one matvec for the
direction margins, one rmatvec for the accepted gradient) — the same
two-contraction economy the reference's fused aggregator achieves for a
single evaluation (ml/function/ValueAndGradientAggregator.scala:34-221),
here extended over the whole line search.

Semantics (convergence reasons, cautious curvature updates, vmap masking)
are identical to lbfgs.py; `solve_glm` routes unconstrained L2 L-BFGS
solves here. Box constraints break the affine-margin trick (projection is
nonlinear in t), so bounded solves stay on the generic path.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu import telemetry
from photon_ml_tpu.ops.glm_objective import GLMBatch, GLMObjective
from photon_ml_tpu.optimization.convergence import (
    ConvergenceReason,
    OptimizerResult,
    check_solver_finite,
)
from photon_ml_tpu.optimization.lbfgs import (
    _LBFGSHistory,
    _empty_history,
    compact_direction,
    update_history,
)

Array = jax.Array

# Per-OUTER-iteration wall time of the host-driven streaming solvers
# (L-BFGS here, TRON in tron.py) — each iteration is a fixed number of
# feature passes over the shard cache, so this histogram is the
# end-to-end cost of one streamed epoch-pair (no-op while telemetry is
# off; the fused lax.while_loop solvers are NOT instrumented — spans
# never open inside jitted code).
_H_ITERATION = telemetry.histogram("training.iteration_seconds")
_M_ITERATIONS = telemetry.counter("training.solver_iterations")
# Batched λ-grid: grid rows still iterating this outer iteration (gauge,
# federation merge policy "sum" — the fleet-wide in-flight point count).
_G_GRID_ACTIVE = telemetry.gauge("training.grid.active_points")


class _State(NamedTuple):
    x: Array
    z: Array  # margins at x (n-vector)
    f: Array
    g: Array
    hist: _LBFGSHistory
    it: Array
    reason: Array
    value_hist: Array
    gnorm_hist: Array
    coef_hist: Optional[Array]


@functools.partial(
    jax.jit,
    static_argnames=("objective", "max_iter", "tol", "history_size", "c1",
                     "max_line_search", "track_coefficients"),
)
def _minimize_lbfgs_glm_impl(
    objective: GLMObjective, x0, batch: GLMBatch, l2, *, max_iter, tol,
    history_size, c1, max_line_search, track_coefficients=False,
) -> OptimizerResult:
    dtype = x0.dtype
    d = x0.shape[-1]
    shrink = 0.5

    z0 = objective.margins(x0, batch)
    f0 = objective.value_from_margins(z0, jnp.vdot(x0, x0), batch, l2)
    g0 = objective.gradient_from_margins(x0, z0, batch, l2)
    gnorm0 = jnp.linalg.norm(g0)
    f0_scale = jnp.maximum(jnp.abs(f0), jnp.asarray(1e-30, dtype))

    value_hist = jnp.full((max_iter + 1,), jnp.nan, dtype).at[0].set(f0)
    gnorm_hist = jnp.full((max_iter + 1,), jnp.nan, dtype).at[0].set(gnorm0)
    coef_hist = (jnp.full((max_iter + 1, d), jnp.nan, dtype).at[0].set(x0)
                 if track_coefficients else None)

    init = _State(
        x=x0, z=z0, f=f0, g=g0,
        hist=_empty_history(d, history_size, dtype),
        it=jnp.zeros((), jnp.int32),
        reason=jnp.where(
            gnorm0 <= 0.0, int(ConvergenceReason.GRADIENT_CONVERGED),
            int(ConvergenceReason.NOT_CONVERGED)).astype(jnp.int32),
        value_hist=value_hist, gnorm_hist=gnorm_hist, coef_hist=coef_hist,
    )

    def cond(st: _State):
        return st.reason == int(ConvergenceReason.NOT_CONVERGED)

    def body(st: _State):
        direction = compact_direction(st.g, st.hist)
        dg = jnp.vdot(direction, st.g)
        use_sd = dg >= 0
        direction = jnp.where(use_sd, -st.g, direction)

        # One matvec for the whole line search.
        zp = objective.margin_direction(direction, batch)
        xx = jnp.vdot(st.x, st.x)
        xp = jnp.vdot(st.x, direction)
        pp = jnp.vdot(direction, direction)
        gp = jnp.vdot(st.g, direction)

        first = st.hist.count == 0
        init_step = jnp.where(
            first, 1.0 / jnp.maximum(jnp.sqrt(pp), 1.0),
            jnp.ones((), dtype))

        # BATCHED Armijo backtracking: margins are affine in the step, so a
        # block of candidates t_k = init * shrink^k is priced in ONE fused
        # [K, n] elementwise reduction (a device-loop iteration costs
        # ~0.14 ms on TPU v5e, so a 5-trial sequential search was ~1 ms of
        # loop overhead). K is capped at 8 to bound the [K, n] intermediate
        # on huge shards; the rare candidates beyond the block (shrink^8
        # ~ 4e-3 of the step) run through the original sequential tail, so
        # the accepted step — the FIRST candidate satisfying Armijo — is
        # bit-identical to fully sequential backtracking.
        n_batched = min(max_line_search + 1, 8)

        def trial_values(ts):
            z_trials = st.z[None, :] + ts[:, None] * zp[None, :]
            data_terms = jnp.sum(
                batch.weights[None, :]
                * objective.loss.loss(z_trials, batch.labels[None, :]),
                axis=-1)
            coef_sq = xx + 2.0 * ts * xp + ts * ts * pp
            return data_terms + 0.5 * l2 * coef_sq

        def armijo_ok(ts, f_trials):
            return jnp.logical_and(f_trials <= st.f + c1 * ts * gp,
                                   jnp.isfinite(f_trials))

        ks = jnp.arange(n_batched, dtype=dtype)
        ts = init_step * jnp.power(jnp.asarray(shrink, dtype), ks)  # [K]
        f_trials = trial_values(ts)
        armijo = armijo_ok(ts, f_trials)
        ok = jnp.any(armijo)
        idx = jnp.argmax(armijo)  # first True (argmax of bool)
        t_acc = ts[idx]
        f_new = f_trials[idx]

        if max_line_search + 1 > n_batched:
            # Sequential tail for candidates past the batched block —
            # normally 0 iterations (the cond sees ok=True immediately).
            def ls_cond(s):
                tail_ok, _, _, k = s
                return jnp.logical_and(~tail_ok, k < max_line_search + 1)

            def ls_body(s):
                _, _, t, k = s
                t = t * shrink
                f_t = trial_values(t[None])[0]
                t_ok = jnp.logical_and(
                    f_t <= st.f + c1 * t * gp, jnp.isfinite(f_t))
                return t_ok, f_t, t, k + 1

            ok, f_new_t, t_tail, _ = lax.while_loop(
                ls_cond, ls_body,
                (ok, f_new, ts[-1], jnp.asarray(n_batched, jnp.int32)))
            in_tail = ~jnp.any(armijo)
            t_acc = jnp.where(in_tail, t_tail, t_acc)
            f_new = jnp.where(in_tail, f_new_t, f_new)

        x_new = st.x + t_acc * direction
        z_new = st.z + t_acc * zp
        g_new = objective.gradient_from_margins(x_new, z_new, batch, l2)

        hist_new = update_history(st.hist, x_new - st.x, g_new - st.g)
        it_new = st.it + 1
        gnorm_new = jnp.linalg.norm(g_new)
        f_delta = jnp.abs(st.f - f_new)
        reason = jnp.where(
            ~ok,
            int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING),
            jnp.where(
                gnorm_new <= tol * gnorm0,
                int(ConvergenceReason.GRADIENT_CONVERGED),
                jnp.where(
                    f_delta <= tol * f0_scale,
                    int(ConvergenceReason.FUNCTION_VALUES_CONVERGED),
                    jnp.where(
                        it_new >= max_iter,
                        int(ConvergenceReason.MAX_ITERATIONS),
                        int(ConvergenceReason.NOT_CONVERGED))))
        ).astype(jnp.int32)

        # A failed line search must not move the iterate.
        x_new = jnp.where(ok, x_new, st.x)
        z_new = jnp.where(ok, z_new, st.z)
        f_new = jnp.where(ok, f_new, st.f)
        g_new = jnp.where(ok, g_new, st.g)
        gnorm_new = jnp.where(ok, gnorm_new, jnp.linalg.norm(st.g))
        hist_new = jax.tree.map(
            lambda a, b: jnp.where(ok, a, b), hist_new, st.hist)

        new = _State(
            x=x_new, z=z_new, f=f_new, g=g_new, hist=hist_new, it=it_new,
            reason=reason,
            value_hist=st.value_hist.at[it_new].set(f_new),
            gnorm_hist=st.gnorm_hist.at[it_new].set(gnorm_new),
            coef_hist=(None if st.coef_hist is None
                       else st.coef_hist.at[it_new].set(x_new)),
        )
        done = ~cond(st)
        return jax.tree.map(lambda a, b: jnp.where(done, a, b), st, new)

    final = lax.while_loop(cond, body, init)
    return OptimizerResult(
        x=final.x, value=final.f, grad_norm=jnp.linalg.norm(final.g),
        iterations=final.it, reason=final.reason,
        value_history=final.value_hist, grad_norm_history=final.gnorm_hist,
        coef_history=final.coef_hist,
    )


@jax.jit
def _stream_direction(g, hist, x):
    """Search direction + the line-search dot products ([d]-space only),
    mirroring the fused body's first block bit for bit."""
    direction = compact_direction(g, hist)
    dg = jnp.vdot(direction, g)
    direction = jnp.where(dg >= 0, -g, direction)
    return (direction, jnp.vdot(x, x), jnp.vdot(x, direction),
            jnp.vdot(direction, direction), jnp.vdot(g, direction))


@functools.partial(jax.jit, static_argnames=("n",))
def _stream_candidates(first, pp, f, gp, n, c1):
    """The batched Armijo candidate block t_k = init * shrink^k and the
    acceptance thresholds — same expressions as the fused impl."""
    dtype = pp.dtype
    init_step = jnp.where(first, 1.0 / jnp.maximum(jnp.sqrt(pp), 1.0),
                          jnp.ones((), dtype))
    ks = jnp.arange(n, dtype=dtype)
    ts = init_step * jnp.power(jnp.asarray(0.5, dtype), ks)
    return ts, f + c1 * ts * gp


@jax.jit
def _stream_coef_sq(xx, xp, pp, ts):
    return xx + 2.0 * ts * xp + ts * ts * pp


@jax.jit
def _stream_axpy(a, t, b):
    return a + t * b


@jax.jit
def _stream_update_history(hist, x_new, x, g_new, g):
    return update_history(hist, x_new - x, g_new - g)


def minimize_lbfgs_glm_streaming(
    sharded_objective,
    x0: Array,
    l2_weight,
    *,
    max_iter: int = 100,
    tol: float = 1e-7,
    history_size: int = 10,
    c1: float = 1e-4,
    max_line_search: int = 30,
    track_coefficients: bool = False,
    trace_ctx=None,
    convergence_ring=None,
    margins_out=None,
) -> OptimizerResult:
    """Out-of-core L-BFGS: the outer iteration runs on the host, streaming
    each feature pass through a :class:`ShardedGLMObjective`
    (ops/sharded_objective.py) whose shard cache replays device-resident
    blocks (spilling/re-uploading under an HBM budget).

    Semantics mirror `_minimize_lbfgs_glm_impl` step for step — margins
    cached per shard (row-space, always resident), ONE matvec pass for
    the whole line search, one rmatvec pass for the accepted gradient,
    identical convergence reasons — so per-iteration feature passes stay
    at exactly 2. The accumulation order is the fixed shard order, so
    results are deterministic and independent of cache residency (see
    the numeric contract in ops/sharded_objective.py; a single-shard
    cache reproduces the fused path bit for bit).

    Mesh-aware transparently: when the sharded objective carries a 1-D
    device mesh, every feature pass AND the per-shard margin updates run
    on each shard's own device (the objective broadcasts
    coefficients/steps and combines partials in fixed shard order); the
    [d]-space outer iteration here — direction, history, convergence —
    runs on the fold device. With the default "ordered" combine the
    solve result is bit-identical for every device count.

    2-D (data x model) meshes compose the same way, with one DOCUMENTED
    state decision: the host-side convergence state — coefficients,
    gradient, L-BFGS curvature history, direction — STAYS FULL-WIDTH on
    the host/default device (it is NOT blocked over the model axis).
    The sharded objective hands this solver full-width [d] gradients
    assembled by its deterministic model-axis concat and takes
    full-width coefficients back, slicing them per column block before
    anything reaches a mesh device — so the solver needs no code for
    the model axis at all, and mesh shapes {1x1, 2x1, 1x2, 2x2} solve
    bit-identically (ops/sharded_objective.py module docstring; O(d)
    host memory for solver state is the accepted cost, blocked solver
    state is the ROADMAP follow-on).

    Spill-tier interaction: the margin cache (z per shard) and the
    line-search trials live in ROW space, which the cache never evicts
    — so `trial_values` and `update_margins` walk `cache.entries`
    without touching feature residency, and the compressed
    (``spill_dtype="bf16"``) and fully out-of-core
    (``spill_source="redecode"``) tiers change NOTHING about the
    iteration structure: the whole Armijo sweep still costs zero
    feature passes, zero re-uploads and zero Avro re-decodes; only the
    2 feature passes per iteration (direction matvec, accepted
    gradient) pay the miss path, so a redecode epoch re-decodes each
    evicted block at most twice per outer iteration.

    Divergence watchdog: the host already holds loss and grad-norm as
    scalars for the convergence compares, so every outer iteration (and
    the initial evaluation) checks them for NaN/Inf and raises a typed
    :class:`~photon_ml_tpu.optimization.convergence.SolverDivergedError`
    — the fused impl cannot do this mid-``while_loop`` and silently
    rides a NaN to a convergence-failure reason. ``trace_ctx`` (one
    :class:`~photon_ml_tpu.telemetry.tracectx.TraceContext` per solve,
    minted per λ-grid point by the streaming driver) gets one
    ``solver_step`` event per outer iteration and, on divergence, a
    ``diverged`` finish whose trace_id tags the fault and flight dump.

    Distribution-observability hooks (``--distmon``, data/distmon.py):
    ``convergence_ring`` (a
    :class:`~photon_ml_tpu.optimization.convergence.ConvergenceRing`)
    gets one ``(iteration, loss, grad_norm, accepted step)`` entry per
    outer iteration — the host already holds every one of those scalars
    for the convergence compares, so the ring adds no sync; and
    ``margins_out`` (a caller-owned list) is replaced with the FINAL
    per-shard margin list, letting the driver sketch training-score
    quantiles from state the solve computed anyway — zero extra feature
    passes (``ShardedGLMObjective.host_scores_from_margins``).
    """
    import numpy as np

    sobj = sharded_objective
    x = jnp.asarray(x0)
    dtype = x.dtype
    np_dtype = np.dtype(dtype)
    l2 = jnp.asarray(l2_weight, dtype)
    d = x.shape[-1]
    shrink = jnp.asarray(0.5, dtype)
    n_batched = min(max_line_search + 1, 8)

    def host(v):
        # 0-d numpy scalar in the solve dtype: host-side convergence
        # arithmetic stays in the SAME precision as the fused impl's
        # on-device comparisons (a python-float compare would widen to
        # f64 and could flip a boundary decision).
        return np.asarray(v)[()]

    tol_s = np_dtype.type(tol)
    z_list, f, g = sobj.margins_value_grad(x, l2)
    f_h = host(f)
    gnorm = host(jnp.linalg.norm(g))
    check_solver_finite("streaming-lbfgs", 0, f_h, gnorm, trace_ctx)
    if convergence_ring is not None:
        convergence_ring.append(0, f_h, gnorm, None)
    gnorm0 = gnorm
    f0_scale = np.maximum(np.abs(f_h), np_dtype.type(1e-30))
    hist = _empty_history(d, history_size, dtype)

    value_hist = np.full(max_iter + 1, np.nan, np_dtype)
    gnorm_hist = np.full(max_iter + 1, np.nan, np_dtype)
    value_hist[0], gnorm_hist[0] = f_h, gnorm
    coef_hist = (np.full((max_iter + 1, d), np.nan, np_dtype)
                 if track_coefficients else None)
    if coef_hist is not None:
        coef_hist[0] = np.asarray(x)

    reason = (ConvergenceReason.GRADIENT_CONVERGED if gnorm0 <= 0.0
              else ConvergenceReason.NOT_CONVERGED)
    it = 0
    while reason == ConvergenceReason.NOT_CONVERGED:
        # ``solver_step`` = one outer iteration (direction + line search
        # + accepted gradient), the per-iteration telemetry the fused
        # impl cannot expose from inside its lax.while_loop.
        with telemetry.timed_span("solver_step", histogram=_H_ITERATION,
                                  counter=_M_ITERATIONS):
            if trace_ctx is not None:
                trace_ctx.event("solver_step")
            direction, xx, xp, pp, gp = _stream_direction(g, hist, x)
            zp_list = sobj.margin_direction_list(direction)

            first = int(hist.count) == 0  # mirrors st.hist.count == 0
            ts, thresholds = _stream_candidates(
                jnp.asarray(first), pp, f, gp, n_batched,
                jnp.asarray(c1, dtype))
            f_trials = sobj.trial_values(
                z_list, zp_list, ts, _stream_coef_sq(xx, xp, pp, ts), l2)
            ft_host = np.asarray(f_trials)
            armijo = np.logical_and(ft_host <= np.asarray(thresholds),
                                    np.isfinite(ft_host))
            ok = bool(armijo.any())
            idx = int(np.argmax(armijo))  # first True
            t_acc = ts[idx]
            f_new = f_trials[idx]

            k = n_batched
            t_tail = ts[-1]
            while not ok and k < max_line_search + 1:
                # Sequential tail past the batched block — rare
                # (shrink^8).
                t_tail = t_tail * shrink
                f_t = sobj.trial_values(
                    z_list, zp_list, t_tail[None],
                    _stream_coef_sq(xx, xp, pp, t_tail[None]), l2)[0]
                f_t_h = host(f_t)
                thr = host(f + jnp.asarray(c1, dtype) * t_tail * gp)
                if f_t_h <= thr and np.isfinite(f_t_h):
                    ok, t_acc, f_new = True, t_tail, f_t
                    break
                k += 1

            it += 1  # the fused impl counts failed-line-search steps too
            if not ok:
                reason = ConvergenceReason.OBJECTIVE_NOT_IMPROVING
                if it <= max_iter:
                    value_hist[it], gnorm_hist[it] = f_h, gnorm
                    if coef_hist is not None:
                        coef_hist[it] = np.asarray(x)
                if convergence_ring is not None:
                    # Failed line search: the iterate did not move.
                    convergence_ring.append(it, f_h, gnorm, 0.0)
                break

            x_new = _stream_axpy(x, t_acc, direction)
            # Margins update on each shard's own device (mesh-aware; the
            # same a + t*b expression as the fused impl, so single-shard
            # bitwise identity holds).
            z_new = sobj.update_margins(z_list, t_acc, zp_list)
            g_new = sobj.grad_from_margins_list(x_new, z_new, l2)
            hist = _stream_update_history(hist, x_new, x, g_new, g)

            gnorm_new = host(jnp.linalg.norm(g_new))
            f_new_h = host(f_new)
            # Watchdog: both scalars are already host-side for the
            # convergence compares below — the check adds no sync.
            check_solver_finite("streaming-lbfgs", it, f_new_h,
                                gnorm_new, trace_ctx)
            f_delta = np.abs(f_h - f_new_h)
            x, z_list, f, g = x_new, z_new, f_new, g_new
            f_h, gnorm = f_new_h, gnorm_new
            value_hist[it], gnorm_hist[it] = f_h, gnorm
            if coef_hist is not None:
                coef_hist[it] = np.asarray(x)
            if convergence_ring is not None:
                convergence_ring.append(it, f_h, gnorm, host(t_acc))

            if gnorm_new <= tol_s * gnorm0:
                reason = ConvergenceReason.GRADIENT_CONVERGED
            elif f_delta <= tol_s * f0_scale:
                reason = ConvergenceReason.FUNCTION_VALUES_CONVERGED
            elif it >= max_iter:
                reason = ConvergenceReason.MAX_ITERATIONS

    if margins_out is not None:
        # Final per-shard margins (aligned with cache.entries) — the
        # driver sketches training scores from these instead of paying
        # a scoring pass.
        margins_out[:] = z_list
    return OptimizerResult(
        x=x, value=f, grad_norm=jnp.asarray(gnorm, dtype),
        iterations=jnp.asarray(it, jnp.int32),
        reason=jnp.asarray(int(reason), jnp.int32),
        value_history=jnp.asarray(value_hist),
        grad_norm_history=jnp.asarray(gnorm_hist),
        coef_history=(None if coef_hist is None
                      else jnp.asarray(coef_hist)),
    )


@jax.jit
def _grid_direction(g, hist, x):
    """Per-row search directions + line-search dot products: the scalar
    `_stream_direction` body vmapped over the grid axis."""
    def one(g_g, hist_g, x_g):
        direction = compact_direction(g_g, hist_g)
        dg = jnp.vdot(direction, g_g)
        direction = jnp.where(dg >= 0, -g_g, direction)
        return (direction, jnp.vdot(x_g, x_g), jnp.vdot(x_g, direction),
                jnp.vdot(direction, direction), jnp.vdot(g_g, direction))

    return jax.vmap(one)(g, hist, x)


@functools.partial(jax.jit, static_argnames=("n",))
def _grid_candidates(first, pp, f, gp, n, c1):
    """[G, K] Armijo candidate blocks + thresholds, per grid row."""
    def one(first_g, pp_g, f_g, gp_g):
        dtype = pp_g.dtype
        init_step = jnp.where(first_g,
                              1.0 / jnp.maximum(jnp.sqrt(pp_g), 1.0),
                              jnp.ones((), dtype))
        ks = jnp.arange(n, dtype=dtype)
        ts = init_step * jnp.power(jnp.asarray(0.5, dtype), ks)
        return ts, f_g + c1 * ts * gp_g

    return jax.vmap(one)(first, pp, f, gp)


@jax.jit
def _grid_coef_sq(xx, xp, pp, ts):
    return (xx[:, None] + 2.0 * ts * xp[:, None]
            + ts * ts * pp[:, None])


@jax.jit
def _grid_axpy_masked(a, t, b):
    """Per-row a + t*b; rows with t == 0 stay bit-identical (masked, not
    added — the coefficient-space twin of the grid margin axpy)."""
    return jnp.where((t != 0.0)[:, None], a + t[:, None] * b, a)


@jax.jit
def _grid_select_rows(mask, a, b):
    """Per-leaf row select: rows where ``mask`` take ``a``, else ``b``."""
    def sel(a_leaf, b_leaf):
        m = mask.reshape(mask.shape + (1,) * (a_leaf.ndim - 1))
        return jnp.where(m, a_leaf, b_leaf)

    return jax.tree.map(sel, a, b)


@jax.jit
def _grid_update_history(hist, x_new, x, g_new, g, moved):
    """vmapped cautious history update, applied only to rows that moved
    (a failed line search must not touch that row's history)."""
    new = jax.vmap(update_history)(hist, x_new - x, g_new - g)
    return _grid_select_rows(moved, new, hist)


def minimize_lbfgs_glm_grid_streaming(
    sharded_objective,
    x0s: Array,
    l2_weights,
    *,
    max_iter: int = 100,
    tol: float = 1e-7,
    history_size: int = 10,
    c1: float = 1e-4,
    max_line_search: int = 30,
    track_coefficients: bool = False,
    trace_ctxs=None,
    convergence_rings=None,
    margins_out=None,
):
    """Batched λ-grid streaming L-BFGS: ONE set of feature passes per
    outer iteration advances ALL G grid points (coefficients ``[G, d]``,
    per-shard margins ``[G, rows]``, λ row ``l2_weights`` of shape
    ``[G]``). Returns a list of G :class:`OptimizerResult`, row-aligned
    with the inputs.

    **Masked convergence.** Row state lives on the host as numpy masks:
    a converged/failed row is frozen by forcing its accepted step to 0
    and selecting its previous state through ``jnp.where`` row masks —
    no extra feature passes, no per-row epochs. Each outer iteration
    still costs exactly 2 feature passes (direction matvec + accepted
    gradient rmatvec) regardless of G, and the loop ends when every
    row's mask is done, so the sweep's total pass count is that of the
    SLOWEST-converging row — not the sum over rows.

    **Bit discipline.** G=1 delegates to
    :func:`minimize_lbfgs_glm_streaming` outright (XLA's vectorized
    reduces are not prefix-stable under a leading batch axis, so a
    ``[1, n]`` vmapped reduction is NOT bitwise the ``[n]`` scalar one)
    — the batched G=1 solve is the current streamed solver, bit for
    bit. For G>1 each row follows the scalar iteration's semantics
    (same candidate schedule, same convergence order and thresholds in
    the same dtype) with vmap-level reassociation bounds on the values.

    **Per-row observability.** ``trace_ctxs``/``convergence_rings`` are
    row-aligned lists (either may be None, entries may be None): each
    active row's TraceContext gets a ``solver_step`` event per outer
    iteration it participates in, and each ring gets one entry per
    iteration the row advanced — the same structure a sequential sweep
    produces. ``training.grid.active_points`` gauges the still-active
    row count each iteration.

    **Row-isolated divergence.** A non-finite loss/grad-norm in one row
    raises :class:`SolverDivergedError` carrying that row's λ,
    ``grid_row`` and trace_id (that row's context is finished as
    ``diverged``); other rows' masks and state are untouched by the
    check itself.

    ``margins_out`` receives the final per-shard ``[G, rows]`` margin
    list; slice one row out with
    ``ShardedGLMObjective.grid_row_margins``.
    """
    import numpy as np

    sobj = sharded_objective
    x = jnp.asarray(x0s)
    if x.ndim != 2:
        raise ValueError(
            f"x0s must be [G, d] (one coefficient row per grid point), "
            f"got shape {x.shape}")
    G, d = x.shape
    dtype = x.dtype
    np_dtype = np.dtype(dtype)
    l2s = jnp.asarray(l2_weights, dtype)
    if l2s.shape != (G,):
        raise ValueError(
            f"l2_weights must be [G]={G} (one λ per grid row), got "
            f"shape {l2s.shape}")
    ctxs = list(trace_ctxs) if trace_ctxs is not None else [None] * G
    rings = (list(convergence_rings) if convergence_rings is not None
             else [None] * G)
    if len(ctxs) != G or len(rings) != G:
        raise ValueError(
            f"trace_ctxs/convergence_rings must be row-aligned with the "
            f"grid (G={G}), got {len(ctxs)}/{len(rings)}")

    if G == 1:
        # Bitwise gate: the 1-row grid IS the scalar streamed solver.
        holder = [] if margins_out is not None else None
        res = minimize_lbfgs_glm_streaming(
            sobj, x[0], l2s[0], max_iter=max_iter, tol=tol,
            history_size=history_size, c1=c1,
            max_line_search=max_line_search,
            track_coefficients=track_coefficients, trace_ctx=ctxs[0],
            convergence_ring=rings[0], margins_out=holder)
        if margins_out is not None:
            margins_out[:] = [z[None] for z in holder]
        return [res]

    tol_s = np_dtype.type(tol)
    c1_dev = jnp.asarray(c1, dtype)
    c1_np = np_dtype.type(c1)
    l2_h = np.asarray(l2s)
    n_batched = min(max_line_search + 1, 8)

    z_list, f, g = sobj.grid_margins_value_grad(x, l2s)
    f_h = np.asarray(f)
    gnorm = np.asarray(jnp.linalg.norm(g, axis=-1))
    for gi in range(G):
        check_solver_finite("streaming-lbfgs-grid", 0, f_h[gi],
                            gnorm[gi], ctxs[gi], lam=l2_h[gi],
                            grid_row=gi)
        if rings[gi] is not None:
            rings[gi].append(0, f_h[gi], gnorm[gi], None)
    gnorm0 = gnorm.copy()
    f0_scale = np.maximum(np.abs(f_h), np_dtype.type(1e-30))
    hist = jax.tree.map(lambda a: jnp.stack([a] * G),
                        _empty_history(d, history_size, dtype))

    value_hist = np.full((G, max_iter + 1), np.nan, np_dtype)
    gnorm_hist = np.full((G, max_iter + 1), np.nan, np_dtype)
    value_hist[:, 0], gnorm_hist[:, 0] = f_h, gnorm
    coef_hist = (np.full((G, max_iter + 1, d), np.nan, np_dtype)
                 if track_coefficients else None)
    if coef_hist is not None:
        coef_hist[:, 0] = np.asarray(x)

    reasons = [ConvergenceReason.GRADIENT_CONVERGED if gnorm0[gi] <= 0.0
               else ConvergenceReason.NOT_CONVERGED for gi in range(G)]
    active = np.array(
        [r == ConvergenceReason.NOT_CONVERGED for r in reasons])
    its = np.zeros(G, np.int64)

    while active.any():
        with telemetry.timed_span("solver_step", histogram=_H_ITERATION,
                                  counter=_M_ITERATIONS):
            _G_GRID_ACTIVE.set(int(active.sum()))
            for gi in np.flatnonzero(active):
                if ctxs[gi] is not None:
                    ctxs[gi].event("solver_step")
            dirs, xx, xp, pp, gp = _grid_direction(g, hist, x)
            zp_list = sobj.grid_margin_direction_list(dirs)

            first_h = np.asarray(hist.count) == 0
            ts, thresholds = _grid_candidates(
                jnp.asarray(first_h), pp, jnp.asarray(f_h), gp,
                n_batched, c1_dev)
            f_trials = sobj.grid_trial_values(
                z_list, zp_list, ts, _grid_coef_sq(xx, xp, pp, ts), l2s)
            ft = np.asarray(f_trials)
            armijo = np.logical_and(ft <= np.asarray(thresholds),
                                    np.isfinite(ft))
            ok = armijo.any(axis=1)
            idx = np.argmax(armijo, axis=1)
            ts_h = np.asarray(ts)
            rows = np.arange(G)
            t_np = np.where(ok & active, ts_h[rows, idx],
                            np_dtype.type(0.0))
            f_new_h = np.where(ok, ft[rows, idx], f_h)

            searching = active & ~ok
            k = n_batched
            t_tail = ts_h[:, -1].copy()
            gp_h = np.asarray(gp)
            while searching.any() and k < max_line_search + 1:
                t_tail = t_tail * np_dtype.type(0.5)
                ts_tail = np.where(searching, t_tail,
                                   np_dtype.type(0.0))[:, None]
                tsd = jnp.asarray(ts_tail)
                f_t = sobj.grid_trial_values(
                    z_list, zp_list, tsd,
                    _grid_coef_sq(xx, xp, pp, tsd), l2s)
                f_t_h = np.asarray(f_t)[:, 0]
                thr_t = f_h + c1_np * t_tail * gp_h
                hit = searching & (f_t_h <= thr_t) & np.isfinite(f_t_h)
                t_np = np.where(hit, t_tail, t_np)
                f_new_h = np.where(hit, f_t_h, f_new_h)
                ok |= hit
                searching &= ~hit
                k += 1

            its[active] += 1  # failed searches count, like the scalar
            moved = ok & active
            failed = active & ~ok
            for gi in np.flatnonzero(failed):
                reasons[gi] = ConvergenceReason.OBJECTIVE_NOT_IMPROVING
                if its[gi] <= max_iter:
                    value_hist[gi, its[gi]] = f_h[gi]
                    gnorm_hist[gi, its[gi]] = gnorm[gi]
                    if coef_hist is not None:
                        coef_hist[gi, its[gi]] = np.asarray(x[gi])
                if rings[gi] is not None:
                    # Failed line search: the row's iterate did not move.
                    rings[gi].append(int(its[gi]), f_h[gi], gnorm[gi],
                                     0.0)
            active &= ok

            if moved.any():
                t_dev = jnp.asarray(t_np)
                moved_dev = jnp.asarray(moved)
                x_new = _grid_axpy_masked(x, t_dev, dirs)
                z_new = sobj.grid_update_margins(z_list, t_dev, zp_list)
                g_full = sobj.grid_grad_from_margins_list(
                    x_new, z_new, l2s)
                g_new = _grid_select_rows(moved_dev, g_full, g)
                hist = _grid_update_history(hist, x_new, x, g_new, g,
                                            moved_dev)
                gnorm_new = np.asarray(jnp.linalg.norm(g_new, axis=-1))
                x, z_list, g = x_new, z_new, g_new
                f_delta = np.abs(f_h - f_new_h)
                f_h = np.where(moved, f_new_h, f_h)
                gnorm = np.where(moved, gnorm_new, gnorm)

                for gi in np.flatnonzero(moved):
                    check_solver_finite(
                        "streaming-lbfgs-grid", int(its[gi]), f_h[gi],
                        gnorm[gi], ctxs[gi], lam=l2_h[gi], grid_row=gi)
                    value_hist[gi, its[gi]] = f_h[gi]
                    gnorm_hist[gi, its[gi]] = gnorm[gi]
                    if coef_hist is not None:
                        coef_hist[gi, its[gi]] = np.asarray(x[gi])
                    if rings[gi] is not None:
                        rings[gi].append(int(its[gi]), f_h[gi],
                                         gnorm[gi], float(t_np[gi]))
                    if gnorm[gi] <= tol_s * gnorm0[gi]:
                        reasons[gi] = ConvergenceReason.GRADIENT_CONVERGED
                    elif f_delta[gi] <= tol_s * f0_scale[gi]:
                        reasons[gi] = (
                            ConvergenceReason.FUNCTION_VALUES_CONVERGED)
                    elif its[gi] >= max_iter:
                        reasons[gi] = ConvergenceReason.MAX_ITERATIONS
                    if reasons[gi] != ConvergenceReason.NOT_CONVERGED:
                        active[gi] = False
    _G_GRID_ACTIVE.set(0)

    if margins_out is not None:
        margins_out[:] = z_list
    x_np = np.asarray(x)
    return [
        OptimizerResult(
            x=jnp.asarray(x_np[gi]),
            value=jnp.asarray(f_h[gi]),
            grad_norm=jnp.asarray(gnorm[gi]),
            iterations=jnp.asarray(int(its[gi]), jnp.int32),
            reason=jnp.asarray(int(reasons[gi]), jnp.int32),
            value_history=jnp.asarray(value_hist[gi]),
            grad_norm_history=jnp.asarray(gnorm_hist[gi]),
            coef_history=(None if coef_hist is None
                          else jnp.asarray(coef_hist[gi])),
        )
        for gi in range(G)
    ]


def minimize_lbfgs_glm(
    objective: GLMObjective,
    batch: GLMBatch,
    x0: Array,
    l2_weight,
    *,
    max_iter: int = 100,
    tol: float = 1e-7,
    history_size: int = 10,
    c1: float = 1e-4,
    max_line_search: int = 30,
    track_coefficients: bool = False,
) -> OptimizerResult:
    """Unconstrained L2 GLM solve with margin-cached line search. Defaults
    mirror minimize_lbfgs (and the reference: maxIter=100, tol=1e-7, m=10,
    ml/optimization/LBFGS.scala:152-156)."""
    x0 = jnp.asarray(x0)
    l2 = jnp.asarray(l2_weight, x0.dtype)
    return _minimize_lbfgs_glm_impl(
        objective, x0, batch, l2, max_iter=max_iter, tol=tol,
        history_size=history_size, c1=c1, max_line_search=max_line_search,
        track_coefficients=track_coefficients,
    )
