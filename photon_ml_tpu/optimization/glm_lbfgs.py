"""GLM-specialized L-BFGS with margin-cached line search.

The generic L-BFGS (lbfgs.py) evaluates value+gradient at every line-search
trial — each evaluation is a matvec + rmatvec over the full training shard.
For a GLM the margins are AFFINE in the coefficients, so along a search
direction p:

    margins(x + t p) = z + t * zp        (z, zp precomputed n-vectors)
    value(x + t p)   = sum_i w_i l(z_i + t zp_i, y_i)
                       + l2/2 (||x||^2 + 2 t x.p + t^2 ||p||^2)

— every trial is O(n) elementwise work with NO feature contraction, and the
gradient is needed only once per iteration, at the accepted point, via
``GLMObjective.gradient_from_margins`` (one rmatvec). Per-iteration feature
contractions drop from 2 x (1 + #trials) to exactly 2 (one matvec for the
direction margins, one rmatvec for the accepted gradient) — the same
two-contraction economy the reference's fused aggregator achieves for a
single evaluation (ml/function/ValueAndGradientAggregator.scala:34-221),
here extended over the whole line search.

Semantics (convergence reasons, cautious curvature updates, vmap masking)
are identical to lbfgs.py; `solve_glm` routes unconstrained L2 L-BFGS
solves here. Box constraints break the affine-margin trick (projection is
nonlinear in t), so bounded solves stay on the generic path.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu import telemetry
from photon_ml_tpu.ops.glm_objective import GLMBatch, GLMObjective
from photon_ml_tpu.optimization.convergence import (
    ConvergenceReason,
    OptimizerResult,
    check_solver_finite,
)
from photon_ml_tpu.optimization.lbfgs import (
    _LBFGSHistory,
    _empty_history,
    compact_direction,
    update_history,
)

Array = jax.Array

# Per-OUTER-iteration wall time of the host-driven streaming solvers
# (L-BFGS here, TRON in tron.py) — each iteration is a fixed number of
# feature passes over the shard cache, so this histogram is the
# end-to-end cost of one streamed epoch-pair (no-op while telemetry is
# off; the fused lax.while_loop solvers are NOT instrumented — spans
# never open inside jitted code).
_H_ITERATION = telemetry.histogram("training.iteration_seconds")
_M_ITERATIONS = telemetry.counter("training.solver_iterations")


class _State(NamedTuple):
    x: Array
    z: Array  # margins at x (n-vector)
    f: Array
    g: Array
    hist: _LBFGSHistory
    it: Array
    reason: Array
    value_hist: Array
    gnorm_hist: Array
    coef_hist: Optional[Array]


@functools.partial(
    jax.jit,
    static_argnames=("objective", "max_iter", "tol", "history_size", "c1",
                     "max_line_search", "track_coefficients"),
)
def _minimize_lbfgs_glm_impl(
    objective: GLMObjective, x0, batch: GLMBatch, l2, *, max_iter, tol,
    history_size, c1, max_line_search, track_coefficients=False,
) -> OptimizerResult:
    dtype = x0.dtype
    d = x0.shape[-1]
    shrink = 0.5

    z0 = objective.margins(x0, batch)
    f0 = objective.value_from_margins(z0, jnp.vdot(x0, x0), batch, l2)
    g0 = objective.gradient_from_margins(x0, z0, batch, l2)
    gnorm0 = jnp.linalg.norm(g0)
    f0_scale = jnp.maximum(jnp.abs(f0), jnp.asarray(1e-30, dtype))

    value_hist = jnp.full((max_iter + 1,), jnp.nan, dtype).at[0].set(f0)
    gnorm_hist = jnp.full((max_iter + 1,), jnp.nan, dtype).at[0].set(gnorm0)
    coef_hist = (jnp.full((max_iter + 1, d), jnp.nan, dtype).at[0].set(x0)
                 if track_coefficients else None)

    init = _State(
        x=x0, z=z0, f=f0, g=g0,
        hist=_empty_history(d, history_size, dtype),
        it=jnp.zeros((), jnp.int32),
        reason=jnp.where(
            gnorm0 <= 0.0, int(ConvergenceReason.GRADIENT_CONVERGED),
            int(ConvergenceReason.NOT_CONVERGED)).astype(jnp.int32),
        value_hist=value_hist, gnorm_hist=gnorm_hist, coef_hist=coef_hist,
    )

    def cond(st: _State):
        return st.reason == int(ConvergenceReason.NOT_CONVERGED)

    def body(st: _State):
        direction = compact_direction(st.g, st.hist)
        dg = jnp.vdot(direction, st.g)
        use_sd = dg >= 0
        direction = jnp.where(use_sd, -st.g, direction)

        # One matvec for the whole line search.
        zp = objective.margin_direction(direction, batch)
        xx = jnp.vdot(st.x, st.x)
        xp = jnp.vdot(st.x, direction)
        pp = jnp.vdot(direction, direction)
        gp = jnp.vdot(st.g, direction)

        first = st.hist.count == 0
        init_step = jnp.where(
            first, 1.0 / jnp.maximum(jnp.sqrt(pp), 1.0),
            jnp.ones((), dtype))

        # BATCHED Armijo backtracking: margins are affine in the step, so a
        # block of candidates t_k = init * shrink^k is priced in ONE fused
        # [K, n] elementwise reduction (a device-loop iteration costs
        # ~0.14 ms on TPU v5e, so a 5-trial sequential search was ~1 ms of
        # loop overhead). K is capped at 8 to bound the [K, n] intermediate
        # on huge shards; the rare candidates beyond the block (shrink^8
        # ~ 4e-3 of the step) run through the original sequential tail, so
        # the accepted step — the FIRST candidate satisfying Armijo — is
        # bit-identical to fully sequential backtracking.
        n_batched = min(max_line_search + 1, 8)

        def trial_values(ts):
            z_trials = st.z[None, :] + ts[:, None] * zp[None, :]
            data_terms = jnp.sum(
                batch.weights[None, :]
                * objective.loss.loss(z_trials, batch.labels[None, :]),
                axis=-1)
            coef_sq = xx + 2.0 * ts * xp + ts * ts * pp
            return data_terms + 0.5 * l2 * coef_sq

        def armijo_ok(ts, f_trials):
            return jnp.logical_and(f_trials <= st.f + c1 * ts * gp,
                                   jnp.isfinite(f_trials))

        ks = jnp.arange(n_batched, dtype=dtype)
        ts = init_step * jnp.power(jnp.asarray(shrink, dtype), ks)  # [K]
        f_trials = trial_values(ts)
        armijo = armijo_ok(ts, f_trials)
        ok = jnp.any(armijo)
        idx = jnp.argmax(armijo)  # first True (argmax of bool)
        t_acc = ts[idx]
        f_new = f_trials[idx]

        if max_line_search + 1 > n_batched:
            # Sequential tail for candidates past the batched block —
            # normally 0 iterations (the cond sees ok=True immediately).
            def ls_cond(s):
                tail_ok, _, _, k = s
                return jnp.logical_and(~tail_ok, k < max_line_search + 1)

            def ls_body(s):
                _, _, t, k = s
                t = t * shrink
                f_t = trial_values(t[None])[0]
                t_ok = jnp.logical_and(
                    f_t <= st.f + c1 * t * gp, jnp.isfinite(f_t))
                return t_ok, f_t, t, k + 1

            ok, f_new_t, t_tail, _ = lax.while_loop(
                ls_cond, ls_body,
                (ok, f_new, ts[-1], jnp.asarray(n_batched, jnp.int32)))
            in_tail = ~jnp.any(armijo)
            t_acc = jnp.where(in_tail, t_tail, t_acc)
            f_new = jnp.where(in_tail, f_new_t, f_new)

        x_new = st.x + t_acc * direction
        z_new = st.z + t_acc * zp
        g_new = objective.gradient_from_margins(x_new, z_new, batch, l2)

        hist_new = update_history(st.hist, x_new - st.x, g_new - st.g)
        it_new = st.it + 1
        gnorm_new = jnp.linalg.norm(g_new)
        f_delta = jnp.abs(st.f - f_new)
        reason = jnp.where(
            ~ok,
            int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING),
            jnp.where(
                gnorm_new <= tol * gnorm0,
                int(ConvergenceReason.GRADIENT_CONVERGED),
                jnp.where(
                    f_delta <= tol * f0_scale,
                    int(ConvergenceReason.FUNCTION_VALUES_CONVERGED),
                    jnp.where(
                        it_new >= max_iter,
                        int(ConvergenceReason.MAX_ITERATIONS),
                        int(ConvergenceReason.NOT_CONVERGED))))
        ).astype(jnp.int32)

        # A failed line search must not move the iterate.
        x_new = jnp.where(ok, x_new, st.x)
        z_new = jnp.where(ok, z_new, st.z)
        f_new = jnp.where(ok, f_new, st.f)
        g_new = jnp.where(ok, g_new, st.g)
        gnorm_new = jnp.where(ok, gnorm_new, jnp.linalg.norm(st.g))
        hist_new = jax.tree.map(
            lambda a, b: jnp.where(ok, a, b), hist_new, st.hist)

        new = _State(
            x=x_new, z=z_new, f=f_new, g=g_new, hist=hist_new, it=it_new,
            reason=reason,
            value_hist=st.value_hist.at[it_new].set(f_new),
            gnorm_hist=st.gnorm_hist.at[it_new].set(gnorm_new),
            coef_hist=(None if st.coef_hist is None
                       else st.coef_hist.at[it_new].set(x_new)),
        )
        done = ~cond(st)
        return jax.tree.map(lambda a, b: jnp.where(done, a, b), st, new)

    final = lax.while_loop(cond, body, init)
    return OptimizerResult(
        x=final.x, value=final.f, grad_norm=jnp.linalg.norm(final.g),
        iterations=final.it, reason=final.reason,
        value_history=final.value_hist, grad_norm_history=final.gnorm_hist,
        coef_history=final.coef_hist,
    )


@jax.jit
def _stream_direction(g, hist, x):
    """Search direction + the line-search dot products ([d]-space only),
    mirroring the fused body's first block bit for bit."""
    direction = compact_direction(g, hist)
    dg = jnp.vdot(direction, g)
    direction = jnp.where(dg >= 0, -g, direction)
    return (direction, jnp.vdot(x, x), jnp.vdot(x, direction),
            jnp.vdot(direction, direction), jnp.vdot(g, direction))


@functools.partial(jax.jit, static_argnames=("n",))
def _stream_candidates(first, pp, f, gp, n, c1):
    """The batched Armijo candidate block t_k = init * shrink^k and the
    acceptance thresholds — same expressions as the fused impl."""
    dtype = pp.dtype
    init_step = jnp.where(first, 1.0 / jnp.maximum(jnp.sqrt(pp), 1.0),
                          jnp.ones((), dtype))
    ks = jnp.arange(n, dtype=dtype)
    ts = init_step * jnp.power(jnp.asarray(0.5, dtype), ks)
    return ts, f + c1 * ts * gp


@jax.jit
def _stream_coef_sq(xx, xp, pp, ts):
    return xx + 2.0 * ts * xp + ts * ts * pp


@jax.jit
def _stream_axpy(a, t, b):
    return a + t * b


@jax.jit
def _stream_update_history(hist, x_new, x, g_new, g):
    return update_history(hist, x_new - x, g_new - g)


def minimize_lbfgs_glm_streaming(
    sharded_objective,
    x0: Array,
    l2_weight,
    *,
    max_iter: int = 100,
    tol: float = 1e-7,
    history_size: int = 10,
    c1: float = 1e-4,
    max_line_search: int = 30,
    track_coefficients: bool = False,
    trace_ctx=None,
    convergence_ring=None,
    margins_out=None,
) -> OptimizerResult:
    """Out-of-core L-BFGS: the outer iteration runs on the host, streaming
    each feature pass through a :class:`ShardedGLMObjective`
    (ops/sharded_objective.py) whose shard cache replays device-resident
    blocks (spilling/re-uploading under an HBM budget).

    Semantics mirror `_minimize_lbfgs_glm_impl` step for step — margins
    cached per shard (row-space, always resident), ONE matvec pass for
    the whole line search, one rmatvec pass for the accepted gradient,
    identical convergence reasons — so per-iteration feature passes stay
    at exactly 2. The accumulation order is the fixed shard order, so
    results are deterministic and independent of cache residency (see
    the numeric contract in ops/sharded_objective.py; a single-shard
    cache reproduces the fused path bit for bit).

    Mesh-aware transparently: when the sharded objective carries a 1-D
    device mesh, every feature pass AND the per-shard margin updates run
    on each shard's own device (the objective broadcasts
    coefficients/steps and combines partials in fixed shard order); the
    [d]-space outer iteration here — direction, history, convergence —
    runs on the fold device. With the default "ordered" combine the
    solve result is bit-identical for every device count.

    Spill-tier interaction: the margin cache (z per shard) and the
    line-search trials live in ROW space, which the cache never evicts
    — so `trial_values` and `update_margins` walk `cache.entries`
    without touching feature residency, and the compressed
    (``spill_dtype="bf16"``) and fully out-of-core
    (``spill_source="redecode"``) tiers change NOTHING about the
    iteration structure: the whole Armijo sweep still costs zero
    feature passes, zero re-uploads and zero Avro re-decodes; only the
    2 feature passes per iteration (direction matvec, accepted
    gradient) pay the miss path, so a redecode epoch re-decodes each
    evicted block at most twice per outer iteration.

    Divergence watchdog: the host already holds loss and grad-norm as
    scalars for the convergence compares, so every outer iteration (and
    the initial evaluation) checks them for NaN/Inf and raises a typed
    :class:`~photon_ml_tpu.optimization.convergence.SolverDivergedError`
    — the fused impl cannot do this mid-``while_loop`` and silently
    rides a NaN to a convergence-failure reason. ``trace_ctx`` (one
    :class:`~photon_ml_tpu.telemetry.tracectx.TraceContext` per solve,
    minted per λ-grid point by the streaming driver) gets one
    ``solver_step`` event per outer iteration and, on divergence, a
    ``diverged`` finish whose trace_id tags the fault and flight dump.

    Distribution-observability hooks (``--distmon``, data/distmon.py):
    ``convergence_ring`` (a
    :class:`~photon_ml_tpu.optimization.convergence.ConvergenceRing`)
    gets one ``(iteration, loss, grad_norm, accepted step)`` entry per
    outer iteration — the host already holds every one of those scalars
    for the convergence compares, so the ring adds no sync; and
    ``margins_out`` (a caller-owned list) is replaced with the FINAL
    per-shard margin list, letting the driver sketch training-score
    quantiles from state the solve computed anyway — zero extra feature
    passes (``ShardedGLMObjective.host_scores_from_margins``).
    """
    import numpy as np

    sobj = sharded_objective
    x = jnp.asarray(x0)
    dtype = x.dtype
    np_dtype = np.dtype(dtype)
    l2 = jnp.asarray(l2_weight, dtype)
    d = x.shape[-1]
    shrink = jnp.asarray(0.5, dtype)
    n_batched = min(max_line_search + 1, 8)

    def host(v):
        # 0-d numpy scalar in the solve dtype: host-side convergence
        # arithmetic stays in the SAME precision as the fused impl's
        # on-device comparisons (a python-float compare would widen to
        # f64 and could flip a boundary decision).
        return np.asarray(v)[()]

    tol_s = np_dtype.type(tol)
    z_list, f, g = sobj.margins_value_grad(x, l2)
    f_h = host(f)
    gnorm = host(jnp.linalg.norm(g))
    check_solver_finite("streaming-lbfgs", 0, f_h, gnorm, trace_ctx)
    if convergence_ring is not None:
        convergence_ring.append(0, f_h, gnorm, None)
    gnorm0 = gnorm
    f0_scale = np.maximum(np.abs(f_h), np_dtype.type(1e-30))
    hist = _empty_history(d, history_size, dtype)

    value_hist = np.full(max_iter + 1, np.nan, np_dtype)
    gnorm_hist = np.full(max_iter + 1, np.nan, np_dtype)
    value_hist[0], gnorm_hist[0] = f_h, gnorm
    coef_hist = (np.full((max_iter + 1, d), np.nan, np_dtype)
                 if track_coefficients else None)
    if coef_hist is not None:
        coef_hist[0] = np.asarray(x)

    reason = (ConvergenceReason.GRADIENT_CONVERGED if gnorm0 <= 0.0
              else ConvergenceReason.NOT_CONVERGED)
    it = 0
    while reason == ConvergenceReason.NOT_CONVERGED:
        # ``solver_step`` = one outer iteration (direction + line search
        # + accepted gradient), the per-iteration telemetry the fused
        # impl cannot expose from inside its lax.while_loop.
        with telemetry.timed_span("solver_step", histogram=_H_ITERATION,
                                  counter=_M_ITERATIONS):
            if trace_ctx is not None:
                trace_ctx.event("solver_step")
            direction, xx, xp, pp, gp = _stream_direction(g, hist, x)
            zp_list = sobj.margin_direction_list(direction)

            first = int(hist.count) == 0  # mirrors st.hist.count == 0
            ts, thresholds = _stream_candidates(
                jnp.asarray(first), pp, f, gp, n_batched,
                jnp.asarray(c1, dtype))
            f_trials = sobj.trial_values(
                z_list, zp_list, ts, _stream_coef_sq(xx, xp, pp, ts), l2)
            ft_host = np.asarray(f_trials)
            armijo = np.logical_and(ft_host <= np.asarray(thresholds),
                                    np.isfinite(ft_host))
            ok = bool(armijo.any())
            idx = int(np.argmax(armijo))  # first True
            t_acc = ts[idx]
            f_new = f_trials[idx]

            k = n_batched
            t_tail = ts[-1]
            while not ok and k < max_line_search + 1:
                # Sequential tail past the batched block — rare
                # (shrink^8).
                t_tail = t_tail * shrink
                f_t = sobj.trial_values(
                    z_list, zp_list, t_tail[None],
                    _stream_coef_sq(xx, xp, pp, t_tail[None]), l2)[0]
                f_t_h = host(f_t)
                thr = host(f + jnp.asarray(c1, dtype) * t_tail * gp)
                if f_t_h <= thr and np.isfinite(f_t_h):
                    ok, t_acc, f_new = True, t_tail, f_t
                    break
                k += 1

            it += 1  # the fused impl counts failed-line-search steps too
            if not ok:
                reason = ConvergenceReason.OBJECTIVE_NOT_IMPROVING
                if it <= max_iter:
                    value_hist[it], gnorm_hist[it] = f_h, gnorm
                    if coef_hist is not None:
                        coef_hist[it] = np.asarray(x)
                if convergence_ring is not None:
                    # Failed line search: the iterate did not move.
                    convergence_ring.append(it, f_h, gnorm, 0.0)
                break

            x_new = _stream_axpy(x, t_acc, direction)
            # Margins update on each shard's own device (mesh-aware; the
            # same a + t*b expression as the fused impl, so single-shard
            # bitwise identity holds).
            z_new = sobj.update_margins(z_list, t_acc, zp_list)
            g_new = sobj.grad_from_margins_list(x_new, z_new, l2)
            hist = _stream_update_history(hist, x_new, x, g_new, g)

            gnorm_new = host(jnp.linalg.norm(g_new))
            f_new_h = host(f_new)
            # Watchdog: both scalars are already host-side for the
            # convergence compares below — the check adds no sync.
            check_solver_finite("streaming-lbfgs", it, f_new_h,
                                gnorm_new, trace_ctx)
            f_delta = np.abs(f_h - f_new_h)
            x, z_list, f, g = x_new, z_new, f_new, g_new
            f_h, gnorm = f_new_h, gnorm_new
            value_hist[it], gnorm_hist[it] = f_h, gnorm
            if coef_hist is not None:
                coef_hist[it] = np.asarray(x)
            if convergence_ring is not None:
                convergence_ring.append(it, f_h, gnorm, host(t_acc))

            if gnorm_new <= tol_s * gnorm0:
                reason = ConvergenceReason.GRADIENT_CONVERGED
            elif f_delta <= tol_s * f0_scale:
                reason = ConvergenceReason.FUNCTION_VALUES_CONVERGED
            elif it >= max_iter:
                reason = ConvergenceReason.MAX_ITERATIONS

    if margins_out is not None:
        # Final per-shard margins (aligned with cache.entries) — the
        # driver sketches training scores from these instead of paying
        # a scoring pass.
        margins_out[:] = z_list
    return OptimizerResult(
        x=x, value=f, grad_norm=jnp.asarray(gnorm, dtype),
        iterations=jnp.asarray(it, jnp.int32),
        reason=jnp.asarray(int(reason), jnp.int32),
        value_history=jnp.asarray(value_hist),
        grad_norm_history=jnp.asarray(gnorm_hist),
        coef_history=(None if coef_hist is None
                      else jnp.asarray(coef_hist)),
    )


def minimize_lbfgs_glm(
    objective: GLMObjective,
    batch: GLMBatch,
    x0: Array,
    l2_weight,
    *,
    max_iter: int = 100,
    tol: float = 1e-7,
    history_size: int = 10,
    c1: float = 1e-4,
    max_line_search: int = 30,
    track_coefficients: bool = False,
) -> OptimizerResult:
    """Unconstrained L2 GLM solve with margin-cached line search. Defaults
    mirror minimize_lbfgs (and the reference: maxIter=100, tol=1e-7, m=10,
    ml/optimization/LBFGS.scala:152-156)."""
    x0 = jnp.asarray(x0)
    l2 = jnp.asarray(l2_weight, x0.dtype)
    return _minimize_lbfgs_glm_impl(
        objective, x0, batch, l2, max_iter=max_iter, tol=tol,
        history_size=history_size, c1=c1, max_line_search=max_line_search,
        track_coefficients=track_coefficients,
    )
