"""OWL-QN (Orthant-Wise Limited-memory Quasi-Newton) for L1 regularization.

TPU-native counterpart of the reference's OWLQN wrapper around Breeze
(ml/optimization/OWLQN.scala:43-91). Same masked-`lax.while_loop` skeleton as
lbfgs.py, with the three OWL-QN modifications (Andrew & Gao 2007):

- descent direction computed from the *pseudo-gradient* of
  F(x) = f(x) + l1 . |x|, sign-projected against the pseudo-gradient;
- trial points are projected onto the orthant of the current iterate
  (components that cross zero are clamped to zero);
- curvature pairs use gradients of the smooth part only.

``l1_weight`` may be a scalar or a per-coordinate vector (so the intercept can
be left unpenalized), and is a *traced* value — the λ-grid of the reference's
``updateRegularizationWeight`` (ml/optimization/DistributedOptimizationProblem.scala:59-70)
re-runs without recompiling.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimization.convergence import (
    ConvergenceReason,
    OptimizerResult,
)
from photon_ml_tpu.optimization.lbfgs import (
    _LBFGSHistory,
    _empty_history,
    backtracking_line_search,
    compact_direction,
    update_history,
)

Array = jax.Array


def pseudo_gradient(x: Array, g: Array, l1: Array) -> Array:
    """Pseudo-gradient of f(x) + l1.|x| (elementwise l1 >= 0)."""
    right = g + l1  # derivative approaching from the right at x == 0
    left = g - l1
    at_zero = jnp.where(right < 0, right, jnp.where(left > 0, left, 0.0))
    return jnp.where(x != 0, g + l1 * jnp.sign(x), at_zero)


def _orthant_project(x_new: Array, orthant: Array) -> Array:
    """Zero components that left the chosen orthant."""
    return jnp.where(jnp.sign(x_new) == orthant, x_new, 0.0)


class _State(NamedTuple):
    x: Array
    f: Array  # full objective incl. l1 term
    g: Array  # smooth gradient
    pg: Array
    hist: _LBFGSHistory
    it: Array
    reason: Array
    value_hist: Array
    gnorm_hist: Array
    coef_hist: Optional[Array]  # [max_iter+1, d] when tracking, else None


@functools.partial(
    jax.jit,
    static_argnames=("fun", "max_iter", "tol", "history_size", "c1",
                     "max_line_search", "track_coefficients"),
)
def _minimize_owlqn_impl(
    fun, x0, l1, args, *, max_iter, tol, history_size, c1, max_line_search,
    track_coefficients=False,
) -> OptimizerResult:
    vg = jax.value_and_grad(fun)
    dtype = x0.dtype
    d = x0.shape[-1]

    def full_value(x, f_smooth):
        return f_smooth + jnp.sum(l1 * jnp.abs(x))

    f0s, g0 = vg(x0, *args)
    f0 = full_value(x0, f0s)
    pg0 = pseudo_gradient(x0, g0, l1)
    pgnorm0 = jnp.linalg.norm(pg0)
    f0_scale = jnp.maximum(jnp.abs(f0), jnp.asarray(1e-30, dtype))

    value_hist = jnp.full((max_iter + 1,), jnp.nan, dtype).at[0].set(f0)
    gnorm_hist = jnp.full((max_iter + 1,), jnp.nan, dtype).at[0].set(pgnorm0)
    coef_hist = (jnp.full((max_iter + 1, d), jnp.nan, dtype).at[0].set(x0)
                 if track_coefficients else None)

    init = _State(
        x=x0, f=f0, g=g0, pg=pg0,
        hist=_empty_history(d, history_size, dtype),
        it=jnp.zeros((), jnp.int32),
        reason=jnp.where(
            pgnorm0 <= 0.0,
            int(ConvergenceReason.GRADIENT_CONVERGED),
            int(ConvergenceReason.NOT_CONVERGED),
        ).astype(jnp.int32),
        value_hist=value_hist, gnorm_hist=gnorm_hist, coef_hist=coef_hist,
    )

    def cond(st: _State):
        return st.reason == int(ConvergenceReason.NOT_CONVERGED)

    def body(st: _State):
        direction = compact_direction(st.pg, st.hist)
        # Sign projection: keep only components that agree with -pg.
        direction = jnp.where(direction * st.pg < 0, direction, 0.0)
        degenerate = jnp.vdot(direction, st.pg) >= 0
        direction = jnp.where(degenerate, -st.pg, direction)

        orthant = jnp.where(st.x != 0, jnp.sign(st.x), jnp.sign(-st.pg))

        first = st.hist.count == 0
        init_step = jnp.where(
            first, 1.0 / jnp.maximum(jnp.linalg.norm(direction), 1.0),
            jnp.ones((), dtype))

        def vg_full(x, *a):
            f_s, g_s = vg(x, *a)
            return full_value(x, f_s), g_s

        ok, x_new, f_new, g_new = backtracking_line_search(
            vg_full, st.x, st.f, st.pg, direction, args,
            initial_step=init_step, c1=c1, max_steps=max_line_search,
            project_fn=lambda z: _orthant_project(z, orthant),
        )

        hist_new = update_history(st.hist, x_new - st.x, g_new - st.g)
        pg_new = pseudo_gradient(x_new, g_new, l1)
        it_new = st.it + 1
        pgnorm_new = jnp.linalg.norm(pg_new)
        f_delta = jnp.abs(st.f - f_new)

        reason = jnp.where(
            ~ok,
            int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING),
            jnp.where(
                pgnorm_new <= tol * pgnorm0,
                int(ConvergenceReason.GRADIENT_CONVERGED),
                jnp.where(
                    f_delta <= tol * f0_scale,
                    int(ConvergenceReason.FUNCTION_VALUES_CONVERGED),
                    jnp.where(
                        it_new >= max_iter,
                        int(ConvergenceReason.MAX_ITERATIONS),
                        int(ConvergenceReason.NOT_CONVERGED)))),
        ).astype(jnp.int32)

        x_new = jnp.where(ok, x_new, st.x)
        f_new = jnp.where(ok, f_new, st.f)
        g_new = jnp.where(ok, g_new, st.g)
        pg_new = jnp.where(ok, pg_new, st.pg)
        hist_new = jax.tree.map(
            lambda a, b: jnp.where(ok, a, b), hist_new, st.hist)

        new = _State(
            x=x_new, f=f_new, g=g_new, pg=pg_new, hist=hist_new, it=it_new,
            reason=reason,
            value_hist=st.value_hist.at[it_new].set(f_new),
            gnorm_hist=st.gnorm_hist.at[it_new].set(pgnorm_new),
            coef_hist=(None if st.coef_hist is None
                       else st.coef_hist.at[it_new].set(x_new)),
        )
        done = ~cond(st)
        return jax.tree.map(lambda a, b: jnp.where(done, a, b), st, new)

    final = lax.while_loop(cond, body, init)
    return OptimizerResult(
        x=final.x, value=final.f, grad_norm=jnp.linalg.norm(final.pg),
        iterations=final.it, reason=final.reason,
        value_history=final.value_hist, grad_norm_history=final.gnorm_hist,
        coef_history=final.coef_hist,
    )


def minimize_owlqn(
    fun: Callable[..., Array],
    x0: Array,
    args: Tuple[Any, ...] = (),
    *,
    l1_weight: Array | float,
    max_iter: int = 100,
    tol: float = 1e-7,
    history_size: int = 10,
    c1: float = 1e-4,
    max_line_search: int = 30,
    track_coefficients: bool = False,
) -> OptimizerResult:
    """Minimize fun(x, *args) + l1_weight . |x| from x0.

    ``fun`` is the smooth part only. ``l1_weight`` broadcasts against x
    (scalar, or per-coordinate to exempt an intercept).
    """
    x0 = jnp.asarray(x0)
    l1 = jnp.broadcast_to(jnp.asarray(l1_weight, x0.dtype), x0.shape)
    return _minimize_owlqn_impl(
        fun, x0, l1, args, max_iter=max_iter, tol=tol,
        history_size=history_size, c1=c1, max_line_search=max_line_search,
        track_coefficients=track_coefficients,
    )
