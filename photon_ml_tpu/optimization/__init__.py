"""Optimizers as XLA-compilable state machines.

One implementation per algorithm, three execution modes (the reference needed
two parallel class hierarchies — Distributed*/SingleNode* — for this;
here mode is just where the arrays live):

- local: jit on one device
- batched: ``vmap`` over an entity axis (random effects)
- distributed: data sharded over a mesh; gradient sums become ICI
  all-reduces inserted by XLA's SPMD partitioner
"""

from photon_ml_tpu.optimization.convergence import (
    ConvergenceReason,
    OptimizerResult,
    SolverDivergedError,
)
from photon_ml_tpu.optimization.lbfgs import minimize_lbfgs
from photon_ml_tpu.optimization.newton import minimize_newton
from photon_ml_tpu.optimization.owlqn import minimize_owlqn
from photon_ml_tpu.optimization.tron import minimize_tron
from photon_ml_tpu.optimization.config import (
    OptimizerType,
    RegularizationType,
    OptimizerConfig,
    RegularizationContext,
    GLMOptimizationConfiguration,
    MFOptimizationConfiguration,
)

__all__ = [
    "ConvergenceReason",
    "OptimizerResult",
    "SolverDivergedError",
    "minimize_lbfgs",
    "minimize_newton",
    "minimize_owlqn",
    "minimize_tron",
    "OptimizerType",
    "RegularizationType",
    "OptimizerConfig",
    "RegularizationContext",
    "GLMOptimizationConfiguration",
    "MFOptimizationConfiguration",
]
