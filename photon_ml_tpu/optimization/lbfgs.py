"""L-BFGS as a single `lax.while_loop` state machine.

TPU-native counterpart of the reference's LBFGS wrapper around Breeze
(ml/optimization/LBFGS.scala:42-156). Design notes:

- Fixed-shape chronological (s, y) history of ``history_size`` pairs
  (shift-on-update, oldest first); empty slots carry rho=0 and contribute
  nothing — no dynamic shapes anywhere, so XLA compiles one kernel for the
  whole solve. The search direction uses the Byrd–Nocedal compact
  representation (see ``compact_direction``), worth ~3x on vmapped
  per-entity solves where op count, not FLOPs, is the cost.
- Backtracking Armijo line search with cautious curvature-pair updates
  (pairs stored only when s.y > eps ||s|| ||y||). Breeze uses strong-Wolfe;
  Armijo+cautious reaches the same optima on convex GLM objectives while
  staying branch-free and `vmap`-safe.
- Box constraints are applied by projecting each trial point onto
  [lower, upper] (reference: OptimizationUtils.projectCoefficientsToHypercube,
  applied at LBFGS.scala:77); Armijo is evaluated on the projected step.
- Every state update is masked by ``done``, so the solver is correct under
  ``vmap`` (lanes that converge early freeze while others keep iterating) —
  this is what lets thousands of per-entity random-effect solves run as one
  batched kernel (SURVEY §2.3 entity sharding).

Convergence semantics follow ml/optimization/Optimizer.scala:156-170:
relative function-value change vs |f0| and gradient norm vs ||g0||.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimization.convergence import (
    ConvergenceReason,
    OptimizerResult,
)

Array = jax.Array

_CAUTIOUS_EPS = 1e-10


class _LBFGSHistory(NamedTuple):
    s: Array  # [m, d] chronological: oldest first, newest at m-1
    y: Array  # [m, d]
    rho: Array  # [m]; 0 marks an empty (not yet filled) slot
    count: Array  # i32 number of valid pairs


def _empty_history(d: int, m: int, dtype) -> _LBFGSHistory:
    return _LBFGSHistory(
        s=jnp.zeros((m, d), dtype),
        y=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        count=jnp.zeros((), jnp.int32),
    )


def two_loop_direction(g: Array, hist: _LBFGSHistory) -> Array:
    """-H_k g via the standard two-loop recursion (reference
    implementation, kept as the oracle for compact_direction's tests).

    Slots with rho == 0 contribute nothing, so partial histories need no
    special casing. The recursion is UNROLLED (m is static).
    """
    m = hist.rho.shape[0]

    q = g
    alphas = []
    for j in reversed(range(m)):  # newest (m-1) -> oldest
        alpha = hist.rho[j] * jnp.vdot(hist.s[j], q)
        q = q - alpha * hist.y[j]
        alphas.append(alpha)
    alphas.reverse()  # alphas[j] now matches slot j

    # Initial Hessian scaling from the newest pair: gamma = s.y / y.y.
    yy = jnp.vdot(hist.y[-1], hist.y[-1])
    sy = jnp.vdot(hist.s[-1], hist.y[-1])
    gamma = jnp.where(hist.count > 0, sy / jnp.maximum(yy, _CAUTIOUS_EPS),
                      jnp.ones((), g.dtype))
    r = gamma * q

    for j in range(m):  # oldest -> newest
        beta = hist.rho[j] * jnp.vdot(hist.y[j], r)
        r = r + (alphas[j] - beta) * hist.s[j]
    return -r


def compact_direction(g: Array, hist: _LBFGSHistory) -> Array:
    """-H_k g via the Byrd–Nocedal–Schnabel compact representation
    (Nocedal & Wright, eq. 7.24):

        H = γI + [S  γY] [[R⁻ᵀ(D + γYᵀY)R⁻¹, -R⁻ᵀ], [-R⁻¹, 0]] [Sᵀ; γYᵀ]

    with R = triu(SᵀY), D = diag(SᵀY), pairs ordered oldest-first. This
    is algebraically identical to the two-loop recursion, but costs two
    [m, d] contractions, two m×m triangular solves, and one [m, d]
    recombination — ~10 XLA ops instead of the two-loop's 4m-deep
    dependent dot/axpy chain (~60 ops at m=10). Under ``vmap`` over
    thousands of entities every op is launch-latency-bound, so op count
    is the entire cost: this cut the random-effect bucket solve ~3x
    (measured, TPU v5e; the reference's per-entity Breeze solves have no
    analog — each entity pays the full recursion,
    ml/optimization/LBFGS.scala:42-156).

    Empty slots (rho == 0, s = y = 0) get a unit diagonal in R; their
    rows of a, b, D, YᵀY are zero, so both triangular solves return
    exact zeros there and the slots contribute nothing — same
    no-special-casing property as the two-loop.
    """
    from jax.scipy.linalg import solve_triangular

    S, Y = hist.s, hist.y  # [m, d], oldest first
    valid = hist.rho != 0
    sty = S @ Y.T  # [m, m]
    a = S @ g
    b = Y @ g
    diag = jnp.diagonal(sty)
    yy = jnp.vdot(Y[-1], Y[-1])
    gamma = jnp.where(hist.count > 0,
                      diag[-1] / jnp.maximum(yy, _CAUTIOUS_EPS),
                      jnp.ones((), g.dtype))
    r_mat = jnp.triu(sty) + jnp.diag(jnp.where(valid, 0.0, 1.0)
                                     .astype(sty.dtype))
    p1 = solve_triangular(r_mat, a, lower=False)
    rhs = (diag * p1) + gamma * (Y @ (Y.T @ p1)) - gamma * b
    p2 = solve_triangular(r_mat.T, rhs, lower=True)
    hg = gamma * g + S.T @ p2 - gamma * (Y.T @ p1)
    return -hg


def update_history(hist: _LBFGSHistory, s: Array, y: Array) -> _LBFGSHistory:
    """Cautious update: store (s, y) only when curvature s.y is safely
    positive. Storage shifts left (oldest drops off slot 0, newest lands
    in slot m-1) — chronological order is an invariant, which is what
    lets compact_direction use plain triu/diag instead of circular
    gathers."""
    sy = jnp.vdot(s, y)
    s_norm = jnp.linalg.norm(s)
    y_norm = jnp.linalg.norm(y)
    ok = sy > _CAUTIOUS_EPS * s_norm * y_norm
    m = hist.rho.shape[0]

    def store(h):
        return _LBFGSHistory(
            s=jnp.concatenate([h.s[1:], s[None]]),
            y=jnp.concatenate([h.y[1:], y[None]]),
            rho=jnp.concatenate([h.rho[1:], (1.0 / sy)[None]]),
            count=jnp.minimum(h.count + 1, m),
        )

    return jax.tree.map(
        lambda a, b: jnp.where(ok, a, b), store(hist), hist
    )


class _LoopState(NamedTuple):
    x: Array
    f: Array
    g: Array
    hist: _LBFGSHistory
    it: Array
    reason: Array
    value_hist: Array
    gnorm_hist: Array
    coef_hist: Optional[Array]  # [max_iter+1, d] when tracking, else None


def _project(x: Array, lower: Optional[Array], upper: Optional[Array]) -> Array:
    if lower is not None:
        x = jnp.maximum(x, lower)
    if upper is not None:
        x = jnp.minimum(x, upper)
    return x


def backtracking_line_search(
    vg: Callable[..., Tuple[Array, Array]],
    x: Array,
    f: Array,
    decrease_grad: Array,
    direction: Array,
    args: Tuple,
    *,
    initial_step: Array,
    c1: float,
    max_steps: int,
    project_fn: Callable[[Array], Array],
    shrink: float = 0.5,
):
    """Armijo backtracking on the projected step. Shared by L-BFGS (box
    projection, raw gradient) and OWL-QN (orthant projection, pseudo-gradient
    — which passes an l1-augmented ``vg``).

    Returns (ok, x_new, f_new, g_new). Evaluates value+grad per trial — on
    TPU the fused objective makes the extra gradient essentially free, and it
    saves a separate evaluation at the accepted point.
    """
    dtype = x.dtype

    def trial(t):
        x_t = project_fn(x + t * direction)
        f_t, g_t = vg(x_t, *args)
        # Armijo on the realized (projected) displacement.
        armijo = f_t <= f + c1 * jnp.vdot(decrease_grad, x_t - x)
        # Reject non-finite trial values outright.
        armijo = jnp.logical_and(armijo, jnp.isfinite(f_t))
        return armijo, x_t, f_t, g_t

    def cond(state):
        ok, _, _, _, k, _ = state
        return jnp.logical_and(~ok, k < max_steps)

    def body(state):
        _, _, _, _, k, t = state
        t = t * shrink
        ok, x_t, f_t, g_t = trial(t)
        return ok, x_t, f_t, g_t, k + 1, t

    ok0, x0_t, f0_t, g0_t = trial(initial_step)
    ok, x_new, f_new, g_new, _, _ = lax.while_loop(
        cond, body, (ok0, x0_t, f0_t, g0_t, jnp.zeros((), jnp.int32),
                     jnp.asarray(initial_step, dtype)),
    )
    return ok, x_new, f_new, g_new


@functools.partial(
    jax.jit,
    static_argnames=(
        "fun", "max_iter", "tol", "history_size", "c1", "max_line_search",
        "has_bounds", "track_coefficients",
    ),
)
def _minimize_lbfgs_impl(
    fun, x0, args, lower, upper, *, max_iter, tol, history_size, c1,
    max_line_search, has_bounds, track_coefficients=False,
) -> OptimizerResult:
    vg = jax.value_and_grad(fun)
    dtype = x0.dtype
    d = x0.shape[-1]
    lo = lower if has_bounds else None
    hi = upper if has_bounds else None

    x0 = _project(x0, lo, hi)
    f0, g0 = vg(x0, *args)
    gnorm0 = jnp.linalg.norm(g0)
    f0_scale = jnp.maximum(jnp.abs(f0), jnp.asarray(1e-30, dtype))

    value_hist = jnp.full((max_iter + 1,), jnp.nan, dtype).at[0].set(f0)
    gnorm_hist = jnp.full((max_iter + 1,), jnp.nan, dtype).at[0].set(gnorm0)
    # NaN sentinel, like the value/gnorm histories: unwritten trailing rows
    # are self-identifying rather than masquerading as zero iterates.
    coef_hist = (jnp.full((max_iter + 1, d), jnp.nan, dtype).at[0].set(x0)
                 if track_coefficients else None)

    init = _LoopState(
        x=x0, f=f0, g=g0, hist=_empty_history(d, history_size, dtype),
        it=jnp.zeros((), jnp.int32),
        reason=jnp.full((), int(ConvergenceReason.NOT_CONVERGED), jnp.int32),
        value_hist=value_hist, gnorm_hist=gnorm_hist, coef_hist=coef_hist,
    )

    def cond(st: _LoopState):
        return st.reason == int(ConvergenceReason.NOT_CONVERGED)

    def body(st: _LoopState):
        direction = compact_direction(st.g, st.hist)
        dg = jnp.vdot(direction, st.g)
        # Fall back to steepest descent if the two-loop direction is not a
        # descent direction (can happen right after cautious-skipped updates).
        use_sd = dg >= 0
        direction = jnp.where(use_sd, -st.g, direction)

        first = st.hist.count == 0
        init_step = jnp.where(
            first,
            1.0 / jnp.maximum(jnp.linalg.norm(direction), 1.0),
            jnp.ones((), dtype),
        )
        ok, x_new, f_new, g_new = backtracking_line_search(
            vg, st.x, st.f, st.g, direction, args,
            initial_step=init_step, c1=c1, max_steps=max_line_search,
            project_fn=lambda z: _project(z, lo, hi),
        )

        hist_new = update_history(st.hist, x_new - st.x, g_new - st.g)
        it_new = st.it + 1

        gnorm_new = jnp.linalg.norm(g_new)
        f_delta = jnp.abs(st.f - f_new)
        reason = jnp.where(
            ~ok,
            int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING),
            jnp.where(
                gnorm_new <= tol * gnorm0,
                int(ConvergenceReason.GRADIENT_CONVERGED),
                jnp.where(
                    f_delta <= tol * f0_scale,
                    int(ConvergenceReason.FUNCTION_VALUES_CONVERGED),
                    jnp.where(
                        it_new >= max_iter,
                        int(ConvergenceReason.MAX_ITERATIONS),
                        int(ConvergenceReason.NOT_CONVERGED),
                    ),
                ),
            ),
        ).astype(jnp.int32)

        # A failed line search must not move the iterate.
        x_new = jnp.where(ok, x_new, st.x)
        f_new = jnp.where(ok, f_new, st.f)
        g_new = jnp.where(ok, g_new, st.g)
        hist_new = jax.tree.map(
            lambda a, b: jnp.where(ok, a, b), hist_new, st.hist
        )

        new = _LoopState(
            x=x_new, f=f_new, g=g_new, hist=hist_new, it=it_new,
            reason=reason,
            value_hist=st.value_hist.at[it_new].set(f_new),
            gnorm_hist=st.gnorm_hist.at[it_new].set(gnorm_new),
            coef_hist=(None if st.coef_hist is None
                       else st.coef_hist.at[it_new].set(x_new)),
        )
        # Freeze lanes that already finished (vmap safety).
        done = ~cond(st)
        return jax.tree.map(lambda a, b: jnp.where(done, a, b), st, new)

    # Degenerate start: already at a stationary point.
    trivial = gnorm0 <= jnp.asarray(0.0, dtype)
    init = init._replace(
        reason=jnp.where(
            trivial, int(ConvergenceReason.GRADIENT_CONVERGED), init.reason
        ).astype(jnp.int32)
    )

    final = lax.while_loop(cond, body, init)
    return OptimizerResult(
        x=final.x, value=final.f, grad_norm=jnp.linalg.norm(final.g),
        iterations=final.it, reason=final.reason,
        value_history=final.value_hist, grad_norm_history=final.gnorm_hist,
        coef_history=final.coef_hist,
    )


def minimize_lbfgs(
    fun: Callable[..., Array],
    x0: Array,
    args: Tuple[Any, ...] = (),
    *,
    max_iter: int = 100,
    tol: float = 1e-7,
    history_size: int = 10,
    lower_bounds: Optional[Array] = None,
    upper_bounds: Optional[Array] = None,
    c1: float = 1e-4,
    max_line_search: int = 30,
    track_coefficients: bool = False,
) -> OptimizerResult:
    """Minimize ``fun(x, *args)`` from ``x0``.

    Defaults mirror the reference (maxIter=100, tol=1e-7, m=10;
    ml/optimization/LBFGS.scala:152-156).

    ``fun`` must be a pure jnp scalar function. For the distributed mode pass
    sharded ``args``; for batched per-entity solves wrap with ``jax.vmap``.
    ``track_coefficients`` records per-iteration coefficient snapshots in
    ``result.coef_history`` (costs an extra [max_iter+1, d] buffer).
    """
    dtype = jnp.asarray(x0).dtype
    has_bounds = lower_bounds is not None or upper_bounds is not None
    d = jnp.asarray(x0).shape[-1]
    neg_inf = jnp.full((d,), -jnp.inf, dtype)
    pos_inf = jnp.full((d,), jnp.inf, dtype)
    lo = neg_inf if lower_bounds is None else jnp.asarray(lower_bounds, dtype)
    hi = pos_inf if upper_bounds is None else jnp.asarray(upper_bounds, dtype)
    return _minimize_lbfgs_impl(
        fun, jnp.asarray(x0), args, lo, hi,
        max_iter=max_iter, tol=tol, history_size=history_size, c1=c1,
        max_line_search=max_line_search, has_bounds=has_bounds,
        track_coefficients=track_coefficients,
    )
