"""Convergence reasons and optimizer results.

Semantics mirror the reference's Optimizer template
(ml/optimization/Optimizer.scala:156-170, ml/util/ConvergenceReason.scala):
an optimizer stops when
  - iteration count hits max_iter                        -> MAX_ITERATIONS
  - |f_k - f_{k-1}| <= tol * |f_0|                       -> FUNCTION_VALUES_CONVERGED
  - ||g_k|| <= tol * ||g_0||                             -> GRADIENT_CONVERGED
  - the line search / trust region cannot improve        -> OBJECTIVE_NOT_IMPROVING

Reasons are small ints so they live inside jitted state and vmap lanes.
"""

from __future__ import annotations

import dataclasses
import enum
import math
import threading
from collections import deque
from typing import Optional

import jax

Array = jax.Array


class ConvergenceRing:
    """Bounded per-outer-iteration solver history ring (loss, gradient
    norm, accepted step size) — the live-observable complement of
    :class:`OptimizerResult`'s padded history arrays.

    The host-driven streaming solvers (optimization/glm_lbfgs.py /
    tron.py ``convergence_ring=``) append one entry per outer iteration
    as it happens, so a multi-hour ``--stream-train --distmon`` run's
    /distz shows each λ-grid point's convergence tail LIVE; the fused
    ``lax.while_loop`` solvers cannot (no host callbacks mid-solve) and
    get their rings populated post-hoc from the result histories
    (data/distmon.py ``ring_from_history`` — ``step`` is None there).
    Bounded: only the newest ``capacity`` entries are retained
    (``recorded`` counts all appends). Lock-guarded: the solver thread
    appends while scrape threads snapshot."""

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.recorded = 0
        self._entries: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    def append(self, iteration: int, value, grad_norm,
               step=None) -> None:
        entry = {
            "iteration": int(iteration),
            "value": float(value),
            "grad_norm": float(grad_norm),
            "step": None if step is None else float(step),
        }
        with self._lock:
            self.recorded += 1
            self._entries.append(entry)

    def snapshot(self) -> dict:
        with self._lock:
            return {"capacity": self.capacity,
                    "recorded": self.recorded,
                    "tail": [dict(e) for e in self._entries]}


def check_solver_finite(solver: str, iteration: int, value, grad_norm,
                        trace_ctx=None, *, lam=None,
                        grid_row=None) -> None:
    """Divergence watchdog for the host-driven streaming solvers: raise
    :class:`SolverDivergedError` when loss or gradient norm went
    non-finite. ``value``/``grad_norm`` must already be HOST scalars
    (the streamed outer loops hold them for convergence compares, so
    the check adds no device sync). ``trace_ctx`` — the solve's trace
    context, finished as ``diverged`` (tail-kept) and its id attached
    to the fault so the flight dump is tagged with it. The batched
    λ-grid solvers pass ``lam``/``grid_row`` so the fault names the ONE
    grid row that went non-finite (row-isolated divergence — the other
    rows' masks are untouched when the caller handles the fault)."""
    v, g = float(value), float(grad_norm)
    if math.isfinite(v) and math.isfinite(g):
        return
    trace_id = None
    if trace_ctx is not None:
        trace_id = trace_ctx.trace_id
        trace_ctx.annotate(solver=solver, iteration=int(iteration),
                           value=v, grad_norm=g)
        if lam is not None:
            trace_ctx.annotate(reg_weight=float(lam))
        if grid_row is not None:
            trace_ctx.annotate(grid_row=int(grid_row))
        trace_ctx.finish("diverged")
    raise SolverDivergedError(solver, iteration, v, g, trace_id=trace_id,
                              lam=lam, grid_row=grid_row)


class SolverDivergedError(RuntimeError):
    """A host-driven streaming solver observed a non-finite loss or
    gradient norm — the divergence watchdog's typed fault.

    The fused ``lax.while_loop`` solvers cannot raise mid-solve (a NaN
    silently rides the history arrays to a convergence-failure reason);
    the streamed L-BFGS/TRON outer loops run on the HOST, so they check
    every outer iteration and fail fast with the evidence attached:
    which solver, which iteration, the offending value/grad-norm, and
    the solve's trace_id (telemetry/tracectx.py) so the driver's flight
    dump — which this fault triggers like any other unhandled driver
    exception — is tagged with a resolvable timeline."""

    def __init__(self, solver: str, iteration: int, value, grad_norm,
                 trace_id: Optional[str] = None, lam=None, grid_row=None):
        where = ""
        if grid_row is not None:
            where = f" [grid row {int(grid_row)}"
            if lam is not None:
                where += f", l2={float(lam)!r}"
            where += "]"
        elif lam is not None:
            where = f" [l2={float(lam)!r}]"
        super().__init__(
            f"{solver} diverged at outer iteration {iteration}{where}: "
            f"value={value!r}, grad_norm={grad_norm!r} (non-finite). "
            "Typical causes: learning-rate/regularization far off scale, "
            "corrupt feature values, or an overflowing loss; see the "
            "flight dump for the solve's last stages"
            + (f" (trace {trace_id})" if trace_id else ""))
        self.solver = solver
        self.iteration = int(iteration)
        self.value = value
        self.grad_norm = grad_norm
        self.trace_id = trace_id
        # Batched λ-grid provenance: the ONE row that diverged (other
        # rows' masks are not poisoned — the caller may drop the row and
        # continue, or fail the sweep with this evidence attached).
        self.lam = None if lam is None else float(lam)
        self.grid_row = None if grid_row is None else int(grid_row)


class ConvergenceReason(enum.IntEnum):
    NOT_CONVERGED = 0
    MAX_ITERATIONS = 1
    FUNCTION_VALUES_CONVERGED = 2
    GRADIENT_CONVERGED = 3
    OBJECTIVE_NOT_IMPROVING = 4

    @property
    def summary(self) -> str:
        return {
            ConvergenceReason.NOT_CONVERGED: "not converged",
            ConvergenceReason.MAX_ITERATIONS: "max iterations reached",
            ConvergenceReason.FUNCTION_VALUES_CONVERGED:
                "objective function values converged",
            ConvergenceReason.GRADIENT_CONVERGED: "gradient converged",
            ConvergenceReason.OBJECTIVE_NOT_IMPROVING:
                "objective is not improving",
        }[self]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class OptimizerResult:
    """Solution + telemetry. Fully array-valued, so it vmaps/shards cleanly.

    The per-iteration ``value_history``/``grad_norm_history`` arrays (padded
    to max_iter+1, valid up to ``iterations``) are the TPU replacement for the
    reference's OptimizationStatesTracker ring
    (ml/optimization/OptimizationStatesTracker.scala).
    """

    x: Array
    value: Array
    grad_norm: Array
    iterations: Array  # i32
    reason: Array  # i32, a ConvergenceReason value
    value_history: Array
    grad_norm_history: Array
    # Per-iteration coefficient snapshots [max_iter+1, d], recorded only when
    # the solver was asked to track them (the reference's ModelTracker state,
    # ml/supervised/model/ModelTracker.scala). None otherwise.
    coef_history: Optional[Array] = None

    @property
    def converged(self) -> Array:
        return self.reason != int(ConvergenceReason.NOT_CONVERGED)

    def reason_enum(self) -> ConvergenceReason:
        return ConvergenceReason(int(self.reason))

    def tree_flatten(self):
        return (
            self.x, self.value, self.grad_norm, self.iterations, self.reason,
            self.value_history, self.grad_norm_history, self.coef_history,
        ), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)
