"""Convergence reasons and optimizer results.

Semantics mirror the reference's Optimizer template
(ml/optimization/Optimizer.scala:156-170, ml/util/ConvergenceReason.scala):
an optimizer stops when
  - iteration count hits max_iter                        -> MAX_ITERATIONS
  - |f_k - f_{k-1}| <= tol * |f_0|                       -> FUNCTION_VALUES_CONVERGED
  - ||g_k|| <= tol * ||g_0||                             -> GRADIENT_CONVERGED
  - the line search / trust region cannot improve        -> OBJECTIVE_NOT_IMPROVING

Reasons are small ints so they live inside jitted state and vmap lanes.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import jax

Array = jax.Array


class ConvergenceReason(enum.IntEnum):
    NOT_CONVERGED = 0
    MAX_ITERATIONS = 1
    FUNCTION_VALUES_CONVERGED = 2
    GRADIENT_CONVERGED = 3
    OBJECTIVE_NOT_IMPROVING = 4

    @property
    def summary(self) -> str:
        return {
            ConvergenceReason.NOT_CONVERGED: "not converged",
            ConvergenceReason.MAX_ITERATIONS: "max iterations reached",
            ConvergenceReason.FUNCTION_VALUES_CONVERGED:
                "objective function values converged",
            ConvergenceReason.GRADIENT_CONVERGED: "gradient converged",
            ConvergenceReason.OBJECTIVE_NOT_IMPROVING:
                "objective is not improving",
        }[self]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class OptimizerResult:
    """Solution + telemetry. Fully array-valued, so it vmaps/shards cleanly.

    The per-iteration ``value_history``/``grad_norm_history`` arrays (padded
    to max_iter+1, valid up to ``iterations``) are the TPU replacement for the
    reference's OptimizationStatesTracker ring
    (ml/optimization/OptimizationStatesTracker.scala).
    """

    x: Array
    value: Array
    grad_norm: Array
    iterations: Array  # i32
    reason: Array  # i32, a ConvergenceReason value
    value_history: Array
    grad_norm_history: Array
    # Per-iteration coefficient snapshots [max_iter+1, d], recorded only when
    # the solver was asked to track them (the reference's ModelTracker state,
    # ml/supervised/model/ModelTracker.scala). None otherwise.
    coef_history: Optional[Array] = None

    @property
    def converged(self) -> Array:
        return self.reason != int(ConvergenceReason.NOT_CONVERGED)

    def reason_enum(self) -> ConvergenceReason:
        return ConvergenceReason(int(self.reason))

    def tree_flatten(self):
        return (
            self.x, self.value, self.grad_norm, self.iterations, self.reason,
            self.value_history, self.grad_norm_history, self.coef_history,
        ), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)
