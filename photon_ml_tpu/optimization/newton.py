"""Damped (Levenberg-style) exact Newton for SMALL dense problems.

The TPU fast path for the per-entity random-effect solves: after feature
selection/projection, entity problems have d of order 8-64
(RandomEffectDataConfiguration.num_features_to_samples_ratio caps them,
reference ml/data/RandomEffectDataSet.scala:380-394). At those sizes the
exact Hessian is a tiny matrix and a direct solve replaces both the L-BFGS
two-loop recursion and TRON's inner CG — the same trust-region-Newton family
as the reference's TRON (ml/optimization/TRON.scala), with the truncated CG
degenerating to an exact solve because the full system fits in registers.

Why it's faster on TPU: one vmapped iteration is ~6 fused batched ops
(Hessian einsum, add damping, linalg.solve, objective eval, compares)
instead of the hundreds of sequential micro-ops a batched L-BFGS iteration
issues (two-loop fori, line-search while) — under `vmap` over thousands of
entities the op-dispatch depth, not FLOPs, is the bottleneck.

Damping loop per iteration (branch-free, masked for vmap):
  step = -(H + damping I)^{-1} g; accept if f decreases (damping shrinks),
  else reject and grow damping — the Levenberg analog of TRON's
  trust-region radius update (TRON.scala:153-255).

Convergence semantics follow ml/optimization/Optimizer.scala:156-170,
identical to lbfgs.py/tron.py.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimization.convergence import (
    ConvergenceReason,
    OptimizerResult,
)
from photon_ml_tpu.optimization.lbfgs import _project

Array = jax.Array

_DAMP_INIT = 1e-4
_DAMP_SHRINK = 0.3
_DAMP_GROW = 10.0
_DAMP_MAX = 1e10


class _NewtonState(NamedTuple):
    x: Array
    f: Array
    g: Array
    damping: Array
    it: Array  # accepted iterations
    fails: Array  # consecutive rejected steps
    reason: Array
    value_hist: Array
    gnorm_hist: Array
    coef_hist: Optional[Array]


@functools.partial(
    jax.jit,
    static_argnames=("fun", "max_iter", "tol", "max_improvement_failures",
                     "has_bounds", "track_coefficients"),
)
def _minimize_newton_impl(
    fun, x0, args, lower, upper, *, max_iter, tol,
    max_improvement_failures, has_bounds, track_coefficients=False,
) -> OptimizerResult:
    vg = jax.value_and_grad(fun)
    hess = jax.hessian(fun)
    dtype = x0.dtype
    d = x0.shape[-1]
    lo = lower if has_bounds else None
    hi = upper if has_bounds else None

    x0 = _project(x0, lo, hi)
    f0, g0 = vg(x0, *args)
    gnorm0 = jnp.linalg.norm(g0)
    f0_scale = jnp.maximum(jnp.abs(f0), jnp.asarray(1e-30, dtype))

    value_hist = jnp.full((max_iter + 1,), jnp.nan, dtype).at[0].set(f0)
    gnorm_hist = jnp.full((max_iter + 1,), jnp.nan, dtype).at[0].set(gnorm0)
    coef_hist = (jnp.full((max_iter + 1, d), jnp.nan, dtype).at[0].set(x0)
                 if track_coefficients else None)

    init = _NewtonState(
        x=x0, f=f0, g=g0,
        damping=jnp.asarray(_DAMP_INIT, dtype),
        it=jnp.zeros((), jnp.int32), fails=jnp.zeros((), jnp.int32),
        reason=jnp.where(
            gnorm0 <= 0.0, int(ConvergenceReason.GRADIENT_CONVERGED),
            int(ConvergenceReason.NOT_CONVERGED)).astype(jnp.int32),
        value_hist=value_hist, gnorm_hist=gnorm_hist, coef_hist=coef_hist,
    )

    eye = jnp.eye(d, dtype=dtype)

    def cond(st: _NewtonState):
        return st.reason == int(ConvergenceReason.NOT_CONVERGED)

    def body(st: _NewtonState):
        H = hess(st.x, *args)
        step = -jnp.linalg.solve(H + st.damping * eye, st.g)
        # A singular/indefinite system yields non-finite entries; treat as a
        # rejected step (damping grows until H + damping I is safely PD).
        step_ok = jnp.all(jnp.isfinite(step))
        x_try = _project(
            st.x + jnp.where(step_ok, step, jnp.zeros_like(step)), lo, hi)
        f_new, g_new = vg(x_try, *args)

        accept = jnp.logical_and(
            jnp.logical_and(step_ok, jnp.isfinite(f_new)), f_new < st.f)
        damping = jnp.where(
            accept,
            jnp.maximum(st.damping * _DAMP_SHRINK, 1e-12),
            jnp.minimum(st.damping * _DAMP_GROW, _DAMP_MAX))
        it_new = st.it + jnp.where(accept, 1, 0).astype(jnp.int32)
        fails_new = jnp.where(accept, 0, st.fails + 1).astype(jnp.int32)

        x_acc = jnp.where(accept, x_try, st.x)
        f_acc = jnp.where(accept, f_new, st.f)
        g_acc = jnp.where(accept, g_new, st.g)
        gnorm_acc = jnp.linalg.norm(g_acc)
        f_delta = jnp.abs(st.f - f_acc)

        reason = jnp.where(
            fails_new > max_improvement_failures,
            int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING),
            jnp.where(
                jnp.logical_and(accept, gnorm_acc <= tol * gnorm0),
                int(ConvergenceReason.GRADIENT_CONVERGED),
                jnp.where(
                    jnp.logical_and(accept, f_delta <= tol * f0_scale),
                    int(ConvergenceReason.FUNCTION_VALUES_CONVERGED),
                    jnp.where(
                        it_new >= max_iter,
                        int(ConvergenceReason.MAX_ITERATIONS),
                        int(ConvergenceReason.NOT_CONVERGED)))),
        ).astype(jnp.int32)

        new = _NewtonState(
            x=x_acc, f=f_acc, g=g_acc, damping=damping, it=it_new,
            fails=fails_new, reason=reason,
            value_hist=jnp.where(
                accept, st.value_hist.at[it_new].set(f_acc), st.value_hist),
            gnorm_hist=jnp.where(
                accept, st.gnorm_hist.at[it_new].set(gnorm_acc),
                st.gnorm_hist),
            coef_hist=(None if st.coef_hist is None
                       else jnp.where(
                           accept, st.coef_hist.at[it_new].set(x_acc),
                           st.coef_hist)),
        )
        done = ~cond(st)
        return jax.tree.map(lambda a, b: jnp.where(done, a, b), st, new)

    final = lax.while_loop(cond, body, init)
    return OptimizerResult(
        x=final.x, value=final.f, grad_norm=jnp.linalg.norm(final.g),
        iterations=final.it, reason=final.reason,
        value_history=final.value_hist, grad_norm_history=final.gnorm_hist,
        coef_history=final.coef_hist,
    )


def minimize_newton(
    fun: Callable[..., Array],
    x0: Array,
    args: Tuple[Any, ...] = (),
    *,
    max_iter: int = 15,
    tol: float = 1e-5,
    max_improvement_failures: int = 25,
    lower_bounds: Optional[Array] = None,
    upper_bounds: Optional[Array] = None,
    track_coefficients: bool = False,
) -> OptimizerResult:
    """Minimize twice-differentiable ``fun(x, *args)`` from ``x0`` with
    damped exact Newton. Intended for small d (the full Hessian is
    materialized). NOT auto-routed by `solve_glm`: batched tiny
    `linalg.solve` lowers to slow unrolled LU on TPU (measured far slower
    than the vmapped L-BFGS there) — use explicitly, e.g. for CPU f64
    solves. Defaults mirror TRON's budget (maxIter=15, tol=1e-5;
    ml/optimization/TRON.scala:258-264). max_improvement_failures is higher
    than TRON's because a rejected damped step is much cheaper than a
    rejected trust-region step (no CG inside).
    """
    x0 = jnp.asarray(x0)
    dtype = x0.dtype
    has_bounds = lower_bounds is not None or upper_bounds is not None
    d = x0.shape[-1]
    lo = (jnp.full((d,), -jnp.inf, dtype) if lower_bounds is None
          else jnp.asarray(lower_bounds, dtype))
    hi = (jnp.full((d,), jnp.inf, dtype) if upper_bounds is None
          else jnp.asarray(upper_bounds, dtype))
    return _minimize_newton_impl(
        fun, x0, args, lo, hi, max_iter=max_iter, tol=tol,
        max_improvement_failures=max_improvement_failures,
        has_bounds=has_bounds, track_coefficients=track_coefficients,
    )
