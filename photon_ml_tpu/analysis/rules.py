"""jaxlint rules — each targets one way a JAX tree silently gets slow.

Every rule has a stable kebab-case id (used in ``# jaxlint:
disable=<rule>`` suppressions and baseline fingerprints), a one-line
``doc`` for ``--list-rules``, and a ``check(mod, project)`` returning
Violations. docs/ANALYSIS.md carries the full catalog with before/after
examples.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from photon_ml_tpu.analysis.core import (
    ModuleSource,
    Project,
    Violation,
    _jit_decorator_statics,
    is_jit_reference,
)

# Modules whose code runs on the device hot path: host-sync and
# dtype-drift findings here cost real dispatches / break f32 parity.
DEVICE_DIRS = (
    "photon_ml_tpu/ops/",
    "photon_ml_tpu/serving/",
    "photon_ml_tpu/optimization/",
    "photon_ml_tpu/algorithm/",
)


def _in_device_dir(mod: ModuleSource) -> bool:
    p = "/" + mod.path
    return any("/" + d in p for d in DEVICE_DIRS)


def _enclosing_scope_nodes(mod: ModuleSource, node: ast.AST) -> Set[ast.AST]:
    out: Set[ast.AST] = set()
    fi = mod.fn_of.get(node)
    while fi is not None:
        out.add(fi.node)
        fi = fi.parent
    return out


class RetraceHazardRule:
    """Per-call recompilation: the single most expensive silent failure —
    every retrace costs a full XLA compile (seconds) on what should be a
    cached microsecond dispatch."""

    id = "retrace-hazard"
    doc = ("lambda/locally-defined function in a static_argnames position, "
           "or jax.jit built inside a function and invoked without caching")

    def check(self, mod: ModuleSource, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                out += self._check_static_args(mod, project, node)
                out += self._check_per_call_jit(mod, node)
        return [v for v in out if v is not None]

    # -- (a) unstable callables in static positions ------------------------

    def _resolve_sig(self, mod: ModuleSource, project: Project,
                     call: ast.Call):
        f = call.func
        if isinstance(f, ast.Name):
            fq = mod.imports.get(f.id, f"{mod.module_name}.{f.id}")
            return project.jit_sigs.get(fq)
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            target = mod.imports.get(f.value.id)
            if target is not None:
                return project.jit_sigs.get(f"{target}.{f.attr}")
        return None

    def _unstable_callable(self, mod: ModuleSource, call: ast.Call,
                           value: ast.AST) -> Optional[str]:
        """'lambda' / 'locally-defined function <n>' when ``value`` is a
        fresh function object per call of the enclosing scope; None for
        stable references (module-level defs, attributes/bound methods —
        those hash stably for a persistent owner)."""
        if isinstance(value, ast.Lambda):
            return "a lambda"
        if isinstance(value, ast.Name):
            scopes = _enclosing_scope_nodes(mod, call)
            for fi in mod.functions:
                if fi.name == value.id and fi.parent is not None \
                        and fi.parent.node in scopes:
                    return f"locally-defined function {value.id!r}"
        return None

    def _check_static_args(self, mod: ModuleSource, project: Project,
                           call: ast.Call) -> list:
        sig = self._resolve_sig(mod, project, call)
        if sig is None:
            return []
        out = []
        for kw in call.keywords:
            if kw.arg is None or not (
                    kw.arg in sig.static_names
                    or (sig.params is not None and kw.arg in sig.params
                        and sig.params.index(kw.arg) in sig.static_nums)):
                continue
            what = self._unstable_callable(mod, call, kw.value)
            if what:
                out.append(mod.violation(
                    kw.value, self.id,
                    f"{what} passed as static arg {kw.arg!r} of "
                    f"{sig.name} (jit at {sig.where}): a fresh function "
                    "object per call defeats the jit cache — pass a "
                    "module-level function or a bound method of a "
                    "persistent object"))
        for idx, arg in enumerate(call.args):
            pname = sig.static_param_at(idx)
            if pname is None:
                continue
            what = self._unstable_callable(mod, call, arg)
            if what:
                out.append(mod.violation(
                    arg, self.id,
                    f"{what} passed as static arg {pname!r} of "
                    f"{sig.name} (jit at {sig.where}): a fresh function "
                    "object per call defeats the jit cache"))
        return out

    # -- (b) per-call jax.jit construction ---------------------------------

    def _check_per_call_jit(self, mod: ModuleSource,
                            call: ast.Call) -> list:
        if not (is_jit_reference(call.func) and mod.fn_of.get(call)):
            return []
        parent = mod.parents.get(call)
        # jax.jit(f)(x): constructed and invoked in one expression.
        if isinstance(parent, ast.Call) and parent.func is call:
            return [mod.violation(
                call, self.id,
                "jax.jit(...) constructed and called in the same "
                "expression inside a function: this retraces and "
                "recompiles on EVERY call — hoist the jit to module "
                "scope or cache the wrapped function")]
        # fn = jax.jit(f) ... fn(x), with fn never escaping the function.
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], ast.Name):
            name = parent.targets[0].id
            if self._only_called_locally(mod, call, name):
                return [mod.violation(
                    call, self.id,
                    f"jax.jit result {name!r} is built and called inside "
                    "this function but never cached (not returned or "
                    "stored): it recompiles on every call of the "
                    "enclosing function")]
        return []

    def _only_called_locally(self, mod: ModuleSource, call: ast.Call,
                             name: str) -> bool:
        fi = mod.fn_of.get(call)
        called = False
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Name) and node.id == name
                    and isinstance(node.ctx, ast.Load)):
                continue
            parent = mod.parents.get(node)
            if isinstance(parent, ast.Call) and parent.func is node:
                called = True
            else:
                return False  # escapes: returned / stored / passed on
        return called


class HostSyncRule:
    """Host-device synchronization inside traced code: a concretization
    of a tracer either crashes the trace or (worse) silently pins a
    value at trace time."""

    id = "host-sync"
    doc = (".item()/float()/int()/np.asarray/block_until_ready applied "
           "inside jit-reachable code in device-path modules")

    def check(self, mod: ModuleSource, project: Project) -> List[Violation]:
        if not _in_device_dir(mod):
            return []
        out: List[Violation] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not project.in_traced_code(mod, node):
                continue
            v = self._check_call(mod, node)
            if v is not None:
                out.append(v)
        return out

    def _static_names_of_scope(self, mod: ModuleSource,
                               node: ast.AST) -> Set[str]:
        names: Set[str] = set()
        fi = mod.fn_of.get(node)
        while fi is not None:
            if isinstance(fi.node, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                for dec in fi.node.decorator_list:
                    statics = _jit_decorator_statics(dec)
                    if statics is not None:
                        names |= statics[0]
            fi = fi.parent
        return names

    def _check_call(self, mod: ModuleSource,
                    call: ast.Call) -> Optional[Violation]:
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr == "item" and not call.args:
                return mod.violation(
                    call, self.id,
                    ".item() in traced code forces a device->host sync "
                    "(or fails under jit) — keep the value on device, or "
                    "materialize OUTSIDE the jitted region")
            if f.attr in ("block_until_ready", "device_get"):
                return mod.violation(
                    call, self.id,
                    f".{f.attr}() in traced code is a host sync point — "
                    "move it outside the jitted region")
            if isinstance(f.value, ast.Name) \
                    and f.value.id in mod.numpy_aliases \
                    and f.attr in ("asarray", "array"):
                return mod.violation(
                    call, self.id,
                    f"np.{f.attr}(...) in traced code materializes the "
                    "operand on host — use jnp equivalents so the value "
                    "stays traced")
        elif isinstance(f, ast.Name) and f.id in ("float", "int") \
                and len(call.args) == 1 \
                and isinstance(call.args[0], ast.Name):
            arg = call.args[0].id
            if arg not in self._static_names_of_scope(mod, call):
                return mod.violation(
                    call, self.id,
                    f"{f.id}({arg}) in traced code concretizes its "
                    "operand (host sync; TracerConversionError if it is "
                    "a tracer) — use jnp.asarray/.astype, or mark "
                    f"{arg!r} static if it is a python scalar")
        return None


class DtypeDriftRule:
    """f32 parity (docs/F32_PARITY.md): device-path modules must not bake
    in float64 or rely on the x64-dependent default dtype — the same code
    must produce the same executables in the f32 and f64 CI configs."""

    id = "dtype-drift"
    doc = ("np.float64 or dtype-less jnp.array/jnp.zeros literals in "
           "device-path modules that must stay f32-parity safe")

    # constructor -> index of the positional dtype argument
    _DTYPE_POS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2}

    def check(self, mod: ModuleSource, project: Project) -> List[Violation]:
        if not _in_device_dir(mod):
            return []
        out: List[Violation] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) \
                    and node.attr == "float64" \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in (mod.numpy_aliases
                                          | mod.jnp_aliases):
                v = mod.violation(
                    node, self.id,
                    "hard-coded float64 in a device-path module breaks "
                    "the f32 parity contract — thread a dtype parameter "
                    "through instead")
                if v is not None:
                    out.append(v)
            elif isinstance(node, ast.Call):
                v = self._check_call(mod, node)
                if v is not None:
                    out.append(v)
        return out

    def _is_jnp_call(self, mod: ModuleSource, call: ast.Call,
                     attrs) -> bool:
        f = call.func
        return (isinstance(f, ast.Attribute) and f.attr in attrs
                and isinstance(f.value, ast.Name)
                and f.value.id in mod.jnp_aliases)

    @staticmethod
    def _has_float_literal(node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Constant) and isinstance(n.value, float):
                return True
        return False

    def _check_call(self, mod: ModuleSource,
                    call: ast.Call) -> Optional[Violation]:
        has_dtype_kw = any(kw.arg == "dtype" for kw in call.keywords)
        if self._is_jnp_call(mod, call, self._DTYPE_POS):
            pos = self._DTYPE_POS[call.func.attr]
            if not has_dtype_kw and len(call.args) <= pos:
                return mod.violation(
                    call, self.id,
                    f"jnp.{call.func.attr}(...) without a dtype defaults "
                    "to the x64-flag-dependent float — pass the computed "
                    "dtype explicitly so f32 and f64 configs build the "
                    "same executables")
        elif self._is_jnp_call(mod, call, ("array", "asarray")):
            if not has_dtype_kw and len(call.args) == 1 \
                    and self._has_float_literal(call.args[0]):
                return mod.violation(
                    call, self.id,
                    f"jnp.{call.func.attr} of a float literal without a "
                    "dtype follows the x64 flag (f64 under x64, f32 "
                    "otherwise) — pass dtype explicitly")
        return None


class NondeterministicPytreeRule:
    """Pytree construction from unordered iteration: leaf order becomes
    part of the jit cache key, so a hash-randomized set order means
    spurious retraces across processes and unstable multihost layouts."""

    id = "nondeterministic-pytree"
    doc = ("iterating a set (or building list/tuple from one) where the "
           "resulting order can differ between processes")

    @staticmethod
    def _is_set_expr(node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in ("set", "frozenset"))

    def check(self, mod: ModuleSource, project: Project) -> List[Violation]:
        out: List[Violation] = []
        msg = ("iteration order of a set is not deterministic across "
               "processes — sort it (sorted(...)) before it can shape a "
               "pytree or a cache key")
        for node in ast.walk(mod.tree):
            target = None
            if isinstance(node, ast.For) and self._is_set_expr(node.iter):
                target = node.iter
            elif isinstance(node, ast.comprehension) \
                    and self._is_set_expr(node.iter):
                target = node.iter
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in ("list", "tuple") \
                    and len(node.args) == 1 \
                    and self._is_set_expr(node.args[0]):
                target = node
            if target is not None:
                v = mod.violation(target, self.id, msg)
                if v is not None:
                    out.append(v)
        return out


class TelemetryInTraceRule:
    """Telemetry belongs to the HOST loop: a span opened inside traced
    code measures trace time once and nothing on later dispatches (and a
    registry mutation there runs at trace time, not per call) — both
    silently lie. Device work is attributed at the dispatch boundary via
    the block_until_ready that already exists in host-sync code
    (docs/OBSERVABILITY.md span rules)."""

    id = "telemetry-in-trace"
    doc = ("telemetry span()/timed_span() or metric mutation "
           "(.inc()/.observe()) inside jit-reachable code")

    # photon_ml_tpu.telemetry entry points that open spans / create
    # metrics; resolved through the import table so local helpers named
    # `span` in unrelated modules do not trip the rule.
    _FACTORIES = ("span", "timed_span", "counter", "gauge", "histogram")
    # Metric mutation methods — distinctive enough to flag on name alone
    # (nothing else in the tree defines .inc/.observe).
    _MUTATORS = ("inc", "observe")

    def check(self, mod: ModuleSource, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not project.in_traced_code(mod, node):
                continue
            v = self._check_call(mod, node)
            if v is not None:
                out.append(v)
        return out

    def _check_call(self, mod: ModuleSource,
                    call: ast.Call) -> Optional[Violation]:
        f = call.func
        if isinstance(f, ast.Name):
            fq = mod.imports.get(f.id, "")
            if f.id in self._FACTORIES \
                    and fq.startswith("photon_ml_tpu.telemetry"):
                return mod.violation(
                    call, self.id,
                    f"telemetry {f.id}() opened inside traced code: it "
                    "would measure trace time once and nothing per "
                    "dispatch — instrument the host loop that launches "
                    "the device work (attribute device time at an "
                    "existing block_until_ready boundary)")
        elif isinstance(f, ast.Attribute):
            if f.attr in self._MUTATORS:
                return mod.violation(
                    call, self.id,
                    f".{f.attr}() metric mutation inside traced code "
                    "runs at trace time, not per call — move it to the "
                    "host loop")
            if f.attr in self._FACTORIES and isinstance(f.value, ast.Name):
                target = mod.imports.get(f.value.id, "")
                if target.startswith("photon_ml_tpu.telemetry") \
                        or target == "photon_ml_tpu.telemetry":
                    return mod.violation(
                        call, self.id,
                        f"telemetry {f.attr}() opened inside traced "
                        "code — instrument the host loop instead")
        return None


class SpillDtypeLeakRule:
    """The shard cache's compressed spill tier (data/shard_cache.py)
    holds feature blocks as bf16 values + delta-encoded u8/u16 indices.
    Those buffers are NOT device-kernel data: a `CSRFeatures` built from
    them without the restore cast would silently jit-trace a second
    executable per bucket (dtype is part of the signature) and
    accumulate at the wrong precision — the sharded objective's kernels
    are compiled for f32/i32 (ops/sharded_objective.py, restore-dtype
    contract)."""

    id = "spill-dtype-leak"
    doc = ("spill-encoded buffers (.enc_values/.enc_cols/.enc_rows) "
           "consumed outside data/shard_cache.py's "
           "restore_spilled_features — bf16/delta data would leak into "
           "device kernels un-restored")

    #: SpillBlock's encoded fields — distinctive enough to flag on name.
    _ATTRS = ("enc_values", "enc_cols", "enc_rows")
    #: The blessed consumers, all in data/shard_cache.py: the codec
    #: pair and SpillBlock's own byte accounting.
    _ALLOWED_MODULE = "photon_ml_tpu/data/shard_cache.py"
    _ALLOWED_FNS = ("encode_spill", "restore_spilled_features", "nbytes")

    def check(self, mod: ModuleSource, project: Project) -> List[Violation]:
        p = "/" + mod.path
        if "/photon_ml_tpu/" not in p:
            return []  # tests/bench poke the codec fields legitimately
        allowed_module = p.endswith("/data/shard_cache.py")
        out: List[Violation] = []
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Attribute)
                    and node.attr in self._ATTRS
                    and isinstance(node.ctx, ast.Load)):
                continue
            if allowed_module and self._in_allowed_fn(mod, node):
                continue
            v = mod.violation(
                node, self.id,
                f".{node.attr} is a spill-ENCODED buffer (bf16 values / "
                "delta-coded indices): consuming it outside "
                "data/shard_cache.py restore_spilled_features leaks "
                "non-f32 data into device kernels un-restored — "
                "restore the block through the cache's miss path "
                "instead")
            if v is not None:
                out.append(v)
        return out

    def _in_allowed_fn(self, mod: ModuleSource, node: ast.AST) -> bool:
        fi = mod.fn_of.get(node)
        while fi is not None:
            if fi.name in self._ALLOWED_FNS:
                return True
            fi = fi.parent
        return False


class BlockingInAsyncRule:
    """The serving front-end's event loop IS the product: one blocking
    call inside a coroutine stalls ADMISSION for every connected
    requester — queue-wait spikes for traffic that never touched the
    offending request. Blocking work belongs on the dispatch executor
    thread (``run_in_executor``); waits belong to ``await``."""

    id = "blocking-in-async"
    doc = ("time.sleep / block_until_ready / no-timeout queue .get() "
           "inside an async def body in serving/ — stalls the event "
           "loop for every in-flight request")

    #: Only the serving package hosts event-loop code; elsewhere a sync
    #: sleep on a worker thread is legitimate pipeline behavior. The
    #: network front door grew event loops OUTSIDE serving/ — the
    #: router CLI and the scoring driver's --listen mode run their own
    #: asyncio loops — so those modules are covered file-wise.
    _DIRS = ("photon_ml_tpu/serving/",)
    _FILES = ("photon_ml_tpu/cli/net_router.py",
              "photon_ml_tpu/cli/game_scoring_driver.py")

    def check(self, mod: ModuleSource, project: Project) -> List[Violation]:
        p = "/" + mod.path
        if not (any("/" + d in p for d in self._DIRS)
                or any(p.endswith("/" + f) for f in self._FILES)):
            return []
        out: List[Violation] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not self._in_async_body(mod, node):
                continue
            v = self._check_call(mod, node)
            if v is not None:
                out.append(v)
        return out

    @staticmethod
    def _in_async_body(mod: ModuleSource, node: ast.AST) -> bool:
        """Innermost enclosing real function (lambdas look through to
        their definer — a lambda body runs wherever it is called, and
        one defined in a coroutine usually runs there). EXCEPT a lambda
        handed straight to ``run_in_executor``/``submit``: that body
        runs on an executor thread where blocking is the whole point —
        it is the remediation this rule's messages recommend."""
        fi = mod.fn_of.get(node)
        while fi is not None and isinstance(fi.node, ast.Lambda):
            parent = mod.parents.get(fi.node)
            if isinstance(parent, ast.Call) \
                    and isinstance(parent.func, ast.Attribute) \
                    and parent.func.attr in ("run_in_executor", "submit"):
                return False
            fi = fi.parent
        return fi is not None and isinstance(fi.node, ast.AsyncFunctionDef)

    def _check_call(self, mod: ModuleSource,
                    call: ast.Call) -> Optional[Violation]:
        # An awaited call yields to the loop by construction
        # (await q.get() on an asyncio.Queue is the CORRECT pattern).
        if isinstance(mod.parents.get(call), ast.Await):
            return None
        f = call.func
        if isinstance(f, ast.Name) \
                and mod.imports.get(f.id) == "time.sleep":
            # 'from time import sleep' — same blocking call, bare name.
            return mod.violation(
                call, self.id,
                "time.sleep() inside an async def blocks the whole "
                "event loop (admission, coalescing, every pending "
                "future) — use 'await asyncio.sleep(...)'")
        if isinstance(f, ast.Attribute):
            if f.attr == "sleep" and isinstance(f.value, ast.Name) \
                    and mod.imports.get(f.value.id) == "time":
                return mod.violation(
                    call, self.id,
                    "time.sleep() inside an async def blocks the whole "
                    "event loop (admission, coalescing, every pending "
                    "future) — use 'await asyncio.sleep(...)'")
            if f.attr in ("block_until_ready", "device_get"):
                return mod.violation(
                    call, self.id,
                    f".{f.attr}() inside an async def parks the event "
                    "loop on device completion — dispatch on the "
                    "executor thread (run_in_executor) and await the "
                    "result instead")
            if f.attr == "get" and not call.args \
                    and not any(kw.arg == "timeout"
                                for kw in call.keywords):
                return mod.violation(
                    call, self.id,
                    "argument-less .get() inside an async def reads as "
                    "a synchronous queue.get() that blocks the loop "
                    "until an item arrives — use an asyncio.Queue "
                    "('await q.get()'), or pass timeout= if this really "
                    "is a thread-queue handoff")
        return None


ALL_RULES = (
    RetraceHazardRule(),
    HostSyncRule(),
    DtypeDriftRule(),
    NondeterministicPytreeRule(),
    TelemetryInTraceRule(),
    SpillDtypeLeakRule(),
    BlockingInAsyncRule(),
)

RULE_IDS = tuple(r.id for r in ALL_RULES)
