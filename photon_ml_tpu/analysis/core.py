"""jaxlint core: dependency-free AST analysis infrastructure for JAX
pitfalls.

The style gate (dev_scripts/lint.py) keeps the tree tidy; this package
keeps it FAST — its rules target the failure modes that silently destroy
device performance instead of correctness: per-call recompilation,
host-device sync points on jit-reachable paths, dtype drift breaking the
f32 parity contract (docs/F32_PARITY.md), and compile-cache-key
instability from unordered iteration. Rules live in rules.py; this module
owns the machinery they share:

- parsing + per-module indexes (parent links, enclosing-function map,
  import aliases, inline suppressions);
- a project-wide fixpoint of which functions are TRACE-REACHABLE
  (jit-decorated, passed to jit/pallas_call/lax combinators, nested in or
  called from reachable bodies — including cross-module calls through
  photon_ml_tpu imports);
- the signature index of jit-wrapped entry points and their static
  argument positions (for the retrace-hazard rule);
- the violation/baseline model: fingerprints are line-number-free
  (path :: rule :: scope :: normalized source line) so the checked-in
  baseline survives unrelated edits, and the gate is "no NEW violations".

Suppression syntax, on the violating line:
    something_hazardous()  # jaxlint: disable=host-sync
    other()  # jaxlint: disable=host-sync,dtype-drift
"""

from __future__ import annotations

import ast
import collections
import dataclasses
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

# Names whose call sites trace their function-valued arguments: a function
# passed (by name or as a lambda) into one of these has its body staged
# into jaxpr, so host-sync rules apply inside it. Matched on the terminal
# attribute name (jax.jit / functools.partial(jax.jit, ...) / pl.pallas_call
# / lax.while_loop all land here).
TRACING_CALLS = frozenset({
    "jit", "pallas_call", "scan", "while_loop", "fori_loop", "cond",
    "switch", "vmap", "pmap", "grad", "value_and_grad", "jacfwd", "jacrev",
    "hessian", "checkpoint", "remat", "custom_jvp", "custom_vjp",
    "shard_map",
})

_SUPPRESS_RE = re.compile(r"#\s*jaxlint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding. ``scope`` is the qualified name of the enclosing
    function ('<module>' at top level); the fingerprint deliberately
    excludes the line number so baselines survive unrelated edits."""

    path: str
    line: int
    rule: str
    message: str
    scope: str = "<module>"
    text: str = ""

    @property
    def fingerprint(self) -> str:
        return f"{self.path}::{self.rule}::{self.scope}::{self.text}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass
class FuncInfo:
    """One function-like scope (def / async def / lambda)."""

    node: ast.AST
    name: str
    qualname: str
    parent: Optional["FuncInfo"]


@dataclasses.dataclass
class ModuleSource:
    """A parsed file plus the per-module indexes rules consume."""

    path: str  # repo-relative, forward slashes
    source: str
    tree: ast.Module
    lines: List[str] = dataclasses.field(default_factory=list)
    suppressions: Dict[int, Set[str]] = dataclasses.field(
        default_factory=dict)
    parents: Dict[ast.AST, ast.AST] = dataclasses.field(
        default_factory=dict)
    functions: List[FuncInfo] = dataclasses.field(default_factory=list)
    fn_of: Dict[ast.AST, Optional[FuncInfo]] = dataclasses.field(
        default_factory=dict)
    # import alias -> fully-qualified module/object name
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    numpy_aliases: Set[str] = dataclasses.field(default_factory=set)
    jnp_aliases: Set[str] = dataclasses.field(default_factory=set)

    @property
    def module_name(self) -> str:
        p = self.path[:-3] if self.path.endswith(".py") else self.path
        p = p.replace("/", ".")
        return p[:-len(".__init__")] if p.endswith(".__init__") else p

    def scope_of(self, node: ast.AST) -> str:
        fi = self.fn_of.get(node)
        return fi.qualname if fi is not None else "<module>"

    def suppressed(self, line: int, rule: str) -> bool:
        rules = self.suppressions.get(line)
        return rules is not None and (rule in rules or "all" in rules)

    def violation(self, node: ast.AST, rule: str, message: str
                  ) -> Optional[Violation]:
        line = getattr(node, "lineno", 0)
        if self.suppressed(line, rule):
            return None
        text = self.lines[line - 1].strip() if 0 < line <= len(
            self.lines) else ""
        return Violation(self.path, line, rule, message,
                         self.scope_of(node), text)


def parse_module(path: str, source: str) -> Optional[ModuleSource]:
    """Parse + index one file; returns None when the file does not parse
    (the style gate owns syntax errors)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None
    mod = ModuleSource(path=path, source=source, tree=tree,
                       lines=source.splitlines())
    for i, line in enumerate(mod.lines, 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            mod.suppressions[i] = {
                r.strip() for r in m.group(1).split(",") if r.strip()}
    _index(mod)
    return mod


def _index(mod: ModuleSource) -> None:
    def visit(node, parent, fn, classname):
        mod.parents[node] = parent
        mod.fn_of[node] = fn
        child_fn = fn
        child_class = classname
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            name = getattr(node, "name", "<lambda>")
            prefix = fn.qualname + "." if fn else ""
            if classname and not fn:
                prefix = classname + "."
            info = FuncInfo(node, name, prefix + name, fn)
            mod.functions.append(info)
            child_fn = info
            child_class = None
        elif isinstance(node, ast.ClassDef):
            child_class = (classname + "." if classname else "") + node.name
        elif isinstance(node, ast.Import):
            for a in node.names:
                mod.imports[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name != "*":
                    mod.imports[a.asname or a.name] = (
                        f"{node.module}.{a.name}")
        for child in ast.iter_child_nodes(node):
            visit(child, node, child_fn, child_class)

    visit(mod.tree, None, None, None)
    for alias, target in mod.imports.items():
        if target == "numpy":
            mod.numpy_aliases.add(alias)
        elif target == "jax.numpy":
            mod.jnp_aliases.add(alias)


def call_name(node: ast.Call) -> str:
    """Terminal name of a call's function: jax.jit -> 'jit'."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def dotted_name(node: ast.AST) -> str:
    """'jax.jit' for Attribute(Name('jax'), 'jit'); '' if not a plain
    dotted path."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def is_jit_reference(node: ast.AST) -> bool:
    """Does this expression denote jax.jit (Name 'jit' or *.jit)?"""
    d = dotted_name(node)
    return d == "jit" or d.endswith(".jit")


@dataclasses.dataclass
class JitSig:
    """Static-argument signature of one jit-wrapped entry point."""

    name: str
    params: Optional[List[str]]  # positional order, None if unknown
    static_names: Set[str]
    static_nums: Set[int]
    where: str

    def static_param_at(self, idx: int) -> Optional[str]:
        if idx in self.static_nums:
            return f"argnum {idx}"
        if self.params is not None and idx < len(self.params):
            p = self.params[idx]
            if p in self.static_names:
                return p
        return None


def _const_str_seq(node: ast.AST) -> Set[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return {e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)}
    return set()


def _const_int_seq(node: ast.AST) -> Set[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return {e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)}
    return set()


def jit_call_statics(call: ast.Call) -> Tuple[Set[str], Set[int]]:
    """static_argnames/static_argnums from a jax.jit(...) or
    functools.partial(jax.jit, ...) call."""
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names |= _const_str_seq(kw.value)
        elif kw.arg == "static_argnums":
            nums |= _const_int_seq(kw.value)
    return names, nums


def _jit_decorator_statics(dec: ast.AST
                           ) -> Optional[Tuple[Set[str], Set[int]]]:
    """(static_names, static_nums) when ``dec`` is a jit decorator:
    @jax.jit, @jit, @jax.jit(...), @functools.partial(jax.jit, ...)."""
    if is_jit_reference(dec):
        return set(), set()
    if isinstance(dec, ast.Call):
        if is_jit_reference(dec.func):
            return jit_call_statics(dec)
        if call_name(dec) == "partial" and dec.args \
                and is_jit_reference(dec.args[0]):
            return jit_call_statics(dec)
    return None


def _fn_params(node: ast.AST) -> Optional[List[str]]:
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    a = node.args
    return [p.arg for p in a.posonlyargs + a.args]


class Project:
    """Cross-file context shared by all rules."""

    def __init__(self, modules: Sequence[ModuleSource]):
        self.modules = list(modules)
        self.module_names = {m.module_name for m in modules}
        self.jit_sigs: Dict[str, JitSig] = {}
        self.reachable_fq: Set[str] = set()
        self._reachable_nodes: Dict[str, Set[ast.AST]] = {}
        self._collect_jit_sigs()
        self._reachability_fixpoint()

    # -- jit signatures ----------------------------------------------------

    def _collect_jit_sigs(self) -> None:
        for mod in self.modules:
            for fi in mod.functions:
                node = fi.node
                if isinstance(node, ast.Lambda):
                    continue
                for dec in node.decorator_list:
                    statics = _jit_decorator_statics(dec)
                    if statics is None:
                        continue
                    names, nums = statics
                    sig = JitSig(fi.name, _fn_params(node), names, nums,
                                 f"{mod.path}:{node.lineno}")
                    self.jit_sigs[fi.name] = sig
                    self.jit_sigs[f"{mod.module_name}.{fi.name}"] = sig
            # g = jax.jit(f, static_argnames=...) at module level
            for node in mod.tree.body:
                if not (isinstance(node, ast.Assign) and len(node.targets)
                        == 1 and isinstance(node.targets[0], ast.Name)):
                    continue
                v = node.value
                if isinstance(v, ast.Call) and is_jit_reference(v.func):
                    names, nums = jit_call_statics(v)
                    target = v.args[0] if v.args else None
                    params = None
                    if isinstance(target, ast.Name):
                        for fi in mod.functions:
                            if fi.name == target.id and fi.parent is None:
                                params = _fn_params(fi.node)
                    gname = node.targets[0].id
                    sig = JitSig(gname, params, names, nums,
                                 f"{mod.path}:{node.lineno}")
                    self.jit_sigs[gname] = sig
                    self.jit_sigs[f"{mod.module_name}.{gname}"] = sig

    # -- trace reachability ------------------------------------------------

    def reachable(self, mod: ModuleSource) -> Set[ast.AST]:
        """Function nodes in ``mod`` whose bodies execute under trace."""
        return self._reachable_nodes.get(mod.path, set())

    def in_traced_code(self, mod: ModuleSource, node: ast.AST) -> bool:
        fi = mod.fn_of.get(node)
        reach = self.reachable(mod)
        while fi is not None:
            if fi.node in reach:
                return True
            fi = fi.parent
        return False

    def _module_reachable(self, mod: ModuleSource) -> Set[ast.AST]:
        by_name = collections.defaultdict(list)
        for fi in mod.functions:
            by_name[fi.name].append(fi)

        roots: Set[ast.AST] = set()
        traced_names: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _jit_decorator_statics(dec) is not None:
                        roots.add(node)
            elif isinstance(node, ast.Call) \
                    and call_name(node) in TRACING_CALLS:
                args = list(node.args) + [
                    kw.value for kw in node.keywords]
                for a in args:
                    if isinstance(a, ast.Lambda):
                        roots.add(a)
                    elif isinstance(a, ast.Name):
                        traced_names.add(a.id)
        for name in traced_names:
            for fi in by_name.get(name, ()):
                roots.add(fi.node)
        fq_prefix = mod.module_name + "."
        for fq in self.reachable_fq:
            if fq.startswith(fq_prefix):
                bare = fq[len(fq_prefix):]
                for fi in by_name.get(bare, ()):
                    if fi.parent is None:  # only module-level defs have
                        roots.add(fi.node)  # a cross-module address

        # Closure: nested-in-reachable and called-by-name-from-reachable.
        reach = set(roots)
        changed = True
        while changed:
            changed = False
            for fi in mod.functions:
                if fi.node in reach:
                    continue
                if fi.parent is not None and fi.parent.node in reach:
                    reach.add(fi.node)
                    changed = True
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)):
                    continue
                if not self.in_module_reach(mod, node, reach):
                    continue
                for fi in by_name.get(node.func.id, ()):
                    if fi.node not in reach:
                        reach.add(fi.node)
                        changed = True
        return reach

    def in_module_reach(self, mod: ModuleSource, node: ast.AST,
                        reach: Set[ast.AST]) -> bool:
        fi = mod.fn_of.get(node)
        while fi is not None:
            if fi.node in reach:
                return True
            fi = fi.parent
        return False

    def _exported_reachable_calls(self, mod: ModuleSource,
                                  reach: Set[ast.AST]) -> Set[str]:
        """fq names of project functions called from reachable bodies
        (the cross-module edge: kernels.score_fixed inside a jitted
        score_bucket marks serving.kernels.score_fixed reachable)."""
        out: Set[str] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not self.in_module_reach(mod, node, reach):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and isinstance(f.value,
                                                           ast.Name):
                target = mod.imports.get(f.value.id)
                if target in self.module_names:
                    out.add(f"{target}.{f.attr}")
            elif isinstance(f, ast.Name):
                target = mod.imports.get(f.id)
                if target and target.rsplit(".", 1)[0] \
                        in self.module_names:
                    out.add(target)
        return out

    def _reachability_fixpoint(self) -> None:
        for _ in range(4):  # cross-module depth is tiny in practice
            new_fq: Set[str] = set()
            for mod in self.modules:
                reach = self._module_reachable(mod)
                self._reachable_nodes[mod.path] = reach
                new_fq |= self._exported_reachable_calls(mod, reach)
            if new_fq <= self.reachable_fq:
                return
            self.reachable_fq |= new_fq


# -- driving ---------------------------------------------------------------

def iter_py_files(paths: Sequence[Path]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        if p.is_dir():
            files += sorted(p.rglob("*.py"))
        else:
            files.append(p)
    seen = set()
    out = []
    for f in files:
        if f not in seen:
            seen.add(f)
            out.append(f)
    return out


def load_modules(root: Path, files: Sequence[Path]) -> List[ModuleSource]:
    mods = []
    for f in files:
        try:
            rel = f.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        mod = parse_module(rel, f.read_text())
        if mod is not None:
            mods.append(mod)
    return mods


def analyze_modules(modules: Sequence[ModuleSource], rules=None
                    ) -> List[Violation]:
    from photon_ml_tpu.analysis import rules as _rules
    active = rules if rules is not None else _rules.ALL_RULES
    project = Project(modules)
    violations: List[Violation] = []
    for mod in modules:
        for rule in active:
            violations += rule.check(mod, project)
    violations.sort(key=lambda v: (v.path, v.line, v.rule, v.message))
    return violations


def analyze_sources(sources: Dict[str, str], rules=None) -> List[Violation]:
    """Analyze in-memory {relpath: source} — the test-facing entry."""
    mods = [m for m in (parse_module(p, s) for p, s in sorted(
        sources.items())) if m is not None]
    return analyze_modules(mods, rules=rules)


# -- baseline --------------------------------------------------------------

def load_baseline(path: Path) -> collections.Counter:
    """Baseline file: one fingerprint per line (repeats = multiplicity),
    '#' comment lines and blanks ignored."""
    if not path.exists():
        return collections.Counter()
    counts: collections.Counter = collections.Counter()
    for line in path.read_text().splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            counts[line] += 1
    return counts


def write_baseline(path: Path, violations: Sequence[Violation]) -> None:
    """Deterministic: sorted fingerprints, one per occurrence."""
    lines = sorted(v.fingerprint for v in violations)
    header = ("# jaxlint baseline — accepted pre-existing violations "
              "(gate = no NEW violations).\n"
              "# Regenerate with: python dev_scripts/jaxlint.py "
              "--baseline-update\n")
    path.write_text(header + "".join(line + "\n" for line in lines))


def apply_baseline(violations: Sequence[Violation],
                   baseline: collections.Counter
                   ) -> Tuple[List[Violation], collections.Counter]:
    """Split into (new violations, stale baseline entries). A fingerprint
    occurring N times is covered up to its baseline multiplicity."""
    budget = collections.Counter(baseline)
    new: List[Violation] = []
    for v in violations:
        if budget[v.fingerprint] > 0:
            budget[v.fingerprint] -= 1
        else:
            new.append(v)
    stale = +budget  # entries with remaining (unmatched) multiplicity
    return new, stale
