"""jaxlint: AST-based static analysis for JAX performance pitfalls.

CLI front-end: dev_scripts/jaxlint.py (wired into tests.sh).
Runtime complement: photon_ml_tpu/utils/tracing_guard.py.
Rule catalog + examples: docs/ANALYSIS.md.
"""

from photon_ml_tpu.analysis.core import (
    Violation,
    analyze_modules,
    analyze_sources,
    apply_baseline,
    iter_py_files,
    load_baseline,
    load_modules,
    write_baseline,
)
from photon_ml_tpu.analysis.rules import ALL_RULES, RULE_IDS

__all__ = [
    "Violation",
    "analyze_modules",
    "analyze_sources",
    "apply_baseline",
    "iter_py_files",
    "load_baseline",
    "load_modules",
    "write_baseline",
    "ALL_RULES",
    "RULE_IDS",
]
