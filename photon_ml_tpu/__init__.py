"""photon_ml_tpu — a TPU-native framework for large-scale GLMs and GAME
(Generalized Additive Mixed Effect) models.

Re-designed from scratch for TPU hardware (JAX / XLA / pjit / shard_map):

- Objective functions are pure ``jnp`` programs; value+gradient come from
  ``jax.value_and_grad`` and Hessian-vector products from ``jax.jvp`` of the
  gradient, letting XLA fuse what the reference implemented as hand-written
  single-pass aggregators (reference:
  photon-ml/src/main/scala/com/linkedin/photon/ml/function/ValueAndGradientAggregator.scala).
- Optimizers (L-BFGS / OWL-QN / TRON) are ``lax.while_loop`` state machines
  that run in three modes: distributed (data sharded over a mesh, gradients
  all-reduced by XLA), batched (``vmap`` over an entity axis for random
  effects), and local (single device).
- The GAME coordinate-descent algorithm keeps scores as dense device-resident
  vectors indexed by row id — the reference's RDD join choreography
  (KeyValueScore) becomes pure elementwise arithmetic.

Capability parity target: Harikiranvuyyuru/photon-ml (LinkedIn Photon-ML).
"""

from photon_ml_tpu.types import TaskType

__version__ = "0.1.0"

__all__ = ["TaskType", "__version__"]
