"""Coordinates: the per-block solvers of GAME coordinate descent.

Reference: ml/algorithm/Coordinate.scala:26-82, FixedEffectCoordinate.scala,
RandomEffectCoordinate.scala. The residual-fitting contract is identical —
each coordinate solves against offsets augmented with the *other*
coordinates' scores — but the execution is TPU-native:

- FixedEffectCoordinate: one distributed GLM solve; batch rows (and the CSR
  nnz stream) shard over the mesh's data axis, coefficients replicate, and
  the gradient reduction compiles to an ICI all-reduce (vs. the reference's
  broadcast + treeAggregate per L-BFGS evaluation).
- RandomEffectCoordinate: per-bucket `vmap`-batched solves over the entity
  axis (vs. the reference's per-entity Breeze solves inside mapValues tasks);
  scores come back through a scatter-add instead of RDD joins.

Scores here, as in the reference (GameEstimator score semantics), are raw
margins x.coef — offsets are NOT included (they are added by evaluators /
objective computations as needed).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.random_effect import EntityBlock, RandomEffectDataset
from photon_ml_tpu.data.sampling import down_sample_weights
from photon_ml_tpu.models.fixed_effect import FixedEffectModel
from photon_ml_tpu.models.glm import model_for_task
from photon_ml_tpu.models.random_effect import RandomEffectModel
from photon_ml_tpu.ops.glm_objective import GLMBatch, GLMObjective
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.optimization.solver import regularization_term, solve_glm
from photon_ml_tpu.types import TaskType

Array = jax.Array


class Coordinate:
    """Interface: update_model(model, residual_scores) and score(model)."""

    name: str

    def update_model(self, model, residual_scores: Optional[Array], rng_key):
        raise NotImplementedError

    def score(self, model) -> Array:
        raise NotImplementedError

    def initialize_model(self):
        raise NotImplementedError

    def regularization_term(self, model) -> float:
        raise NotImplementedError


@dataclasses.dataclass
class FixedEffectCoordinate(Coordinate):
    """Global GLM coordinate (ml/algorithm/FixedEffectCoordinate.scala:34-166)."""

    name: str
    data: GameDataset
    feature_shard_id: str
    task_type: TaskType
    config: GLMOptimizationConfiguration
    lower_bounds: Optional[Array] = None
    upper_bounds: Optional[Array] = None
    normalization: Optional[object] = None  # NormalizationContext
    dtype: object = jnp.float32
    mesh: Optional[object] = None  # jax.sharding.Mesh: shard rows over it

    def __post_init__(self):
        self._batch = self.data.fixed_effect_batch(
            self.feature_shard_id, dtype=self.dtype)
        if self.mesh is not None:
            from photon_ml_tpu.parallel import shard_batch

            self._batch = shard_batch(self._batch, self.mesh)
        self._objective = GLMObjective(
            loss_for_task(self.task_type), self.normalization)

    def initialize_model(self) -> FixedEffectModel:
        d = self.data.feature_shards[self.feature_shard_id].shape[1]
        glm_cls = model_for_task(self.task_type)
        from photon_ml_tpu.models.coefficients import Coefficients
        return FixedEffectModel(
            glm_cls(Coefficients.zeros(d, self.dtype)), self.feature_shard_id)

    def update_model(
        self, model: FixedEffectModel, residual_scores: Optional[Array],
        rng_key,
    ) -> Tuple[FixedEffectModel, object]:
        batch = self._batch
        if residual_scores is not None:
            # The batch may be row-padded for sharding; pad the residual with
            # zeros to match (padding rows have weight 0, so the value added
            # there is irrelevant).
            pad = batch.num_rows - residual_scores.shape[0]
            if pad:
                residual_scores = jnp.concatenate(
                    [residual_scores,
                     jnp.zeros((pad,), residual_scores.dtype)])
            batch = batch.with_offsets(
                batch.offsets + residual_scores.astype(batch.offsets.dtype))
        weights = down_sample_weights(
            rng_key, batch.labels, batch.weights,
            self.config.down_sampling_rate,
            self.task_type.is_classification)
        batch = GLMBatch(batch.features, batch.labels, batch.offsets, weights)
        # Models live in the ORIGINAL feature space; the solve happens in the
        # normalized space (reference: the estimator converts trained
        # coefficients back through the NormalizationContext).
        coef0 = model.glm.coefficients.means
        if self.normalization is not None:
            coef0 = self.normalization.model_to_normalized_space(coef0)
        result = solve_glm(
            self._objective, batch, self.config, coef0,
            self.lower_bounds, self.upper_bounds)
        coef = result.x
        if self.normalization is not None:
            coef = self.normalization.model_to_original_space(coef)
        from photon_ml_tpu.models.coefficients import Coefficients
        new_glm = model.glm.update_coefficients(Coefficients(coef))
        return model.update_model(new_glm), result

    def score(self, model: FixedEffectModel) -> Array:
        # Original-space coefficients against raw features — consistent with
        # host-side scoring (FixedEffectModel.score_numpy). The batch may be
        # row-padded for sharding; scores are truncated to the true row count
        # so they align with other coordinates' score vectors.
        return model.glm.compute_score(
            self._batch.features)[: self.data.num_rows]

    def regularization_term(self, model: FixedEffectModel) -> float:
        # The penalty applies in the optimization (normalized) space.
        coef = model.glm.coefficients.means
        if self.normalization is not None:
            coef = self.normalization.model_to_normalized_space(coef)
        return regularization_term(self.config, coef)


@dataclasses.dataclass
class RandomEffectCoordinate(Coordinate):
    """Entity-sharded coordinate
    (ml/algorithm/RandomEffectCoordinate.scala:36-201)."""

    name: str
    dataset: RandomEffectDataset
    task_type: TaskType
    config: GLMOptimizationConfiguration
    mesh: Optional[object] = None  # jax.sharding.Mesh: shard entities over it

    def __post_init__(self):
        if self.mesh is not None:
            from photon_ml_tpu.parallel import shard_block

            self.dataset = dataclasses.replace(
                self.dataset,
                blocks=[shard_block(b, self.mesh,
                                    sentinel_row=self.dataset.n_rows)
                        for b in self.dataset.blocks],
                passive_blocks=[
                    None if b is None else
                    shard_block(b, self.mesh,
                                sentinel_row=self.dataset.n_rows)
                    for b in self.dataset.passive_blocks],
            )
        self._objective = GLMObjective(loss_for_task(self.task_type))

    def initialize_model(self) -> RandomEffectModel:
        return RandomEffectModel.zeros_like_dataset(self.dataset)

    def update_model(
        self, model: RandomEffectModel, residual_scores: Optional[Array],
        rng_key,
    ) -> Tuple[RandomEffectModel, List[object]]:
        """vmap-batched per-entity solves, one kernel per bucket
        (the TPU analog of the activeData.join(problems).join(models)
        mapValues solve, RandomEffectCoordinate.scala:104-113)."""
        new_coefs = []
        trackers = []
        for block, coefs in zip(self.dataset.blocks, model.local_coefs):
            extra = _gather_residual(residual_scores, block,
                                     self.dataset.n_rows)
            result = _solve_block(
                self._objective, self.config, block, extra, coefs)
            new_coefs.append(result.x)
            trackers.append(result)
        return model.with_coefs(new_coefs), trackers

    def score(self, model: RandomEffectModel) -> Array:
        margins = []
        passive_margins = []
        for block, coefs in zip(self.dataset.blocks, model.local_coefs):
            m = block.local_margins(coefs)
            margins.append(jnp.where(block.row_ids < self.dataset.n_rows,
                                     m, 0.0))
        for pblock, coefs in zip(self.dataset.passive_blocks,
                                 model.local_coefs):
            if pblock is None:
                passive_margins.append(None)
            else:
                m = pblock.local_margins(coefs)
                passive_margins.append(
                    jnp.where(pblock.row_ids < self.dataset.n_rows, m, 0.0))
        return self.dataset.scatter_scores(margins, passive_margins)

    def regularization_term(self, model: RandomEffectModel) -> float:
        return sum(regularization_term(self.config, c)
                   for c in model.local_coefs)


def _gather_residual(residual_scores: Optional[Array], block: EntityBlock,
                     n_rows: int) -> Optional[Array]:
    if residual_scores is None:
        return None
    ext = jnp.concatenate(
        [residual_scores,
         jnp.zeros((1,), residual_scores.dtype)])
    return ext[block.row_ids]


@functools.partial(jax.jit, static_argnames=("objective", "config"))
def _solve_block(
    objective: GLMObjective, config: GLMOptimizationConfiguration,
    block: EntityBlock, extra_offsets, coefs0,
):
    """One vmapped solve over the bucket's entity axis, jitted so the whole
    batched solve (trace included) is cached across coordinate-descent
    iterations. ``objective`` hashes by identity and ``config`` by value —
    both stable for a persistent coordinate."""
    offsets = block.offsets if extra_offsets is None else \
        block.offsets + extra_offsets.astype(block.offsets.dtype)

    def fit_one(coef0, x, y, off, w):
        from photon_ml_tpu.ops.features import DenseFeatures
        batch = GLMBatch(DenseFeatures(x), y, off, w)
        return solve_glm(objective, batch, config, coef0)

    return jax.vmap(fit_one)(coefs0, block.x, block.labels, offsets,
                             block.weights)
