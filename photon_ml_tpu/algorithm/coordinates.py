"""Coordinates: the per-block solvers of GAME coordinate descent.

Reference: ml/algorithm/Coordinate.scala:26-82, FixedEffectCoordinate.scala,
RandomEffectCoordinate.scala. The residual-fitting contract is identical —
each coordinate solves against offsets augmented with the *other*
coordinates' scores — but the execution is TPU-native:

- FixedEffectCoordinate: one distributed GLM solve; batch rows (and the CSR
  nnz stream) shard over the mesh's data axis, coefficients replicate, and
  the gradient reduction compiles to an ICI all-reduce (vs. the reference's
  broadcast + treeAggregate per L-BFGS evaluation).
- RandomEffectCoordinate: per-bucket `vmap`-batched solves over the entity
  axis (vs. the reference's per-entity Breeze solves inside mapValues tasks);
  scores come back through a scatter-add instead of RDD joins.

Scores here, as in the reference (GameEstimator score semantics), are raw
margins x.coef — offsets are NOT included (they are added by evaluators /
objective computations as needed).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.random_effect import EntityBlock, RandomEffectDataset
from photon_ml_tpu.data.sampling import down_sample_weights
from photon_ml_tpu.models.fixed_effect import FixedEffectModel
from photon_ml_tpu.models.glm import model_for_task
from photon_ml_tpu.models.random_effect import RandomEffectModel
from photon_ml_tpu.ops.features import KroneckerFeatures
from photon_ml_tpu.ops.glm_objective import GLMBatch, GLMObjective
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
from photon_ml_tpu.optimization.convergence import OptimizerResult
from photon_ml_tpu.optimization.solver import solve_glm
from photon_ml_tpu.types import TaskType

Array = jax.Array


class Coordinate:
    """Interface: update_model(model, residual_scores) and score(model).

    Coordinates additionally expose a PURE functional face used by the
    coordinate-descent driver to fuse a whole coordinate update (residual
    reduce -> solve -> re-score -> objective) into ONE jitted dispatch —
    the TPU answer to the reference's per-phase RDD jobs, and the fix for
    per-dispatch tunnel latency dominating small iterations:

    - ``step_data()``     -> pytree of device data, passed explicitly to the
                             jitted step so large arrays are arguments, not
                             baked trace constants;
    - ``params_of(model)``/``model_of(params, model)`` convert between the
                             model object and its trainable pytree;
    - ``pure_update(data, params, residual, key)`` -> (params', tracker);
    - ``pure_score(data, params)``                 -> dense score vector;
    - ``pure_penalties(params)``                   -> (coef, l1, l2) triples.
    All pure_* methods are traceable (no host syncs, fixed shapes).
    """

    name: str

    def update_model(self, model, residual_scores: Optional[Array], rng_key):
        raise NotImplementedError

    def score(self, model) -> Array:
        raise NotImplementedError

    def initialize_model(self):
        raise NotImplementedError

    def penalties(self, model) -> List[Tuple[Array, Array, Array]]:
        """(coefficients, l1, l2) triples in the optimization space — the
        coordinate's contribution to the coordinate-descent objective
        (CoordinateDescent.scala:203-212). l1/l2 are python floats that
        constant-fold into the jitted objective."""
        raise NotImplementedError

    # -- pure functional face (fused coordinate-descent path) --------------

    def step_data(self):
        raise NotImplementedError

    def params_of(self, model):
        raise NotImplementedError

    def model_of(self, params, model):
        raise NotImplementedError

    def pure_update(self, data, params, residual: Optional[Array], rng_key):
        raise NotImplementedError

    def pure_score(self, data, params) -> Array:
        raise NotImplementedError

    def penalty_data(self):
        """Device data the penalty needs beyond the params (e.g. the
        normalization context's factor/shift arrays). Passed back into
        ``pure_penalties`` as an argument so it is never captured as a
        trace constant."""
        return None

    def pure_penalties(self, params,
                       pdata=None) -> List[Tuple[Array, Array, Array]]:
        raise NotImplementedError


def _l1_l2(config: GLMOptimizationConfiguration) -> Tuple[float, float]:
    lam = config.regularization_weight
    rc = config.regularization_context
    return rc.l1_weight(lam), rc.l2_weight(lam)


@dataclasses.dataclass
class FixedEffectCoordinate(Coordinate):
    """Global GLM coordinate (ml/algorithm/FixedEffectCoordinate.scala:34-166)."""

    name: str
    data: GameDataset
    feature_shard_id: str
    task_type: TaskType
    config: GLMOptimizationConfiguration
    lower_bounds: Optional[Array] = None
    upper_bounds: Optional[Array] = None
    normalization: Optional[object] = None  # NormalizationContext
    dtype: object = jnp.float32
    mesh: Optional[object] = None  # jax.sharding.Mesh: shard rows over it
    # Feature-dimension ("model parallel") sharding: coefficients and
    # feature columns shard over the mesh's model axis (falling back to
    # the data axis on a 1-D mesh); on a 2-D (data x model) mesh rows
    # shard over the data axis SIMULTANEOUSLY — the reference's
    # >200k-feature regime (GameEstimator.scala:330-334) composed with
    # its #examples axis. Coefficients are zero-padded to the sharded
    # width inside the update/score dispatches and unpadded on the way
    # out; models always live at the true feature count.
    feature_sharding: bool = False

    def __post_init__(self):
        self._batch = self.data.fixed_effect_batch(
            self.feature_shard_id, dtype=self.dtype)
        self._d = self.data.feature_shards[self.feature_shard_id].shape[1]
        self._d_pad = self._d
        if self.mesh is not None:
            from photon_ml_tpu.parallel import (
                DATA_AXIS,
                MODEL_AXIS,
                shard_batch,
                shard_batch_feature_dim,
            )

            if self.feature_sharding:
                two_d = MODEL_AXIS in self.mesh.shape
                self._batch = shard_batch_feature_dim(
                    self._batch, self.mesh,
                    col_axis=MODEL_AXIS if two_d else DATA_AXIS,
                    row_axis=DATA_AXIS if two_d else None)
                self._d_pad = self._batch.features.shape[-1]
            else:
                self._batch = shard_batch(self._batch, self.mesh)
        norm_solve = self.normalization
        if norm_solve is not None and self._d_pad != self._d:
            # Padded feature columns need inert normalization entries
            # (factor 1 / shift 0) so the padded coordinates stay zero.
            pad = self._d_pad - self._d
            norm_solve = dataclasses.replace(
                norm_solve,
                factors=(None if norm_solve.factors is None else jnp.pad(
                    norm_solve.factors, (0, pad), constant_values=1.0)),
                shifts=(None if norm_solve.shifts is None else jnp.pad(
                    norm_solve.shifts, (0, pad))))
        self._norm_solve = norm_solve
        self._objective = GLMObjective(
            loss_for_task(self.task_type), norm_solve)
        # Penalty scalars as PYTHON floats: they constant-fold into the
        # jitted objective. (Closed-over DEVICE scalars measured ~50ms/call
        # of extra runtime on the remote-TPU backend — never capture device
        # arrays in hot jitted closures.)
        self._l1, self._l2 = _l1_l2(self.config)

    def _pad_d(self, arr, fill=0.0):
        """Zero-pad a [d] vector to the feature-sharded width (no-op
        without feature sharding)."""
        if arr is None or self._d_pad == self._d:
            return arr
        return jnp.pad(jnp.asarray(arr), (0, self._d_pad - self._d),
                       constant_values=fill)

    def initialize_model(self) -> FixedEffectModel:
        d = self.data.feature_shards[self.feature_shard_id].shape[1]
        glm_cls = model_for_task(self.task_type)
        from photon_ml_tpu.models.coefficients import Coefficients
        return FixedEffectModel(
            glm_cls(Coefficients.zeros(d, self.dtype)), self.feature_shard_id)

    def update_model(
        self, model: FixedEffectModel, residual_scores: Optional[Array],
        rng_key,
    ) -> Tuple[FixedEffectModel, object]:
        # Models live in the ORIGINAL feature space; the solve happens in the
        # normalized space (reference: the estimator converts trained
        # coefficients back through the NormalizationContext). Residual
        # padding, down-sampling, the space transforms and the solve all run
        # as one jitted dispatch.
        coef, result = self.pure_update(
            self.step_data(), self.params_of(model), residual_scores,
            rng_key)
        from photon_ml_tpu.models.coefficients import Coefficients
        new_glm = model.glm.update_coefficients(Coefficients(coef))
        return model.update_model(new_glm), result

    def score(self, model: FixedEffectModel) -> Array:
        # Original-space coefficients against raw features — consistent with
        # host-side scoring (FixedEffectModel.score_numpy). The batch may be
        # row-padded for sharding; scores are truncated to the true row count
        # so they align with other coordinates' score vectors. One jitted
        # dispatch (matvec + slice fused).
        return _fe_score_impl(self._pad_d(model.glm.coefficients.means),
                              self._batch.features,
                              n_rows=self.data.num_rows)

    def penalties(self, model: FixedEffectModel):
        # The penalty applies in the optimization (normalized) space.
        return self.pure_penalties(model.glm.coefficients.means,
                                   self.normalization)

    # -- pure functional face ----------------------------------------------

    def step_data(self):
        # _norm_solve (padded to the sharded width when feature sharding
        # is on) is what the solve-space transforms inside _solve_fixed
        # must use. Bounds clamp the solve-space iterate directly —
        # reference semantics (the Breeze iterate IS the normalized-space
        # vector; projectCoefficientsToHypercube clamps it raw,
        # LBFGS.scala:77). Penalties on unpadded params use
        # self.normalization.
        return (self._batch, self._norm_solve, self.lower_bounds,
                self.upper_bounds)

    def params_of(self, model: FixedEffectModel) -> Array:
        return model.glm.coefficients.means

    def model_of(self, params: Array, model: FixedEffectModel):
        from photon_ml_tpu.models.coefficients import Coefficients
        return model.update_model(
            model.glm.update_coefficients(Coefficients(params)))

    def pure_update(self, data, params, residual, rng_key):
        batch, normalization, lb, ub = data
        result, coef = _solve_fixed(
            self._objective, self.config, self.task_type.is_classification,
            batch, residual, rng_key, self._pad_d(params),
            self._pad_d(lb, -jnp.inf), self._pad_d(ub, jnp.inf),
            normalization)
        if self._d_pad != self._d:
            coef = coef[: self._d]
        return coef, result

    def pure_score(self, data, params) -> Array:
        batch = data[0]
        return _fe_score_impl(self._pad_d(params), batch.features,
                              n_rows=self.data.num_rows)

    def penalty_data(self):
        return self.normalization

    def pure_penalties(self, params, pdata=None):
        coef = params
        if pdata is not None:
            coef = pdata.model_to_normalized_space(coef)
        return [(coef, self._l1, self._l2)]


@dataclasses.dataclass
class StreamingFixedEffectCoordinate:
    """Out-of-core fixed-effect solver over a device shard cache — the
    spill-mode (`--hbm-budget`) counterpart of FixedEffectCoordinate.

    Where FixedEffectCoordinate holds ONE device batch and solves inside
    a fused `lax.while_loop`, this coordinate accumulates (value,
    gradient, Hessian-vector) per-shard over a
    :class:`~photon_ml_tpu.data.shard_cache.DeviceShardCache`
    (ops/sharded_objective.py) and drives the solve from the host
    (optimization/glm_lbfgs.py `minimize_lbfgs_glm_streaming` /
    optimization/tron.py `minimize_tron_streaming`) — the treeAggregate
    shape of the reference's distributed solve, with HBM as the
    partition cache tier.

    Scope (enforced): L-BFGS or TRON with L2 only — no L1/OWL-QN, box
    constraints, normalization context, or down-sampling (< 1.0). Those
    configurations stream-train through the resident assembled path,
    which reuses the full one-shot machinery.

    ``mesh`` (`--mesh-devices` / `--mesh-shape`) activates the device
    fold: the cache must be placed on the same devices
    (`DeviceShardCache.from_stream(devices=...)`); per-shard partials
    accumulate on their own device and combine in fixed shard order, so
    the solved model is bit-identical for every mesh size
    (ops/sharded_objective.py). A 2-D (data x model) mesh
    (`make_mesh_2d(R, C)`, C > 1) additionally shards the coefficient
    dimension: the cache must then be built with ``col_blocks=C``, and
    the solved model stays bitwise-identical across mesh shapes
    {1x1, 2x1, 1x2, 2x2} (sharded_objective module docstring; the
    solver-facing convergence state stays full-width on the host).
    """

    name: str
    cache: object  # DeviceShardCache
    feature_shard_id: str
    task_type: TaskType
    config: GLMOptimizationConfiguration
    dtype: object = jnp.float32
    tracing_guard: Optional[object] = None
    # Reuse a previously built ShardedGLMObjective (λ-grid sweeps: the l2
    # weight is a traced argument, so sharing the objective shares every
    # compiled accumulate kernel across grid points — the same
    # no-recompile contract as the resident solvers).
    sharded_objective: Optional[object] = None
    mesh: Optional[object] = None  # 1-D or 2-D jax.sharding.Mesh (device fold)

    def __post_init__(self):
        from photon_ml_tpu.optimization.config import OptimizerType
        from photon_ml_tpu.ops.sharded_objective import ShardedGLMObjective

        l1, l2 = _l1_l2(self.config)
        if l1 > 0:
            raise ValueError(
                "streaming fixed-effect solves support L2 only; "
                "L1/elastic-net needs the resident (assembled) path")
        if self.config.down_sampling_rate < 1.0:
            raise ValueError(
                "down-sampling is not supported with --hbm-budget "
                "streaming solves (per-row randomness is defined on the "
                "full batch); use the resident path")
        if self.config.optimizer_type not in (OptimizerType.LBFGS,
                                              OptimizerType.TRON):
            raise ValueError(
                f"streaming fixed-effect solves support LBFGS/TRON, got "
                f"{self.config.optimizer_type}")
        self._l2 = l2
        if self.sharded_objective is not None:
            if self.sharded_objective.cache is not self.cache:
                raise ValueError(
                    "shared sharded_objective must wrap the same cache")
            want = None
            if self.mesh is not None:
                from photon_ml_tpu.parallel import mesh_fold_devices

                devs = mesh_fold_devices(self.mesh)
                want = devs if len(devs) > 1 else None
            if self.sharded_objective.devices != want:
                raise ValueError(
                    "shared sharded_objective must use the same mesh "
                    f"(objective devices {self.sharded_objective.devices}, "
                    f"coordinate mesh devices {want})")
            self._sharded = self.sharded_objective
            self._objective = self._sharded.objective
        else:
            self._objective = GLMObjective(loss_for_task(self.task_type))
            self._sharded = ShardedGLMObjective(
                self._objective, self.cache,
                tracing_guard=self.tracing_guard, mesh=self.mesh)
            # Expose the built objective through the same field callers
            # pass it back in with (grid sweeps share compiled kernels).
            self.sharded_objective = self._sharded

    def initialize_model(self) -> FixedEffectModel:
        from photon_ml_tpu.models.coefficients import Coefficients

        glm_cls = model_for_task(self.task_type)
        return FixedEffectModel(
            glm_cls(Coefficients.zeros(self.cache.n_features, self.dtype)),
            self.feature_shard_id)

    def solve(self, model: Optional[FixedEffectModel] = None,
              trace_ctx=None, convergence_ring=None, margins_out=None
              ) -> Tuple[FixedEffectModel, OptimizerResult]:
        """One full-batch GLM solve by streamed accumulation (warm-started
        from ``model`` when given). ``trace_ctx`` — the solve's trace
        context (telemetry/tracectx.py; the streaming driver mints one
        per λ-grid point), threaded into the host-driven solver for
        per-iteration events and divergence-watchdog tagging.
        ``convergence_ring`` / ``margins_out`` — the ``--distmon``
        distribution-observability hooks, threaded through to the
        host-driven solvers (see ``minimize_lbfgs_glm_streaming``)."""
        from photon_ml_tpu.optimization.config import OptimizerType
        from photon_ml_tpu.optimization.glm_lbfgs import (
            minimize_lbfgs_glm_streaming,
        )
        from photon_ml_tpu.optimization.tron import minimize_tron_streaming

        if model is None:
            model = self.initialize_model()
        coef0 = jnp.asarray(model.glm.coefficients.means, self.dtype)
        if self.config.optimizer_type == OptimizerType.TRON:
            if not self._objective.loss.twice_differentiable:
                raise ValueError(
                    f"TRON requires a twice-differentiable loss, got "
                    f"{self._objective.loss.name}")
            result = minimize_tron_streaming(
                self._sharded, coef0, self._l2,
                max_iter=self.config.max_iterations,
                tol=self.config.tolerance, trace_ctx=trace_ctx,
                convergence_ring=convergence_ring,
                margins_out=margins_out)
        else:
            result = minimize_lbfgs_glm_streaming(
                self._sharded, coef0, self._l2,
                max_iter=self.config.max_iterations,
                tol=self.config.tolerance, trace_ctx=trace_ctx,
                convergence_ring=convergence_ring,
                margins_out=margins_out)
        self._sharded.assert_trace_budget()
        from photon_ml_tpu.models.coefficients import Coefficients

        new_glm = model.glm.update_coefficients(Coefficients(result.x))
        return model.update_model(new_glm), result


def grid_batchable(configs) -> Tuple[bool, str]:
    """Can this λ-grid run as ONE batched streamed solve
    (:func:`solve_fixed_effect_grid`)? True only when every point is a
    streamable L2 solve and the points are homogeneous in everything
    but ``regularization_weight`` — the batched solvers share one
    candidate schedule / trust-region recipe across rows, so only the
    λ row may vary. Returns ``(ok, why_not)``."""
    from photon_ml_tpu.optimization.config import OptimizerType

    configs = list(configs)
    if not configs:
        return False, "empty grid"
    base = configs[0]
    if base.optimizer_type not in (OptimizerType.LBFGS,
                                   OptimizerType.TRON):
        return False, (f"streaming grid solves support LBFGS/TRON, got "
                       f"{base.optimizer_type}")
    for cfg in configs:
        l1, _ = _l1_l2(cfg)
        if l1 > 0:
            return False, ("L1/elastic-net grid points need the "
                           "resident path")
        if cfg.down_sampling_rate < 1.0:
            return False, ("down-sampling is not supported by streamed "
                           "solves")
        if (cfg.optimizer_type != base.optimizer_type
                or cfg.max_iterations != base.max_iterations
                or cfg.tolerance != base.tolerance):
            return False, (
                "grid points must share optimizer type, max_iterations "
                "and tolerance to batch — only the regularization "
                "weight may vary across rows")
    return True, ""


def solve_fixed_effect_grid(
    coordinate: StreamingFixedEffectCoordinate,
    configs,
    models=None,
    trace_ctxs=None,
    convergence_rings=None,
    margins_out=None,
) -> List[Tuple[FixedEffectModel, OptimizerResult]]:
    """Solve a whole λ-grid in ONE batched streamed sweep: coefficients
    stack to ``[G, d]`` and every feature pass of the underlying grid
    solver (optimization/glm_lbfgs.py `minimize_lbfgs_glm_grid_streaming`
    / tron.py `minimize_tron_grid_streaming`) advances all G points —
    a sweep costs the slowest row's pass count instead of the sum over
    rows (~G× less decode+H2D traffic).

    ``coordinate`` supplies the cache/objective/task (its own config
    must be one of the homogeneous grid's shapes); ``configs`` is the
    λ-grid (validated via :func:`grid_batchable` — ValueError with the
    reason when not batchable). ``models`` warm-starts per row
    (row-aligned list, entries may be None). ``trace_ctxs`` /
    ``convergence_rings`` / ``margins_out`` thread through to the grid
    solver (per-row observability; ``margins_out`` receives the
    ``[G, rows]`` per-shard margins — slice rows out with
    ``ShardedGLMObjective.grid_row_margins``).

    Returns a row-aligned list of ``(FixedEffectModel, OptimizerResult)``
    — the same pairs G sequential ``coordinate.solve`` calls produce.
    G=1 delegates to the scalar streamed solver inside the grid solver
    (bitwise gate), so this entry point is safe for any grid size.
    """
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.optimization.config import OptimizerType
    from photon_ml_tpu.optimization.glm_lbfgs import (
        minimize_lbfgs_glm_grid_streaming,
    )
    from photon_ml_tpu.optimization.tron import minimize_tron_grid_streaming

    configs = list(configs)
    ok, why = grid_batchable(configs)
    if not ok:
        raise ValueError(f"λ-grid is not batchable: {why}")
    G = len(configs)
    if models is None:
        models = [None] * G
    models = [m if m is not None else coordinate.initialize_model()
              for m in models]
    if len(models) != G:
        raise ValueError(
            f"models must be row-aligned with the grid (G={G}), got "
            f"{len(models)}")

    dtype = coordinate.dtype
    x0s = jnp.stack([jnp.asarray(m.glm.coefficients.means, dtype)
                     for m in models])
    l2s = np.asarray([_l1_l2(cfg)[1] for cfg in configs],
                     np.dtype(dtype))
    base = configs[0]
    if base.optimizer_type == OptimizerType.TRON:
        if not coordinate._objective.loss.twice_differentiable:
            raise ValueError(
                f"TRON requires a twice-differentiable loss, got "
                f"{coordinate._objective.loss.name}")
        results = minimize_tron_grid_streaming(
            coordinate._sharded, x0s, l2s,
            max_iter=base.max_iterations, tol=base.tolerance,
            trace_ctxs=trace_ctxs, convergence_rings=convergence_rings,
            margins_out=margins_out)
    else:
        results = minimize_lbfgs_glm_grid_streaming(
            coordinate._sharded, x0s, l2s,
            max_iter=base.max_iterations, tol=base.tolerance,
            trace_ctxs=trace_ctxs, convergence_rings=convergence_rings,
            margins_out=margins_out)
    coordinate._sharded.assert_trace_budget()

    out = []
    for model, result in zip(models, results):
        new_glm = model.glm.update_coefficients(Coefficients(result.x))
        out.append((model.update_model(new_glm), result))
    return out


@dataclasses.dataclass
class RandomEffectCoordinate(Coordinate):
    """Entity-sharded coordinate
    (ml/algorithm/RandomEffectCoordinate.scala:36-201).

    ``normalization`` (a NormalizationContext over the GLOBAL feature
    space) and ``lower_bounds``/``upper_bounds`` (global [d] arrays)
    mirror the reference's per-problem normalization + constraintMap
    (RandomEffectOptimizationProblem.scala:105-125,
    OptimizationUtils.scala:53): both are gathered into each block's
    local feature space through feat_idx at construction, ride along
    as device data, and fold into the fused Pallas kernel (or the
    vmapped fallback) — no silent perf cliff for normalized/bounded
    configs. Models stay in the ORIGINAL space; solves happen in the
    normalized space with per-entity transforms on the way in/out."""

    name: str
    dataset: RandomEffectDataset
    task_type: TaskType
    config: GLMOptimizationConfiguration
    mesh: Optional[object] = None  # jax.sharding.Mesh: shard entities over it
    lower_bounds: Optional[Array] = None  # global feature space, [d]
    upper_bounds: Optional[Array] = None
    normalization: Optional[object] = None  # NormalizationContext (global)

    def __post_init__(self):
        if self.mesh is not None:
            self.dataset = _shard_re_dataset(self.dataset, self.mesh)
        self._objective = GLMObjective(loss_for_task(self.task_type))
        self._l1, self._l2 = _l1_l2(self.config)
        if (self.normalization is not None
                and self.dataset.projection is not None):
            raise ValueError(
                "normalization on a projected random-effect dataset is "
                "not supported — latent columns are not global features")
        self._norm_blocks = tuple(
            _gather_block_normalization(self.normalization, b)
            for b in self.dataset.blocks)
        # Bounds clamp the SOLVE-SPACE (normalized) coefficients — the
        # reference's exact semantics: its optimizer iterate is the
        # normalized-space vector (the aggregators compute margins via
        # effectiveCoefficients = coef :* factors,
        # ValueAndGradientAggregator.scala:100-120) and
        # projectCoefficientsToHypercube clamps that iterate against the
        # raw constraint values (LBFGS.scala:77,
        # OptimizationUtils.scala:53). No space conversion.
        self._bounds_blocks = tuple(
            _gather_block_bounds(self.lower_bounds, self.upper_bounds, b)
            for b in self.dataset.blocks)

    def initialize_model(self) -> RandomEffectModel:
        dt = (self.dataset.blocks[0].x.dtype if self.dataset.blocks
              else jnp.float32)
        return RandomEffectModel.zeros_like_dataset(self.dataset, dtype=dt)

    def update_model(
        self, model: RandomEffectModel, residual_scores: Optional[Array],
        rng_key,
    ) -> Tuple[RandomEffectModel, List[object]]:
        """vmap-batched per-entity solves, one kernel per bucket
        (the TPU analog of the activeData.join(problems).join(models)
        mapValues solve, RandomEffectCoordinate.scala:104-113)."""
        params, trackers = self.pure_update(
            self.step_data(), self.params_of(model), residual_scores,
            rng_key)
        return self.model_of(params, model), trackers

    def score(self, model: RandomEffectModel) -> Array:
        """All bucket margins + the scatter assembly as ONE jitted dispatch
        (the eager per-block einsum/where/scatter chain costs several
        host->device round trips per call on a remote chip)."""
        return _re_score_impl(
            tuple(self.dataset.blocks), tuple(self.dataset.passive_blocks),
            tuple(model.local_coefs), n_rows=self.dataset.n_rows)

    def penalties(self, model: RandomEffectModel):
        return self.pure_penalties(tuple(model.local_coefs),
                                   self.penalty_data())

    # -- pure functional face ----------------------------------------------

    def step_data(self):
        return (tuple(self.dataset.blocks),
                tuple(self.dataset.passive_blocks),
                self._norm_blocks, self._bounds_blocks)

    def params_of(self, model: RandomEffectModel):
        return tuple(model.local_coefs)

    def model_of(self, params, model: RandomEffectModel):
        return model.with_coefs(list(params))

    def pure_update(self, data, params, residual, rng_key):
        # All bucket solves trace into the caller's single dispatch (vs one
        # dispatch per size-class bucket when called eagerly). Original-
        # space warm starts convert to the solve (normalized) space, and
        # solutions convert back (GameEstimator-side semantics in the
        # reference; here per entity via the gathered transforms).
        from photon_ml_tpu.data.normalization import (
            gathered_to_normalized_space,
            gathered_to_original_space,
        )

        blocks, _, norm_blocks, bounds_blocks = data
        new_coefs, results = [], []
        for block, c0, norm, bounds in zip(blocks, params, norm_blocks,
                                           bounds_blocks):
            if norm is not None:
                c0 = gathered_to_normalized_space(c0, *norm)
            result = _solve_block(
                self._objective, self.config, block, residual, c0,
                sharded=self.mesh is not None, mesh=self.mesh,
                norm=norm, bounds=bounds)
            coef = result.x
            if norm is not None:
                coef = gathered_to_original_space(coef, *norm)
            new_coefs.append(coef)
            results.append(result)
        return tuple(new_coefs), results

    def pure_score(self, data, params) -> Array:
        blocks, pblocks = data[0], data[1]
        return _re_score_impl(blocks, pblocks, tuple(params),
                              n_rows=self.dataset.n_rows)

    def penalty_data(self):
        return self._norm_blocks

    def pure_penalties(self, params, pdata=None):
        # The penalty applies in the optimization (normalized) space,
        # like the fixed effect (L2Regularization.scala:75).
        from photon_ml_tpu.data.normalization import (
            gathered_to_normalized_space,
        )

        norm_blocks = pdata if pdata is not None else (None,) * len(params)
        out = []
        for c, norm in zip(params, norm_blocks):
            if norm is not None:
                c = gathered_to_normalized_space(c, *norm)
            out.append((c, self._l1, self._l2))
        return out


def _gather_block_normalization(normalization, block: EntityBlock):
    """(factors, shifts, intercept_mask) in the block's local feature
    space, or None when no normalization is active (see
    data/normalization.py gather_normalization)."""
    if normalization is None:
        return None
    from photon_ml_tpu.data.normalization import gather_normalization

    factors, shifts, mask = gather_normalization(normalization,
                                                 block.feat_idx)
    if factors is None and shifts is None:
        return None
    dt = block.x.dtype
    conv = lambda a: None if a is None else a.astype(dt)
    return conv(factors), conv(shifts), mask.astype(dt)


def _gather_block_bounds(lower, upper, block: EntityBlock):
    """(lower, upper) [E, d] in the block's local feature space, or None.
    Padding columns (feat_idx == -1) are unbounded — their coefficients
    are driven to zero by L2 and never touch data."""
    if lower is None and upper is None:
        return None
    dt = block.x.dtype
    safe = jnp.maximum(block.feat_idx, 0)
    pad = block.feat_idx < 0

    def gather(vec, default):
        if vec is None:
            return jnp.full(block.feat_idx.shape, default, dt)
        return jnp.where(pad, default, jnp.asarray(vec, dt)[safe])

    return gather(lower, -jnp.inf), gather(upper, jnp.inf)


def _shard_re_dataset(dataset: RandomEffectDataset, mesh
                      ) -> RandomEffectDataset:
    """Shard every (active + passive) bucket's entity axis over the mesh."""
    from photon_ml_tpu.parallel import shard_block

    return dataclasses.replace(
        dataset,
        blocks=[shard_block(b, mesh, sentinel_row=dataset.n_rows)
                for b in dataset.blocks],
        passive_blocks=[
            None if b is None else
            shard_block(b, mesh, sentinel_row=dataset.n_rows)
            for b in dataset.passive_blocks],
    )


@dataclasses.dataclass
class StreamingFactoredRandomEffectCoordinate:
    """Out-of-core factored random effect (matrix factorization) — the
    streamed/sharded counterpart of :class:`FactoredRandomEffectCoordinate`,
    built on `ops/mf_alternating.py` + `data/factor_cache.py` (PAPERS.md
    "ALX: Large Scale Matrix Factorization on TPUs"): factor tables live
    in a budgeted `DeviceFactorCache` (pow-2 observation-count bucketing,
    replay-aware eviction, f32/bf16/redecode spill tiers), observations
    stream through `BlockGameStream` batches re-decoded per feature pass,
    the per-entity gamma half-step is an exact streamed ridge ALS
    (batched per-bucket normal-equation solves), and the projection
    refit reuses `minimize_lbfgs_glm_streaming` over the duck-typed
    Kronecker-margin objective. Factor tables larger than
    ``hbm_budget_bytes`` train to completion out-of-core.

    Scope (enforced): LINEAR_REGRESSION (squared loss — the alternating
    half-steps are least squares; other GLM losses alternate IRLS
    in-core), L2-only with a strictly positive gamma ridge (λ₂ = 0
    normal equations are singular for low-observation entities), no
    down-sampling, L-BFGS latent refits. Everything else trains through
    the in-core coordinate.

    Plugs into coordinate descent behind the existing residual-fitting
    contract: ``solve(model, residual_scores=...)`` folds the other
    coordinates' scores into the streamed offsets, and ``score(model)``
    returns raw margins γᵀ B x. Each alternating sweep runs under its
    own minted `TraceContext` (kind ``mf_sweep`` — slow sweeps land on
    /tracez) and the per-sweep objective is checked by
    `check_solver_finite`, so a NaN/Inf alternating solve raises a typed
    :class:`~photon_ml_tpu.optimization.convergence.SolverDivergedError`
    with a trace-tagged flight dump, like the streamed L-BFGS/TRON
    paths.

    ``mf_objective`` shares the built `StreamedMFObjective` (plan +
    factor cache + compiled kernels) across λ-grid points with the same
    ``num_factors`` — the same no-recompile sharing contract as
    `StreamingFixedEffectCoordinate.sharded_objective`.
    """

    name: str
    make_stream: object  # () -> iterable of GameDataset batches
    feature_shard_id: str
    random_effect_type: str
    task_type: TaskType
    config: GLMOptimizationConfiguration  # per-entity gamma ridge
    latent_config: GLMOptimizationConfiguration  # projection refit
    mf_config: "MFOptimizationConfiguration"
    n_features: Optional[int] = None  # settled by the planning pass
    hbm_budget_bytes: Optional[int] = None
    spill_dtype: str = "f32"
    spill_source: str = "buffer"
    entities_per_shard: int = 512
    seed: int = 7
    tracing_guard: Optional[object] = None
    mf_objective: Optional[object] = None  # shared StreamedMFObjective
    random_access: Optional[object] = None  # BlockRandomAccess hook

    def __post_init__(self):
        from photon_ml_tpu.optimization.config import OptimizerType

        if self.task_type != TaskType.LINEAR_REGRESSION:
            raise ValueError(
                "streamed MF alternating least squares is defined for "
                "LINEAR_REGRESSION (squared loss); other tasks train "
                "through the in-core FactoredRandomEffectCoordinate")
        l1, l2 = _l1_l2(self.config)
        ll1, self._ll2 = _l1_l2(self.latent_config)
        if l1 > 0 or ll1 > 0:
            raise ValueError(
                "streamed MF supports L2 only; L1/elastic-net factors "
                "need the in-core path")
        if l2 <= 0:
            raise ValueError(
                "streamed MF needs a strictly positive gamma L2 weight "
                "(the per-entity ridge normal equations are singular at "
                "λ₂ = 0 for low-observation entities)")
        if self.config.down_sampling_rate < 1.0 \
                or self.latent_config.down_sampling_rate < 1.0:
            raise ValueError(
                "down-sampling is not supported with streamed MF "
                "solves; use the in-core path")
        if self.latent_config.optimizer_type != OptimizerType.LBFGS:
            raise ValueError(
                f"streamed MF latent refits support LBFGS, got "
                f"{self.latent_config.optimizer_type}")
        self._l2 = l2
        k = self.mf_config.num_factors
        if self.mf_objective is not None:
            if self.mf_objective.k != k:
                raise ValueError(
                    f"shared mf_objective was built for num_factors="
                    f"{self.mf_objective.k}, coordinate asks for {k}")
            self._obj = self.mf_objective
        else:
            from photon_ml_tpu.data.factor_cache import (
                DeviceFactorCache,
                count_stream_entities,
                plan_factors,
            )
            from photon_ml_tpu.ops.mf_alternating import (
                StreamedMFObjective,
            )

            with _telemetry_span("factor_plan"):
                vocab, counts, n_rows, d_by_shard = count_stream_entities(
                    self.make_stream(), self.random_effect_type)
            if self.feature_shard_id not in d_by_shard:
                raise KeyError(
                    f"stream carries no feature shard "
                    f"{self.feature_shard_id!r} "
                    f"(have {sorted(d_by_shard)})")
            d = d_by_shard[self.feature_shard_id]
            if self.n_features is not None and self.n_features != d:
                raise ValueError(
                    f"stream decodes {d} features for shard "
                    f"{self.feature_shard_id!r}, coordinate expected "
                    f"{self.n_features}")
            self.n_features = d
            plan = plan_factors(vocab, counts,
                                entities_per_shard=self.entities_per_shard)
            cache = DeviceFactorCache(
                plan, k, hbm_budget_bytes=self.hbm_budget_bytes,
                spill_dtype=self.spill_dtype,
                spill_source=self.spill_source)
            self._obj = StreamedMFObjective(
                self.make_stream, self.feature_shard_id,
                self.random_effect_type, plan, cache, d,
                loss_for_task(self.task_type),
                tracing_guard=self.tracing_guard,
                random_access=self.random_access)
            self._obj.n_rows = n_rows
            self.mf_objective = self._obj
        self.n_features = self._obj.d

    @property
    def cache(self):
        """The factor cache (live /statusz residency provider)."""
        return self._obj.cache

    @property
    def plan(self):
        return self._obj.plan

    def initialize_model(self):
        """Zero factors + the SAME seeded Gaussian projection init as
        the in-core coordinate, so streamed-vs-in-core parity starts
        from identical B₀."""
        from photon_ml_tpu.models.factored_random_effect import (
            FactoredRandomEffectModel,
        )
        from photon_ml_tpu.projector.projectors import ProjectionMatrix

        k = self.mf_config.num_factors
        d = self.n_features
        plan = self._obj.plan
        b0 = ProjectionMatrix.gaussian(k, d, intercept_col=None,
                                       seed=self.seed)
        latent = RandomEffectModel(
            random_effect_type=self.random_effect_type,
            feature_shard_id=self.feature_shard_id,
            local_coefs=[jnp.zeros((s.n_entities, k), jnp.float32)
                         for s in plan.shards],
            feat_idx=[jnp.tile(jnp.arange(k), (s.n_entities, 1))
                      for s in plan.shards],
            entity_codes=[s.codes.astype(np.int32) for s in plan.shards],
            vocabulary=plan.vocabulary,
            num_global_features=d,
            projection=b0,
        )
        return FactoredRandomEffectModel(latent, self.mf_config)

    def solve(self, model=None, residual_scores=None, trace_ctx=None):
        """``mf_config.max_iterations`` alternating sweeps (streamed
        ridge gamma pass + streamed L-BFGS projection refit), warm-
        starting B from ``model``. Returns ``(model, trackers)`` with
        one OptimizerResult per sweep — the in-core coordinate's
        tracker shape."""
        from photon_ml_tpu import telemetry
        from photon_ml_tpu.optimization.glm_lbfgs import (
            minimize_lbfgs_glm_streaming,
        )
        from photon_ml_tpu.optimization.convergence import (
            check_solver_finite,
        )

        if model is None:
            model = self.initialize_model()
        b_mat = jnp.asarray(model.projection_matrix, jnp.float32)
        self._obj.set_residual(residual_scores)
        trackers = []
        for sweep in range(self.mf_config.max_iterations):
            # One trace context per alternating sweep: slow sweeps land
            # on /tracez, and a divergence fault carries the sweep's
            # trace_id into the flight dump (PR-11 watchdog parity).
            ctx = telemetry.mint("mf_sweep")
            ctx.annotate(coordinate=self.name, sweep=sweep,
                         num_factors=self.mf_config.num_factors,
                         reg_weight=self.config.regularization_weight)
            if trace_ctx is not None:
                trace_ctx.event("mf_sweep")
            ctx.event("gamma_pass")
            self._obj.gamma_pass(b_mat, self._l2)
            ctx.event("latent_refit")
            result = minimize_lbfgs_glm_streaming(
                self._obj, jnp.reshape(b_mat, (-1,)), self._ll2,
                max_iter=self.latent_config.max_iterations,
                tol=self.latent_config.tolerance, trace_ctx=ctx)
            b_mat = jnp.reshape(result.x, b_mat.shape)
            # Per-sweep watchdog: the refit's own iterations are already
            # host-checked inside the streamed L-BFGS; re-assert on the
            # sweep boundary so a NaN that rode the FACTOR tables into
            # the refit fails fast under the MF label.
            check_solver_finite(
                "streaming-mf-alternating", sweep,
                np.asarray(result.value)[()],
                np.asarray(result.grad_norm)[()], ctx)
            ctx.finish("ok")
            trackers.append(result)
        self._obj.assert_trace_budget()
        tables = self._obj.factor_tables()
        return model.with_update(list(tables), np.asarray(b_mat)), trackers

    def score(self, model) -> Array:
        """Raw margins γᵀ B x per global row (offsets excluded, like
        every coordinate score) — one streamed pass over the
        observations. Scores the MODEL's factor tables, not the
        objective's internal solve state (a later λ-grid point sharing
        the objective may have overwritten it)."""
        return jnp.asarray(self._obj.score_pass(
            np.asarray(model.projection_matrix, np.float32),
            tables=model.latent.local_coefs))


def _telemetry_span(stage: str):
    from photon_ml_tpu.telemetry import span

    return span(stage)


@dataclasses.dataclass
class FactoredRandomEffectCoordinate(Coordinate):
    """Matrix-factorization-flavored random effect
    (ml/algorithm/FactoredRandomEffectCoordinate.scala:39-289).

    Entity e's coefficients are γ_eᵀ B with γ_e ∈ R^k per entity and a
    shared, learned B ∈ R^{k×d}. Each update alternates (reference loop at
    :103-151):

    1. per-entity latent solves — features projected through the current B
       on device (one einsum per bucket), then the same vmap-batched solve
       as RandomEffectCoordinate;
    2. refit of B as a single GLM over all rows whose virtual features are
       x_i ⊗ γ_entity(i) (reference :229-287 materializes the Kronecker
       product per datum and shuffles it; here KroneckerFeatures contracts
       it lazily via einsum — nothing is materialized).

    The dataset must be built with the IDENTITY projector so blocks carry
    global-width features (B itself is the dimension reduction).
    """

    name: str
    dataset: RandomEffectDataset
    task_type: TaskType
    config: GLMOptimizationConfiguration  # per-entity latent solves
    latent_config: GLMOptimizationConfiguration  # projection-matrix refit
    mf_config: "MFOptimizationConfiguration"
    seed: int = 7
    mesh: Optional[object] = None

    def __post_init__(self):
        if self.dataset.projection is not None:
            raise ValueError(
                "FactoredRandomEffectCoordinate learns its own projection — "
                "build the dataset with projector_type=IDENTITY")
        d = self.dataset.num_global_features
        for b in self.dataset.blocks:
            if b.d_pad < d:
                raise ValueError(
                    "factored random effects need global-width blocks "
                    f"(d_pad {b.d_pad} < num_global_features {d}); build "
                    "the dataset with projector_type=IDENTITY")
        if self.mesh is not None:
            self.dataset = _shard_re_dataset(self.dataset, self.mesh)
        self._objective = GLMObjective(loss_for_task(self.task_type))
        self._l1, self._l2 = _l1_l2(self.config)
        self._ll1, self._ll2 = _l1_l2(self.latent_config)

    @property
    def _dtype(self):
        return self.dataset.blocks[0].x.dtype

    def initialize_model(self):
        from photon_ml_tpu.models.factored_random_effect import (
            FactoredRandomEffectModel,
        )
        from photon_ml_tpu.projector.projectors import ProjectionMatrix

        ds = self.dataset
        k = self.mf_config.num_factors
        b0 = ProjectionMatrix.gaussian(
            k, ds.num_global_features, intercept_col=None, seed=self.seed)
        latent = RandomEffectModel(
            random_effect_type=ds.config.random_effect_type,
            feature_shard_id=ds.config.feature_shard_id,
            local_coefs=[jnp.zeros((b.num_entities, k), self._dtype)
                         for b in ds.blocks],
            feat_idx=[jnp.tile(jnp.arange(k), (b.num_entities, 1))
                      for b in ds.blocks],
            entity_codes=list(ds.entity_codes),
            vocabulary=ds.vocabulary,
            num_global_features=ds.num_global_features,
            projection=b0,
        )
        return FactoredRandomEffectModel(latent, self.mf_config)

    def update_model(self, model, residual_scores: Optional[Array], rng_key):
        params, trackers = self.pure_update(
            self.step_data(), self.params_of(model), residual_scores, rng_key)
        return self.model_of(params, model), trackers

    def score(self, model) -> Array:
        return self.pure_score(self.step_data(), self.params_of(model))

    def penalties(self, model):
        return self.pure_penalties(self.params_of(model))

    # -- pure functional face ----------------------------------------------

    def step_data(self):
        return (tuple(self.dataset.blocks),
                tuple(self.dataset.passive_blocks))

    def params_of(self, model):
        dt = self._dtype
        return (tuple(jnp.asarray(g, dt) for g in model.latent.local_coefs),
                jnp.asarray(model.projection_matrix, dt))

    def model_of(self, params, model):
        import numpy as np

        gammas, B = params
        return model.with_update(list(gammas), np.asarray(B))

    def pure_update(self, data, params, residual, rng_key):
        blocks, _ = data
        gammas, B = list(params[0]), params[1]
        d = self.dataset.num_global_features
        residuals = [_gather_residual(residual, b) for b in blocks]
        # Row-major view of x/labels/offsets/weights is iteration-invariant;
        # only the per-row gammas change across alternations.
        x_flat, y_flat, off_flat, w_flat = _flatten_factored_static(
            blocks, residuals, d)
        trackers = []
        for _ in range(self.mf_config.max_iterations):
            gammas = [
                _solve_factored_block(
                    self._objective, self.config, block, B, extra, g0, d,
                    sharded=self.mesh is not None, mesh=self.mesh).x
                for block, extra, g0 in zip(blocks, residuals, gammas)]
            batch = GLMBatch(
                KroneckerFeatures(x_flat, _flatten_gammas(blocks, gammas)),
                y_flat, off_flat, w_flat)
            result = _solve_latent_matrix(
                self._objective, self.latent_config, batch, B.reshape(-1))
            B = result.x.reshape(B.shape)
            trackers.append(result)
        return (tuple(gammas), B), trackers

    def pure_score(self, data, params) -> Array:
        blocks, pblocks = data
        gammas, B = params
        return _fre_score_impl(
            blocks, pblocks, tuple(gammas), B,
            n_rows=self.dataset.n_rows, d=self.dataset.num_global_features)

    def pure_penalties(self, params, pdata=None):
        gammas, B = params
        out = [(g, self._l1, self._l2) for g in gammas]
        out.append((B, self._ll1, self._ll2))
        return out


@functools.partial(
    jax.jit,
    static_argnames=("objective", "config", "d", "sharded", "mesh"))
def _solve_factored_block(
    objective: GLMObjective, config: GLMOptimizationConfiguration,
    block: EntityBlock, B, extra_offsets, gamma0, d: int,
    sharded: bool = False, mesh=None,
):
    """Per-entity latent solves against the current B: one projection einsum
    for the whole bucket, then the batched solve (fused Pallas kernel on
    TPU — the latent bucket has the same shape contract as the
    random-effect one, see _solve_block; with a mesh the kernel runs per
    device over the entity-sharded bucket via shard_map, B replicated)."""
    lat = jnp.einsum("end,kd->enk", block.x[..., :d], B)
    offsets = block.offsets if extra_offsets is None else \
        block.offsets + extra_offsets.astype(block.offsets.dtype)

    use_kernel = _use_pallas_entity_solver(
        objective, config, lat, sharded=sharded and mesh is None)

    if use_kernel and sharded and mesh is not None:
        return _shard_mapped_pallas_solver(
            objective, config, mesh, lat, block.labels, offsets,
            block.weights, gamma0)

    if use_kernel:
        return _dispatch_pallas_solver(objective, config, lat,
                                       block.labels, offsets,
                                       block.weights, gamma0)

    def fit_one(g0, x_lat, y, off, w):
        from photon_ml_tpu.ops.features import DenseFeatures
        batch = GLMBatch(DenseFeatures(x_lat), y, off, w)
        return solve_glm(objective, batch, config, g0)

    return jax.vmap(fit_one)(gamma0, lat, block.labels, offsets,
                             block.weights)


def _flatten_factored_static(blocks, residuals, d: int):
    """All active rows across buckets in row-major order — the
    iteration-invariant half of the latent-matrix refit batch (replaces the
    reference's partitionBy-uid Kronecker shuffle,
    FactoredRandomEffectCoordinate.scala:269-287)."""
    xs, ys, offs, ws = [], [], [], []
    for block, extra in zip(blocks, residuals):
        xs.append(block.x[..., :d].reshape(-1, d))
        ys.append(block.labels.reshape(-1))
        off = block.offsets if extra is None else \
            block.offsets + extra.astype(block.offsets.dtype)
        offs.append(off.reshape(-1))
        ws.append(block.weights.reshape(-1))
    return (jnp.concatenate(xs), jnp.concatenate(ys),
            jnp.concatenate(offs), jnp.concatenate(ws))


def _flatten_gammas(blocks, gammas) -> Array:
    """Per-row latent factors aligned with _flatten_factored_static's rows."""
    gs = []
    for block, gamma in zip(blocks, gammas):
        e, n_pad = block.labels.shape
        k = gamma.shape[-1]
        gs.append(jnp.broadcast_to(gamma[:, None, :], (e, n_pad, k))
                  .reshape(-1, k))
    return jnp.concatenate(gs)


@functools.partial(jax.jit, static_argnames=("objective", "config"))
def _solve_latent_matrix(
    objective: GLMObjective, config: GLMOptimizationConfiguration,
    batch: GLMBatch, coef0,
):
    return solve_glm(objective, batch, config, coef0)


def _gather_residual(residual_scores: Optional[Array],
                     block: EntityBlock) -> Optional[Array]:
    """Per-row residual for a block: a zero sentinel slot is appended so
    padding rows (row_ids == n_rows) gather 0."""
    if residual_scores is None:
        return None
    ext = jnp.concatenate(
        [residual_scores,
         jnp.zeros((1,), residual_scores.dtype)])
    return ext[block.row_ids]


def _dispatch_pallas_solver(objective, config, x, labels, offsets,
                            weights, coef0, norm=None, bounds=None):
    """Shared kernel dispatch for the random-effect and factored-latent
    bucket solves — one place owns the l1/l2 derivation and the kernel
    call so the two paths cannot diverge. l1 > 0 selects the kernel's
    OWL-QN mode (matching solve_glm's routing to minimize_owlqn);
    ``norm``/``bounds`` are the gathered per-entity arrays folded into
    the kernel."""
    from photon_ml_tpu.ops.pallas_entity_solver import pallas_entity_lbfgs

    from photon_ml_tpu.optimization.config import OptimizerType

    rc = config.regularization_context
    l1 = rc.l1_weight(config.regularization_weight) if rc else 0.0
    l2 = rc.l2_weight(config.regularization_weight) if rc else 0.0
    mode = ("tron" if config.optimizer_type == OptimizerType.TRON
            else "owlqn" if l1 > 0 else "lbfgs")
    factors, shifts = (norm[0], norm[1]) if norm is not None else (None,
                                                                   None)
    lower, upper = bounds if bounds is not None else (None, None)
    return pallas_entity_lbfgs(
        objective.loss, x, labels, offsets, weights, coef0, l2, l1,
        factors=factors, shifts=shifts, lower=lower, upper=upper,
        max_iter=config.max_iterations, tol=config.tolerance,
        mode=mode, interpret=_pallas_interpret())


def _shard_mapped_pallas_solver(objective, config, mesh, x, labels,
                                offsets, weights, coef0, norm=None,
                                bounds=None):
    """Entity-sharded kernel dispatch: one fused kernel per device over
    its shard of the entity axis, results reassembled under the same
    sharding. One implementation for the random-effect and
    factored-latent paths (same non-divergence contract as
    _dispatch_pallas_solver). The gathered normalization/bounds arrays
    shard along the entity axis like everything else."""
    from jax.sharding import PartitionSpec as P

    s2, s3 = P("data", None), P("data", None, None)
    out_specs = OptimizerResult(
        x=s2, value=P("data"), grad_norm=P("data"),
        iterations=P("data"), reason=P("data"),
        value_history=None, grad_norm_history=None, coef_history=None)
    norm_specs = None if norm is None else tuple(
        None if a is None else s2 for a in norm)
    bounds_specs = None if bounds is None else (s2, s2)

    def local_solve(x_l, labels_l, off_l, w_l, c0_l, norm_l, bounds_l):
        return _dispatch_pallas_solver(objective, config, x_l, labels_l,
                                       off_l, w_l, c0_l, norm=norm_l,
                                       bounds=bounds_l)

    return jax.shard_map(
        local_solve, mesh=mesh,
        in_specs=(s3, s2, s2, s2, s2, norm_specs, bounds_specs),
        out_specs=out_specs,
        # pallas_call's out_shapes carry no varying-mesh-axes info
        check_vma=False,
    )(x, labels, offsets, weights, coef0, norm, bounds)


def _pallas_interpret() -> bool:
    """PHOTON_ML_TPU_PALLAS_INTERPRET=1 forces the Pallas entity solver
    (interpreter mode) on any backend — an end-to-end drive of the kernel
    code path without TPU hardware. Trace-time, like NO_PALLAS."""
    import os

    return os.environ.get("PHOTON_ML_TPU_PALLAS_INTERPRET") == "1"


_FALLBACK_WARNED: set = set()


def _warn_fallback(reason: str):
    """One warning per distinct reason when a TPU run silently loses the
    fused-kernel path — surfacing what used to be an invisible perf
    cliff (VERDICT r3 weak #4)."""
    if reason not in _FALLBACK_WARNED:
        _FALLBACK_WARNED.add(reason)
        import logging

        logging.getLogger(__name__).warning(
            "random-effect solve falling back to the vmapped path (%s); "
            "the fused Pallas kernel does not cover this configuration",
            reason)


def _use_pallas_entity_solver(objective, config, x,
                              sharded: bool, norm=None,
                              bounds=None) -> bool:
    """The fused Pallas kernel covers the random-effect solve
    configurations: TPU backend, L-BFGS (L2, box constraints via
    projected trials) or OWL-QN (L1/elastic-net) or TRON
    (twice-differentiable losses, L2-only, box constraints via
    projected trust-region trials), with or without per-entity
    normalization, dense blocks that fit the kernel's VMEM working
    set. Mesh-sharded blocks are ALSO kernel-eligible —
    _solve_block wraps the kernel in shard_map (one kernel per device
    over its entity shard) and passes sharded=False here to express
    that; sharded=True means "sharded with no mesh to scope a
    per-device kernel" and falls back to the portable vmapped path.

    ``sharded`` must be decided by the caller at the Python level (the
    coordinate knows whether a mesh shards its blocks) — inside a trace
    ``x`` is a tracer and carries no sharding. All checks here use
    only static information (config, shapes, backend), so the decision
    is stable for a given jit cache entry. PHOTON_ML_TPU_NO_PALLAS=1
    disables the kernel; the flag is read when a solve first TRACES, so
    set it before building coordinates, not mid-run (jit-cached entries
    keep the path they were traced with)."""
    import os

    from photon_ml_tpu.optimization.config import OptimizerType
    from photon_ml_tpu.ops.pallas_entity_solver import (
        entity_solver_vmem_bytes,
    )

    if os.environ.get("PHOTON_ML_TPU_NO_PALLAS") == "1":
        return False
    on_tpu = jax.default_backend() == "tpu" or _pallas_interpret()
    if not on_tpu:  # interpret: kernel on any backend
        return False
    if sharded:
        _warn_fallback("entity-sharded blocks with no mesh in scope")
        return False
    rc = config.regularization_context
    l1 = rc.l1_weight(config.regularization_weight) if rc else 0.0
    if config.optimizer_type not in (OptimizerType.LBFGS,
                                     OptimizerType.TRON):
        _warn_fallback(f"optimizer {config.optimizer_type}")
        return False
    if config.optimizer_type == OptimizerType.TRON:
        # solve_glm raises for TRON + L1 or a once-differentiable loss;
        # the vmapped fallback preserves those error contracts.
        if l1 > 0 or not objective.loss.twice_differentiable:
            return False
    if bounds is not None and l1 > 0:
        # solve_glm raises for L1 + bounds; preserve the error contract.
        return False
    if objective.normalization is not None:
        # Objective-level (global-context) normalization is the fixed
        # effect's path; per-entity normalization reaches the kernel via
        # the gathered ``norm`` arrays instead.
        _warn_fallback("objective-level normalization context")
        return False
    # VMEM working set per 128-entity grid step, from the same constants
    # the kernel dispatch uses (ops/pallas_entity_solver.py). Stay well
    # under the ~16 MB/core budget; oversize buckets keep the vmapped
    # path.
    e, r, d = x.shape
    itemsize = np.dtype(x.dtype).itemsize
    vmem = entity_solver_vmem_bytes(
        r, d, itemsize, normalized=norm is not None,
        bounded=bounds is not None)
    if vmem >= 10 * 2**20:
        _warn_fallback(
            f"bucket working set ~{vmem >> 20} MiB exceeds the VMEM "
            f"budget (r={r}, d={d})")
        return False
    return True


@functools.partial(
    jax.jit, static_argnames=("objective", "config", "sharded", "mesh"))
def _solve_block(
    objective: GLMObjective, config: GLMOptimizationConfiguration,
    block: EntityBlock, residual_scores, coefs0, sharded: bool = False,
    mesh=None, norm=None, bounds=None,
):
    """One batched solve over the bucket's entity axis, jitted so the whole
    batched solve (trace included) is cached across coordinate-descent
    iterations. ``objective`` hashes by identity and ``config`` by value —
    both stable for a persistent coordinate. The residual gather (the
    reference's addScoresToOffsets join) fuses into the same dispatch.
    ``norm`` = gathered (factors, shifts, intercept_mask), ``bounds`` =
    gathered (lower, upper) — both per-entity local-space arrays; coef0
    and the returned coefficients are in the SOLVE space (normalized
    when ``norm`` is set; the coordinate owns the space transforms).

    On TPU the standard random-effect configurations (L-BFGS/L2 incl.
    box constraints, OWL-QN elastic-net, and TRON — all with optional
    normalization) route to the fused Pallas kernel
    (ops/pallas_entity_solver.py) — the whole per-entity solve as one
    kernel, ~5x over the vmapped op-by-op path. With a mesh, the kernel
    runs per device over the entity-sharded bucket via ``shard_map``
    (each device solves its own 1/n of the entities — entity sharding
    composed with the kernel; sentinel padding entities converge
    instantly). Remaining fallbacks (oversize VMEM, CPU) use the
    portable vmapped solver."""
    offsets = block.offsets
    extra = _gather_residual(residual_scores, block)
    if extra is not None:
        offsets = offsets + extra.astype(offsets.dtype)

    # With a mesh the kernel is still eligible — it runs per device via
    # shard_map below — so the "sharded" rejection only applies when no
    # mesh is available to scope it.
    use_kernel = _use_pallas_entity_solver(
        objective, config, block.x, sharded=sharded and mesh is None,
        norm=norm, bounds=bounds)

    if use_kernel and sharded and mesh is not None:
        return _shard_mapped_pallas_solver(
            objective, config, mesh, block.x, block.labels, offsets,
            block.weights, coefs0, norm=norm, bounds=bounds)

    if use_kernel:
        return _dispatch_pallas_solver(objective, config, block.x,
                                       block.labels, offsets,
                                       block.weights, coefs0, norm=norm,
                                       bounds=bounds)

    def fit_one(coef0, x, y, off, w, norm_e, bounds_e):
        from photon_ml_tpu.ops.features import DenseFeatures

        if norm_e is not None:
            fac, shf, _ = norm_e
            # Normalize by rewriting the entity's dense rows inside the
            # jitted solve (a fusion, not a persistent HBM copy) — the
            # solve then runs in the normalized space directly, exactly
            # like the kernel's in-VMEM x' transform.
            if shf is not None:
                x = x - shf[None, :]
            if fac is not None:
                x = x * fac[None, :]
        lb, ub = bounds_e if bounds_e is not None else (None, None)
        batch = GLMBatch(DenseFeatures(x), y, off, w)
        return solve_glm(objective, batch, config, coef0, lb, ub)

    return jax.vmap(fit_one)(coefs0, block.x, block.labels, offsets,
                             block.weights, norm, bounds)


@functools.partial(
    jax.jit, static_argnames=("objective", "config", "is_classification"))
def _solve_fixed(
    objective: GLMObjective, config: GLMOptimizationConfiguration,
    is_classification: bool, batch: GLMBatch, residual_scores, rng_key,
    coef0, lower_bounds, upper_bounds, normalization,
):
    """The full fixed-effect update as one dispatch: residual->offsets,
    down-sampling, normalized-space solve, back-transform."""
    if residual_scores is not None:
        # The batch may be row-padded for sharding; pad the residual with
        # zeros to match (padding rows have weight 0, so the value added
        # there is irrelevant).
        pad = batch.num_rows - residual_scores.shape[0]
        if pad:
            residual_scores = jnp.concatenate(
                [residual_scores, jnp.zeros((pad,), residual_scores.dtype)])
        batch = batch.with_offsets(
            batch.offsets + residual_scores.astype(batch.offsets.dtype))
    weights = down_sample_weights(
        rng_key, batch.labels, batch.weights, config.down_sampling_rate,
        is_classification)
    batch = GLMBatch(batch.features, batch.labels, batch.offsets, weights)
    if normalization is not None:
        coef0 = normalization.model_to_normalized_space(coef0)
    result = solve_glm(objective, batch, config, coef0,
                       lower_bounds, upper_bounds)
    coef = result.x
    if normalization is not None:
        coef = normalization.model_to_original_space(coef)
    return result, coef


@functools.partial(jax.jit, static_argnames=("n_rows",))
def _fe_score_impl(coef, feats, n_rows: int):
    return feats.matvec(coef)[:n_rows]


def _scatter_margins(scores, block, margins, n_rows):
    m = jnp.where(block.row_ids < n_rows, margins, 0.0)
    return scores.at[block.row_ids.reshape(-1)].add(m.reshape(-1))


@functools.partial(jax.jit, static_argnames=("n_rows",))
def _re_score_impl(blocks, pblocks, coefs, n_rows: int):
    scores = jnp.zeros((n_rows + 1,),
                       coefs[0].dtype if coefs else jnp.float32)
    for block, c in zip(blocks, coefs):
        scores = _scatter_margins(scores, block, block.local_margins(c),
                                  n_rows)
    for block, c in zip(pblocks, coefs):
        if block is not None:
            scores = _scatter_margins(scores, block, block.local_margins(c),
                                      n_rows)
    return scores[:-1]


@functools.partial(jax.jit, static_argnames=("n_rows", "d"))
def _fre_score_impl(blocks, pblocks, gammas, B, n_rows: int, d: int):
    def block_margins(block, gamma):
        coefs = gamma @ B  # [E, d]
        pad = block.d_pad - d
        if pad:
            coefs = jnp.pad(coefs, ((0, 0), (0, pad)))
        return block.local_margins(coefs)

    scores = jnp.zeros((n_rows + 1,), B.dtype)
    for block, g in zip(blocks, gammas):
        scores = _scatter_margins(scores, block, block_margins(block, g),
                                  n_rows)
    for block, g in zip(pblocks, gammas):
        if block is not None:
            scores = _scatter_margins(scores, block, block_margins(block, g),
                                      n_rows)
    return scores[:-1]
