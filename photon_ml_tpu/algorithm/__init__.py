"""GAME block-coordinate-descent algorithm layer."""

from photon_ml_tpu.algorithm.coordinates import (
    Coordinate,
    FactoredRandomEffectCoordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
    StreamingFactoredRandomEffectCoordinate,
    StreamingFixedEffectCoordinate,
)
from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent

__all__ = [
    "Coordinate",
    "FactoredRandomEffectCoordinate",
    "FixedEffectCoordinate",
    "RandomEffectCoordinate",
    "StreamingFactoredRandomEffectCoordinate",
    "StreamingFixedEffectCoordinate",
    "CoordinateDescent",
]
