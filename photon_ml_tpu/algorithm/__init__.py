"""GAME block-coordinate-descent algorithm layer."""

from photon_ml_tpu.algorithm.coordinates import (
    Coordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent

__all__ = [
    "Coordinate",
    "FixedEffectCoordinate",
    "RandomEffectCoordinate",
    "CoordinateDescent",
]
