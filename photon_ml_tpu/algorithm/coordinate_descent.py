"""Block coordinate descent over named coordinates — the GAME outer loop.

Reference: ml/algorithm/CoordinateDescent.scala:41-271. Semantics preserved:
for each iteration, for each coordinate in the updating sequence —
subtract the coordinate's own score from the total (residual), re-solve
against the residual as extra offsets, re-score, recompute the full
objective = sum_i w_i l(total_score_i + offset_i, y_i) + sum_c reg_c, and
track the best full model by the first validation evaluator.

TPU re-design: scores are dense device vectors, so the reference's
KeyValueScore fullOuterJoin +/- algebra (partial-score reduce at
CoordinateDescent.scala:150-158) is elementwise add/subtract in HBM, and the
per-coordinate "addScoresToOffsets" shuffle is a gather.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinates import Coordinate
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.evaluation.evaluators import Evaluator
from photon_ml_tpu.models.game_model import GameModel
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)

Array = jax.Array


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    objective_history: List[float]  # one entry per coordinate update
    validation_history: List[Dict[str, float]]  # one entry per iteration
    best_model: Optional[GameModel]
    best_metric: Optional[float]
    trackers: Dict[str, list]  # coordinate name -> per-update OptimizerResults
    timings: Dict[str, float]


class CoordinateDescent:
    def __init__(
        self,
        coordinates: Dict[str, Coordinate],  # ordered updating sequence
        task_type: TaskType,
        validation_data: Optional[GameDataset] = None,
        validation_evaluators: Sequence[Evaluator] = (),
    ):
        if not coordinates:
            raise ValueError("at least one coordinate is required")
        self.coordinates = dict(coordinates)
        self.task_type = task_type
        self.validation_data = validation_data
        self.validation_evaluators = list(validation_evaluators)

    def run(
        self,
        num_iterations: int,
        seed: int = 0,
        initial_model: Optional[GameModel] = None,
        checkpoint_dir=None,
        checkpoint_interval: int = 1,
        checkpoint_tag: str = "",
    ) -> CoordinateDescentResult:
        """checkpoint_dir: save resumable state every `checkpoint_interval`
        coordinate updates, and resume from the latest checkpoint found
        there (the reference has no mid-training checkpointing — SURVEY §5;
        per-step keys use fold_in so a resumed run is bit-identical to an
        uninterrupted one). checkpoint_tag: caller-supplied configuration
        fingerprint folded into the checkpoint identity check."""
        from photon_ml_tpu.utils import checkpoint as ckpt

        if checkpoint_interval < 1:
            raise ValueError(
                f"checkpoint_interval must be >= 1, got {checkpoint_interval}")
        loss = loss_for_task(self.task_type)
        names = list(self.coordinates)

        if initial_model is None:
            models = {n: c.initialize_model()
                      for n, c in self.coordinates.items()}
        else:
            models = {n: initial_model.get_model(n) for n in names}

        base_key = jax.random.PRNGKey(seed)
        objective_history: List[float] = []
        validation_history: List[Dict[str, float]] = []
        trackers: Dict[str, list] = {n: [] for n in names}
        timings: Dict[str, float] = {n: 0.0 for n in names}
        best_model, best_metric = None, None
        done_steps = 0
        meta = {"seed": seed, "coordinates": names,
                "taskType": self.task_type.value, "tag": checkpoint_tag}

        def _save(step):
            # Materialize IN PLACE so each device scalar is transferred
            # exactly once across the run, not once per checkpoint.
            objective_history[:] = _as_floats(objective_history)
            ckpt.save_checkpoint(checkpoint_dir, ckpt.CheckpointState(
                step=step, models=models,
                objective_history=list(objective_history),
                validation_history=validation_history,
                best_metric=best_metric,
                best_models=(dict(best_model.models)
                             if best_model is not None else None),
                timings=timings, trackers=trackers, meta=meta))

        if checkpoint_dir is not None:
            latest = ckpt.latest_checkpoint(checkpoint_dir)
            if latest is not None:
                state = ckpt.load_checkpoint(latest)
                if state.meta is not None and state.meta != meta:
                    raise ValueError(
                        f"checkpoint {latest} belongs to a different "
                        f"configuration (saved {state.meta}, current {meta});"
                        " point --checkpoint-dir elsewhere or delete it")
                done_steps = state.step
                models = dict(state.models)
                objective_history = list(state.objective_history)
                validation_history = list(state.validation_history)
                best_metric = state.best_metric
                timings = dict(state.timings)
                trackers = {n: list(state.trackers.get(n, []))
                            for n in names}
                if state.best_models is not None:
                    best_model = GameModel(dict(state.best_models),
                                           self.task_type)
                logger.info("resumed from %s (step %d)", latest, done_steps)

        scores: Dict[str, Array] = {
            n: self.coordinates[n].score(models[n]) for n in names}

        validating = (self.validation_data is not None
                      and bool(self.validation_evaluators))
        step = 0
        for it in range(num_iterations):
            for ci, n in enumerate(names):
                step += 1
                if step <= done_steps:
                    continue  # resumed past this update
                coord = self.coordinates[n]
                t0 = time.perf_counter()
                # Deterministic per-step key: resume-invariant, unlike
                # sequential splitting.
                sub = jax.random.fold_in(base_key, step)
                # Single coordinate: residual is None (no other scores) —
                # mirrors CoordinateDescent.scala's descend-only-one branch.
                # The residual is reduced FRESH from the other coordinates'
                # scores every step (the reference's partial-score reduce,
                # CoordinateDescent.scala:150-158) rather than kept as a
                # running total: identical models then take an identical
                # arithmetic path, which is what makes a resumed run match
                # an uninterrupted one bit-for-bit in f32.
                residual = _residual_of_others(scores, names, n)
                models[n], tracker = coord.update_model(
                    models[n], residual, sub)
                trackers[n].append(tracker)
                scores[n] = coord.score(models[n])
                total = (scores[n] if residual is None
                         else residual + scores[n])
                timings[n] += time.perf_counter() - t0

                # Device scalar — NOT synced here. A float() per coordinate
                # update costs a full host<->device round trip; histories are
                # materialized at checkpoint/return instead.
                obj = self._training_objective(loss, total, models)
                objective_history.append(obj)
                if logger.isEnabledFor(logging.INFO):
                    logger.info("iter %d coordinate %s: objective=%.6f", it,
                                n, float(obj))
                # Defer the last-coordinate save to after validation: one
                # save per iteration boundary, and a crash during validation
                # resumes from before the final update, so the re-run never
                # skips the iteration's validation/best-model bookkeeping.
                last_of_iteration = ci == len(names) - 1
                if (checkpoint_dir is not None
                        and step % checkpoint_interval == 0
                        and not (last_of_iteration and validating)):
                    _save(step)

            if step <= done_steps:
                continue  # whole iteration was restored, incl. validation
            if validating:
                game_model = GameModel(dict(models), self.task_type)
                val_scores = game_model.score(self.validation_data)
                metrics = {
                    ev.name: ev.evaluate_dataset(val_scores,
                                                 self.validation_data)
                    for ev in self.validation_evaluators}
                validation_history.append(metrics)
                head = self.validation_evaluators[0]
                m0 = metrics[head.name]
                if head.better_than(m0, best_metric):
                    best_metric, best_model = m0, game_model
                logger.info("iter %d validation: %s", it, metrics)
                if checkpoint_dir is not None:
                    # The iteration-boundary save, carrying this iteration's
                    # validation entry + best model.
                    _save(step)

        final = GameModel(dict(models), self.task_type)
        if best_model is None:
            best_model = final
        return CoordinateDescentResult(
            model=final,
            objective_history=_as_floats(objective_history),
            validation_history=validation_history,
            best_model=best_model,
            best_metric=best_metric,
            trackers=trackers,
            timings=timings,
        )

    def _training_objective(self, loss, total_scores: Array, models):
        """Full training objective as a DEVICE scalar (one jitted dispatch,
        no host sync) — the eager version cost several host<->device round
        trips per coordinate update on a remote chip."""
        labels, offsets, weights = self._training_rows(total_scores.dtype)
        penalties = tuple(
            tuple(self.coordinates[n].penalties(models[n]))
            for n in self.coordinates)
        return _objective_impl(loss, total_scores, labels, offsets,
                               weights, penalties)

    def _training_rows(self, dtype) -> Tuple[Array, Array, Array]:
        """(labels, offsets, weights) aligned with the global row order,
        taken from the first coordinate's data. Cached — built once per run,
        kept in HBM."""
        cached = getattr(self, "_rows_cache", None)
        if cached is not None:
            return cached
        first = self.coordinates[list(self.coordinates)[0]]
        data = getattr(first, "data", None)
        if isinstance(data, GameDataset):
            rows = (jnp.asarray(data.responses, dtype),
                    jnp.asarray(data.offsets, dtype),
                    jnp.asarray(data.weights, dtype))
        else:
            # Random-effect-only: reconstruct from the blocks.
            rows = _rows_from_blocks(first.dataset)
            rows = tuple(r.astype(dtype) for r in rows)
        self._rows_cache = rows
        return rows


def _residual_of_others(scores: Dict[str, Array], names: Sequence[str],
                        current: str) -> Optional[Array]:
    others = [scores[m] for m in names if m != current]
    if not others:
        return None
    if len(others) == 1:
        return others[0]
    return jnp.sum(jnp.stack(others), axis=0)


def _as_floats(history) -> List[float]:
    """Materialize a history of (device-scalar | float) objective values with
    one batched transfer rather than one sync per entry."""
    if not history:
        return []
    arrs = [v for v in history if isinstance(v, jax.Array)]
    if arrs:
        jax.block_until_ready(arrs[-1])
    return [float(v) for v in history]


@functools.partial(jax.jit, static_argnames=("loss",))
def _objective_impl(loss, total_scores, labels, offsets, weights, penalties):
    """Full coordinate-descent objective: weighted loss on total scores plus
    every coordinate's penalty (CoordinateDescent.scala:203-212).
    ``penalties`` is a nested tuple of (coefs, l1, l2) device triples."""
    out = jnp.sum(weights * loss.loss(total_scores + offsets, labels))
    for coord_penalties in penalties:
        for c, l1, l2 in coord_penalties:
            out = out + 0.5 * l2 * jnp.sum(jnp.square(c))
            out = out + l1 * jnp.sum(jnp.abs(c))
    return out


def _rows_from_blocks(ds) -> Tuple[Array, Array, Array]:
    n = ds.n_rows
    labels = np.zeros(n + 1, np.float32)
    offsets = np.zeros(n + 1, np.float32)
    weights = np.zeros(n + 1, np.float32)
    for blocks in (ds.blocks, [b for b in ds.passive_blocks if b is not None]):
        for b in blocks:
            rid = np.asarray(b.row_ids).ravel()
            labels[rid] = np.asarray(b.labels).ravel()
            offsets[rid] = np.asarray(b.offsets).ravel()
            weights[rid] = np.asarray(b.weights).ravel()
    return (jnp.asarray(labels[:-1]), jnp.asarray(offsets[:-1]),
            jnp.asarray(weights[:-1]))
