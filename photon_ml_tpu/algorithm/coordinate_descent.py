"""Block coordinate descent over named coordinates — the GAME outer loop.

Reference: ml/algorithm/CoordinateDescent.scala:41-271. Semantics preserved:
for each iteration, for each coordinate in the updating sequence —
subtract the coordinate's own score from the total (residual), re-solve
against the residual as extra offsets, re-score, recompute the full
objective = sum_i w_i l(total_score_i + offset_i, y_i) + sum_c reg_c, and
track the best full model by the first validation evaluator.

TPU re-design: scores are dense device vectors, so the reference's
KeyValueScore fullOuterJoin +/- algebra (partial-score reduce at
CoordinateDescent.scala:150-158) is elementwise add/subtract in HBM, and the
per-coordinate "addScoresToOffsets" shuffle is a gather.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinates import Coordinate
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.evaluation.evaluators import Evaluator
from photon_ml_tpu.models.game_model import GameModel
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)

Array = jax.Array


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    objective_history: List[float]  # one entry per coordinate update
    validation_history: List[Dict[str, float]]  # one entry per iteration
    best_model: Optional[GameModel]
    best_metric: Optional[float]
    trackers: Dict[str, list]  # coordinate name -> per-update OptimizerResults
    timings: Dict[str, float]


class CoordinateDescent:
    def __init__(
        self,
        coordinates: Dict[str, Coordinate],  # ordered updating sequence
        task_type: TaskType,
        validation_data: Optional[GameDataset] = None,
        validation_evaluators: Sequence[Evaluator] = (),
    ):
        if not coordinates:
            raise ValueError("at least one coordinate is required")
        self.coordinates = dict(coordinates)
        self.task_type = task_type
        self.validation_data = validation_data
        self.validation_evaluators = list(validation_evaluators)

    def run(
        self,
        num_iterations: int,
        seed: int = 0,
        initial_model: Optional[GameModel] = None,
    ) -> CoordinateDescentResult:
        loss = loss_for_task(self.task_type)
        names = list(self.coordinates)

        if initial_model is None:
            models = {n: c.initialize_model()
                      for n, c in self.coordinates.items()}
        else:
            models = {n: initial_model.get_model(n) for n in names}

        scores: Dict[str, Array] = {
            n: self.coordinates[n].score(models[n]) for n in names}
        total = jnp.sum(jnp.stack(list(scores.values())), axis=0)

        key = jax.random.PRNGKey(seed)
        objective_history: List[float] = []
        validation_history: List[Dict[str, float]] = []
        trackers: Dict[str, list] = {n: [] for n in names}
        timings: Dict[str, float] = {n: 0.0 for n in names}
        best_model, best_metric = None, None

        for it in range(num_iterations):
            for n in names:
                coord = self.coordinates[n]
                t0 = time.perf_counter()
                key, sub = jax.random.split(key)
                # Single coordinate: residual is None (no other scores) —
                # mirrors CoordinateDescent.scala's descend-only-one branch.
                residual = None if len(names) == 1 else total - scores[n]
                models[n], tracker = coord.update_model(
                    models[n], residual, sub)
                trackers[n].append(tracker)
                scores[n] = coord.score(models[n])
                total = (scores[n] if residual is None
                         else residual + scores[n])
                timings[n] += time.perf_counter() - t0

                obj = self._training_objective(loss, total, models)
                objective_history.append(obj)
                logger.info("iter %d coordinate %s: objective=%.6f", it, n,
                            obj)

            if self.validation_data is not None and self.validation_evaluators:
                game_model = GameModel(dict(models), self.task_type)
                val_scores = game_model.score(self.validation_data)
                metrics = {
                    ev.name: ev.evaluate_dataset(val_scores,
                                                 self.validation_data)
                    for ev in self.validation_evaluators}
                validation_history.append(metrics)
                head = self.validation_evaluators[0]
                m0 = metrics[head.name]
                if head.better_than(m0, best_metric):
                    best_metric, best_model = m0, game_model
                logger.info("iter %d validation: %s", it, metrics)

        final = GameModel(dict(models), self.task_type)
        if best_model is None:
            best_model = final
        return CoordinateDescentResult(
            model=final,
            objective_history=objective_history,
            validation_history=validation_history,
            best_model=best_model,
            best_metric=best_metric,
            trackers=trackers,
            timings=timings,
        )

    def _training_objective(self, loss, total_scores: Array, models) -> float:
        labels, offsets, weights = self._training_rows(total_scores.dtype)
        data_term = jnp.sum(
            weights * loss.loss(total_scores + offsets, labels))
        reg = sum(self.coordinates[n].regularization_term(models[n])
                  for n in self.coordinates)
        # Single host sync for the whole objective (device scalars only).
        return float(data_term + reg)

    def _training_rows(self, dtype) -> Tuple[Array, Array, Array]:
        """(labels, offsets, weights) aligned with the global row order,
        taken from the first coordinate's data. Cached — built once per run,
        kept in HBM."""
        cached = getattr(self, "_rows_cache", None)
        if cached is not None:
            return cached
        first = self.coordinates[list(self.coordinates)[0]]
        data = getattr(first, "data", None)
        if isinstance(data, GameDataset):
            rows = (jnp.asarray(data.responses, dtype),
                    jnp.asarray(data.offsets, dtype),
                    jnp.asarray(data.weights, dtype))
        else:
            # Random-effect-only: reconstruct from the blocks.
            rows = _rows_from_blocks(first.dataset)
            rows = tuple(r.astype(dtype) for r in rows)
        self._rows_cache = rows
        return rows


def _rows_from_blocks(ds) -> Tuple[Array, Array, Array]:
    n = ds.n_rows
    labels = np.zeros(n + 1, np.float32)
    offsets = np.zeros(n + 1, np.float32)
    weights = np.zeros(n + 1, np.float32)
    for blocks in (ds.blocks, [b for b in ds.passive_blocks if b is not None]):
        for b in blocks:
            rid = np.asarray(b.row_ids).ravel()
            labels[rid] = np.asarray(b.labels).ravel()
            offsets[rid] = np.asarray(b.offsets).ravel()
            weights[rid] = np.asarray(b.weights).ravel()
    return (jnp.asarray(labels[:-1]), jnp.asarray(offsets[:-1]),
            jnp.asarray(weights[:-1]))
