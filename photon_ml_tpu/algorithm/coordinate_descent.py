"""Block coordinate descent over named coordinates — the GAME outer loop.

Reference: ml/algorithm/CoordinateDescent.scala:41-271. Semantics preserved:
for each iteration, for each coordinate in the updating sequence —
subtract the coordinate's own score from the total (residual), re-solve
against the residual as extra offsets, re-score, recompute the full
objective = sum_i w_i l(total_score_i + offset_i, y_i) + sum_c reg_c, and
track the best full model by the first validation evaluator.

TPU re-design: scores are dense device vectors, so the reference's
KeyValueScore fullOuterJoin +/- algebra (partial-score reduce at
CoordinateDescent.scala:150-158) is elementwise add/subtract in HBM, and the
per-coordinate "addScoresToOffsets" shuffle is a gather.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from photon_ml_tpu.algorithm.coordinates import Coordinate
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.evaluation.evaluators import Evaluator
from photon_ml_tpu.models.game_model import GameModel
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils.tracing_guard import TracingGuard

logger = logging.getLogger(__name__)

Array = jax.Array


def _unstack_tracker_block(trs: Dict[str, object], names: Sequence[str],
                           base: Dict[str, list]) -> None:
    """Append one block's host tracker pytrees (leading n_iters axis) into
    per-coordinate per-update lists — shared by eager (checkpoint-save) and
    lazy materialization so both produce identical entry shapes."""
    n_iters = jax.tree.leaves(trs[names[0]])[0].shape[0]
    for i in range(n_iters):
        for n in names:
            tr = jax.tree.map(lambda a: a[i], trs[n])
            if isinstance(tr, tuple):
                tr = list(tr)
            base[n].append(tr)


class LazyTrackers(Mapping):
    """coordinate name -> per-update optimizer trackers, materialized from
    device on FIRST ACCESS. Tracker pytrees (per-entity value/gnorm
    histories) are the largest per-update artifacts; fetching them eagerly
    at run end would serialize a multi-MB device->host transfer into every
    training run whether or not the caller ever looks at telemetry."""

    def __init__(self, base: Dict[str, list],
                 pending: List[dict], names: Sequence[str]):
        self._base = base
        self._pending = list(pending)
        self._names = list(names)

    def _force(self) -> None:
        if not self._pending:
            return
        host_blocks = jax.device_get(self._pending)
        self._pending = []
        for trs in host_blocks:
            _unstack_tracker_block(trs, self._names, self._base)

    def __getitem__(self, key):
        self._force()
        return self._base[key]

    def __iter__(self):
        self._force()
        return iter(self._base)

    def __len__(self):
        return len(self._base)


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    objective_history: List[float]  # one entry per coordinate update
    validation_history: List[Dict[str, float]]  # one entry per iteration
    best_model: Optional[GameModel]
    best_metric: Optional[float]
    # coordinate name -> per-update OptimizerResults (device telemetry is
    # fetched lazily on first access — see LazyTrackers)
    trackers: Mapping[str, list]
    timings: Dict[str, float]


class CoordinateDescent:
    def __init__(
        self,
        coordinates: Dict[str, Coordinate],  # ordered updating sequence
        task_type: TaskType,
        validation_data: Optional[GameDataset] = None,
        validation_evaluators: Sequence[Evaluator] = (),
    ):
        if not coordinates:
            raise ValueError("at least one coordinate is required")
        self.coordinates = dict(coordinates)
        self.task_type = task_type
        self.validation_data = validation_data
        self.validation_evaluators = list(validation_evaluators)
        self._fused_fns = None
        self._block_fns: Dict[int, object] = {}
        self._val_scorer = None
        # Shared retrace infrastructure (utils/tracing_guard.py): every
        # fused executable registers here, and run() asserts the hot
        # loop's compile-count invariant — each executable traces exactly
        # once — instead of trusting it silently.
        self.tracing_guard = TracingGuard()

    def _fused_update_fns(self):
        """One jitted function per coordinate performing the ENTIRE update —
        residual reduce, solve (all buckets), re-score, full objective — as a
        single device dispatch. On a remote chip the eager sequence cost
        ~5-6 dispatches x tunnel latency per update; fused it costs one.

        Data pytrees are passed as ARGUMENTS (not trace constants) so the
        compiled executables reference buffers, and params of every
        coordinate flow in so the objective's penalty terms evaluate
        on-device with no model materialization."""
        if self._fused_fns is not None:
            return self._fused_fns
        loss = loss_for_task(self.task_type)
        names = list(self.coordinates)

        def make(n):
            coord = self.coordinates[n]

            def fused(data, pdata_all, params_all, other_scores, base_key,
                      step, rows):
                residual = None
                for s in other_scores:
                    residual = s if residual is None else residual + s
                key = jax.random.fold_in(base_key, step)
                new_p, tracker = coord.pure_update(
                    data, params_all[n], residual, key)
                score = coord.pure_score(data, new_p)
                total = score if residual is None else residual + score
                labels, offsets, weights = rows
                obj = jnp.sum(weights * loss.loss(total + offsets, labels))
                for m in names:
                    pm = new_p if m == n else params_all[m]
                    for c, l1, l2 in self.coordinates[m].pure_penalties(
                            pm, pdata_all[m]):
                        obj = obj + 0.5 * l2 * jnp.sum(jnp.square(c))
                        obj = obj + l1 * jnp.sum(jnp.abs(c))
                return new_p, score, obj, tracker

            return jax.jit(fused)

        self._fused_fns = {n: make(n) for n in names}
        for n, fn in self._fused_fns.items():
            self.tracing_guard.track(f"fused:{n}", fn)
        return self._fused_fns

    def _fused_block_fn(self, n_iters: int):
        """ONE jitted dispatch executing `n_iters` FULL coordinate-descent
        iterations (every coordinate, in sequence) via lax.scan.

        Per-dispatch latency to a remote TPU is ~7-70 ms — at the bench
        shapes that latency, not device time, dominated the per-step path
        (one dispatch per coordinate update). Scanning whole iterations on
        device leaves one dispatch per sync point (validation/checkpoint/
        run end); loop boundaries inside the scan cost ~0.14 ms.

        Returns (params, scores, objs[n_iters, n_coords], trackers) where
        tracker leaves carry a leading n_iters axis; everything stays on
        device until `_materialize` fetches it in a single transfer.

        Semantics are identical to the per-step path: same residual
        recompute, same fold_in(base_key, step) key per update, same full
        objective (reference: CoordinateDescent.scala:150-212).
        """
        fn = self._block_fns.get(n_iters)
        if fn is not None:
            return fn
        loss = loss_for_task(self.task_type)
        names = list(self.coordinates)
        n_coords = len(names)

        def block(data_args, pdata_args, params, scores, base_key, step0,
                  rows):
            labels, offsets, weights = rows

            def one_iteration(carry, it_idx):
                params, scores = carry
                objs = []
                trs = {}
                for ci, n in enumerate(names):
                    coord = self.coordinates[n]
                    step = (step0 + it_idx * np.uint32(n_coords)
                            + np.uint32(ci + 1))
                    residual = None
                    for m in names:
                        if m == n:
                            continue
                        residual = (scores[m] if residual is None
                                    else residual + scores[m])
                    key = jax.random.fold_in(base_key, step)
                    new_p, tracker = coord.pure_update(
                        data_args[n], params[n], residual, key)
                    sc = coord.pure_score(data_args[n], new_p)
                    params = {**params, n: new_p}
                    scores = {**scores, n: sc}
                    total = sc if residual is None else residual + sc
                    obj = jnp.sum(
                        weights * loss.loss(total + offsets, labels))
                    for m in names:
                        for c, l1, l2 in self.coordinates[m].pure_penalties(
                                params[m], pdata_args[m]):
                            obj = obj + 0.5 * l2 * jnp.sum(jnp.square(c))
                            obj = obj + l1 * jnp.sum(jnp.abs(c))
                    objs.append(obj)
                    trs[n] = tracker
                return (params, scores), (jnp.stack(objs), trs)

            (params, scores), (objs, trs) = lax.scan(
                one_iteration, (params, scores),
                jnp.arange(n_iters, dtype=jnp.uint32))
            return params, scores, objs, trs

        fn = jax.jit(block)
        self._block_fns[n_iters] = fn
        self.tracing_guard.track(f"block:{n_iters}", fn)
        return fn

    def run(
        self,
        num_iterations: int,
        seed: int = 0,
        initial_model: Optional[GameModel] = None,
        checkpoint_dir=None,
        checkpoint_interval: int = 1,
        checkpoint_tag: Union[str, Mapping[str, str]] = "",
    ) -> CoordinateDescentResult:
        """checkpoint_dir: save resumable state every `checkpoint_interval`
        coordinate updates, and resume from the latest checkpoint found
        there (the reference has no mid-training checkpointing — SURVEY §5;
        per-step keys use fold_in so a resumed run is bit-identical to an
        uninterrupted one). checkpoint_tag: caller-supplied configuration
        fingerprint (str or mapping) folded into the checkpoint identity
        check; mappings are compared canonically (key order is cosmetic)."""
        from photon_ml_tpu.utils import checkpoint as ckpt

        if checkpoint_interval < 1:
            raise ValueError(
                f"checkpoint_interval must be >= 1, got {checkpoint_interval}")
        names = list(self.coordinates)

        if initial_model is None:
            models = {n: c.initialize_model()
                      for n, c in self.coordinates.items()}
        else:
            models = {n: initial_model.get_model(n) for n in names}

        base_key = jax.random.PRNGKey(seed)
        objective_history: List[float] = []
        validation_history: List[Dict[str, float]] = []
        trackers: Dict[str, list] = {n: [] for n in names}
        timings: Dict[str, float] = {n: 0.0 for n in names}
        best_model, best_metric = None, None
        done_steps = 0
        meta = {"seed": seed, "coordinates": names,
                "taskType": self.task_type.value,
                "tag": (dict(checkpoint_tag)
                        if isinstance(checkpoint_tag, Mapping)
                        else checkpoint_tag)}

        def _save(step):
            _sync_models()
            _materialize_all()
            ckpt.save_checkpoint(checkpoint_dir, ckpt.CheckpointState(
                step=step, models=models,
                objective_history=list(objective_history),
                validation_history=validation_history,
                best_metric=best_metric,
                best_models=(dict(best_model.models)
                             if best_model is not None else None),
                timings=timings, trackers=trackers, meta=meta))

        if checkpoint_dir is not None:
            latest = ckpt.latest_checkpoint(checkpoint_dir)
            if latest is not None:
                state = ckpt.load_checkpoint(latest)
                # Canonical-fingerprint comparison: benign dict reordering
                # (insertion order of the tag/config mapping) hashes the
                # same, and mapping tags also match their legacy flattened
                # string form; a changed seed, task type, or updating
                # SEQUENCE (list order is semantic) still hard-errors.
                if (state.meta is not None
                        and not (ckpt.meta_fingerprints(state.meta)
                                 & ckpt.meta_fingerprints(meta))):
                    raise ValueError(
                        f"checkpoint {latest} belongs to a different "
                        f"configuration (saved {state.meta}, current {meta});"
                        " point --checkpoint-dir elsewhere or delete it")
                done_steps = state.step
                models = dict(state.models)
                objective_history = list(state.objective_history)
                validation_history = list(state.validation_history)
                best_metric = state.best_metric
                timings = dict(state.timings)
                trackers = {n: list(state.trackers.get(n, []))
                            for n in names}
                if state.best_models is not None:
                    best_model = GameModel(dict(state.best_models),
                                           self.task_type)
                logger.info("resumed from %s (step %d)", latest, done_steps)

        # The fused path: params/scores dicts are the authoritative training
        # state on device; model objects are materialized lazily (checkpoint,
        # validation, return) so the hot loop is exactly ONE dispatch per
        # coordinate update.
        data_args = {n: self.coordinates[n].step_data() for n in names}
        pdata_args = {n: self.coordinates[n].penalty_data() for n in names}
        params = {n: self.coordinates[n].params_of(models[n]) for n in names}
        # Canonicalize param leaves to device arrays: checkpoint-loaded
        # models carry host np.ndarray leaves, and np inputs key a
        # SEPARATE pjit executable from the device arrays of steady-state
        # calls — one silent recompile per coordinate on every resume
        # (surfaced by tracing_guard's per_fn=1 invariant below).
        params = {n: jax.tree.map(jnp.asarray, p)
                  for n, p in params.items()}
        fused = self._fused_update_fns()

        def _sync_models():
            for m in names:
                models[m] = self.coordinates[m].model_of(params[m], models[m])

        scores: Dict[str, Array] = {
            n: self.coordinates[n].pure_score(data_args[n], params[n])
            for n in names}
        rows = self._training_rows(next(iter(scores.values())).dtype)

        # Objective history lives in a FIXED-CAPACITY device vector updated
        # by a tiny jitted set (enqueue-only); materialization is ONE
        # device->host transfer. Per-entry float() syncs cost a full tunnel
        # round trip each (~65-85ms measured on the remote-TPU backend) and
        # dominated whole runs. Capacity is padded to a power of two so the
        # updater executable is shared across runs of different lengths.
        total_steps = max(num_iterations * len(names),
                          len(objective_history))
        cap = max(64, 1 << max(0, total_steps - 1).bit_length())
        hist_dtype = np.dtype(next(iter(scores.values())).dtype)
        hist_dev = jnp.zeros(cap, hist_dtype)
        hist_len = len(objective_history)  # absolute step count written
        mat_hist_len = hist_len  # prefix already materialized (resumed)

        # Device-resident results of fused iteration BLOCKS, appended in
        # step order and fetched host-side in ONE transfer per sync point.
        pending_blocks: List[tuple] = []
        # Tracker blocks left on device at run end (lazy fetch).
        pending_tracker_blocks: List[dict] = []
        n_coords = len(names)

        def _materialize_history():
            nonlocal mat_hist_len
            if hist_len > mat_hist_len:
                vals = np.asarray(hist_dev)[mat_hist_len:hist_len]
                objective_history.extend(float(v) for v in vals)
                mat_hist_len = hist_len

        def _materialize_pending(include_trackers: bool = True):
            if not pending_blocks:
                return
            if include_trackers:
                host_blocks = jax.device_get(pending_blocks)
                for objs, trs in host_blocks:
                    for i in range(objs.shape[0]):
                        for ci in range(n_coords):
                            objective_history.append(float(objs[i, ci]))
                    _unstack_tracker_block(trs, names, trackers)
            else:
                # Objectives only (small); tracker blocks stay on device
                # for lazy fetch via LazyTrackers.
                objs_host = jax.device_get([b[0] for b in pending_blocks])
                for objs in objs_host:
                    for i in range(objs.shape[0]):
                        for ci in range(n_coords):
                            objective_history.append(float(objs[i, ci]))
                pending_tracker_blocks.extend(
                    b[1] for b in pending_blocks)
            pending_blocks.clear()

        def _materialize_all():
            # Per-step entries always precede block entries (the per-step
            # path only runs before blocks start or exclusively), so this
            # order keeps objective_history in step order.
            _materialize_history()
            _materialize_pending()

        validating = (self.validation_data is not None
                      and bool(self.validation_evaluators))
        # Blocks cover whole iterations; they apply when checkpoint saves
        # land on iteration boundaries (otherwise the per-step path below
        # preserves the exact mid-iteration save behavior).
        blockable = (checkpoint_dir is None
                     or checkpoint_interval % n_coords == 0)

        def _run_validation(it):
            nonlocal best_metric, best_model
            _sync_models()
            game_model = GameModel(dict(models), self.task_type)
            # Device-side scoring: the validation shards live in HBM
            # (uploaded once at first use); per-iteration scoring is one
            # jitted dispatch + ONE transfer of the score vector, vs the
            # reference's per-submodel score joins
            # (FixedEffectModel.scala:94-105, RandomEffectModel.scala).
            if self._val_scorer is None:
                from photon_ml_tpu.models.device_scoring import (
                    DeviceGameScorer,
                )
                self._val_scorer = DeviceGameScorer(
                    game_model, self.validation_data, dtype=hist_dtype)
            val_scores = np.asarray(self._val_scorer.score(game_model))
            metrics = {
                ev.name: ev.evaluate_dataset(val_scores,
                                             self.validation_data)
                for ev in self.validation_evaluators}
            validation_history.append(metrics)
            head = self.validation_evaluators[0]
            m0 = metrics[head.name]
            if head.better_than(m0, best_metric):
                best_metric, best_model = m0, game_model
            logger.info("iter %d validation: %s", it, metrics)

        step = 0
        it = 0
        while it < num_iterations:
            if step + n_coords <= done_steps:
                # Whole iteration was restored, incl. its validation.
                step += n_coords
                it += 1
                continue
            partial_resume = step < done_steps  # resume lands mid-iteration

            if blockable and not partial_resume:
                # -------- fused block path: one dispatch per sync span ----
                if validating:
                    span = 1
                elif checkpoint_dir is not None:
                    next_save = ((step // checkpoint_interval) + 1
                                 ) * checkpoint_interval
                    span = (next_save - step) // n_coords
                else:
                    span = num_iterations - it
                span = max(1, min(span, num_iterations - it))
                t0 = time.perf_counter()
                params, scores, objs, trs = self._fused_block_fn(span)(
                    data_args, pdata_args, params, scores, base_key,
                    np.uint32(step), rows)
                pending_blocks.append((objs, trs))
                elapsed = time.perf_counter() - t0
                for n in names:
                    timings[n] += elapsed / n_coords
                step += span * n_coords
                it += span
                logger.info(
                    "iterations %d-%d dispatched as one device block "
                    "(%.1f ms)", it - span, it - 1, 1e3 * elapsed)
                if validating:
                    _run_validation(it - 1)
                if (checkpoint_dir is not None
                        and (validating or step % checkpoint_interval == 0)):
                    # Iteration-boundary save (carries this iteration's
                    # validation entry + best model when validating).
                    _save(step)
                continue

            # -------- per-step path: partial-iteration resume or ---------
            # -------- non-iteration-aligned checkpoint intervals ---------
            for ci, n in enumerate(names):
                step += 1
                if step <= done_steps:
                    continue  # resumed past this update
                t0 = time.perf_counter()
                # One dispatch: residual reduce (the reference's
                # partial-score reduce, CoordinateDescent.scala:150-158,
                # recomputed FRESH each step so a resumed run matches an
                # uninterrupted one bit-for-bit), per-step fold_in key,
                # solve, re-score, full objective incl. every coordinate's
                # penalties. The step index is passed as a device scalar so
                # the compiled executable is reused across steps.
                new_p, new_score, obj, tracker = fused[n](
                    data_args[n], pdata_args, params,
                    tuple(scores[m] for m in names if m != n),
                    base_key, np.uint32(step), rows)
                params[n] = new_p
                scores[n] = new_score
                if isinstance(tracker, tuple):
                    tracker = list(tracker)
                trackers[n].append(tracker)
                timings[n] += time.perf_counter() - t0

                # Device-side history write — NOT synced here (a float()
                # per update costs a full tunnel round trip); materialized
                # in one transfer at checkpoint/return.
                hist_dev = _hist_set(hist_dev, np.uint32(step - 1), obj)
                hist_len = max(hist_len, step)
                logger.info("iter %d coordinate %s updated (%.1f ms)", it,
                            n, 1e3 * (time.perf_counter() - t0))
                # Defer the last-coordinate save to after validation: one
                # save per iteration boundary, and a crash during validation
                # resumes from before the final update, so the re-run never
                # skips the iteration's validation/best-model bookkeeping.
                last_of_iteration = ci == n_coords - 1
                if (checkpoint_dir is not None
                        and step % checkpoint_interval == 0
                        and not (last_of_iteration and validating)):
                    _save(step)

            if validating:
                _run_validation(it)
                if checkpoint_dir is not None:
                    _save(step)
            it += 1

        _sync_models()
        _materialize_history()
        _materialize_pending(include_trackers=False)
        # Hot-loop compile invariant: every fused executable (per-
        # coordinate step fns, per-span block fns) traced exactly once
        # this run — the runtime complement of jaxlint's retrace-hazard
        # rule. A trip here means argument shapes/dtypes/statics drifted
        # call-to-call and every "one dispatch" above silently paid a
        # recompile.
        self.tracing_guard.assert_max_retraces(per_fn=1)
        if logger.isEnabledFor(logging.INFO) and objective_history:
            logger.info("objective history: %s",
                        ["%.6f" % v for v in objective_history])
        final = GameModel(dict(models), self.task_type)
        if best_model is None:
            best_model = final
        return CoordinateDescentResult(
            model=final,
            objective_history=list(objective_history),
            validation_history=validation_history,
            best_model=best_model,
            best_metric=best_metric,
            trackers=LazyTrackers(trackers, pending_tracker_blocks, names),
            timings=timings,
        )

    def _training_rows(self, dtype) -> Tuple[Array, Array, Array]:
        """(labels, offsets, weights) aligned with the global row order,
        taken from the first coordinate's data. Cached — built once per run,
        kept in HBM."""
        cached = getattr(self, "_rows_cache", None)
        if cached is not None:
            return cached
        first = self.coordinates[list(self.coordinates)[0]]
        data = getattr(first, "data", None)
        if isinstance(data, GameDataset) or hasattr(data, "responses"):
            # GameDataset (host f64 columns) or a streamed-ingest shim
            # (data/shard_cache.StreamedFixedEffectData — device f32
            # columns, for which the asarray cast is a no-op and the
            # values match the one-shot cast bit for bit).
            rows = (jnp.asarray(data.responses, dtype),
                    jnp.asarray(data.offsets, dtype),
                    jnp.asarray(data.weights, dtype))
        else:
            # Random-effect-only: reconstruct from the blocks.
            rows = _rows_from_blocks(first.dataset)
            rows = tuple(r.astype(dtype) for r in rows)
        self._rows_cache = rows
        return rows


@jax.jit
def _hist_set(hist, idx, value):
    """Write one objective value into the device-resident history vector."""
    return hist.at[idx].set(value.astype(hist.dtype))


def _rows_from_blocks(ds) -> Tuple[Array, Array, Array]:
    n = ds.n_rows
    labels = np.zeros(n + 1, np.float32)
    offsets = np.zeros(n + 1, np.float32)
    weights = np.zeros(n + 1, np.float32)
    for blocks in (ds.blocks, [b for b in ds.passive_blocks if b is not None]):
        for b in blocks:
            rid = np.asarray(b.row_ids).ravel()
            labels[rid] = np.asarray(b.labels).ravel()
            offsets[rid] = np.asarray(b.offsets).ravel()
            weights[rid] = np.asarray(b.weights).ravel()
    return (jnp.asarray(labels[:-1]), jnp.asarray(offsets[:-1]),
            jnp.asarray(weights[:-1]))
