"""GLM training driver — the TPU counterpart of the reference's
spark-submit entry (ml/Driver.scala:70-638, flags from ml/Params.scala:42-203
/ ml/OptionNames.scala; defaults preserved: 80 iterations, λ=[10], LBFGS,
L2, tolerance 1e-6, intercept on).

Staged pipeline: INIT -> PREPROCESSED -> TRAINED -> VALIDATED -> DIAGNOSED.
Outputs under --output-directory:
  log-message.txt, best-model/{model.txt,model.avro},
  all-models/<λ>/..., validation-metrics.json, summary.json
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

from photon_ml_tpu.data.avro_reader import read_labeled_points
from photon_ml_tpu.data.index_map import IdentityIndexMap, IndexMap
from photon_ml_tpu.data.libsvm import read_libsvm
from photon_ml_tpu.data.normalization import build_normalization_context
from photon_ml_tpu.data.stats import BasicStatisticalSummary
from photon_ml_tpu.data.validators import validate_data
from photon_ml_tpu.diagnostics import (
    DiagnosticMode,
    DiagnosticReport,
    bootstrap_training,
    expected_magnitude_importance,
    fitting_diagnostic,
    hosmer_lemeshow_diagnostic,
    prediction_error_independence,
    variance_importance,
    write_report,
)
from photon_ml_tpu.diagnostics.reporting import ModelDiagnosticReport
from photon_ml_tpu.estimators.model_selection import select_best_model
from photon_ml_tpu.estimators.model_training import train_glm_models
from photon_ml_tpu.evaluation.evaluators import METRIC_METADATA
from photon_ml_tpu.evaluation.validation import evaluate_glm
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import write_container
from photon_ml_tpu.io.model_io import glm_to_avro_record, write_text_model
from photon_ml_tpu.optimization.config import (
    OptimizerType,
    RegularizationContext,
    RegularizationType,
    constraint_arrays,
    parse_constraint_string,
)
from photon_ml_tpu.types import DataValidationType, NormalizationType, TaskType
from photon_ml_tpu.utils import (
    PhotonOptimizationLogEvent,
    TrainingFinishEvent,
    TrainingStartEvent,
)
from photon_ml_tpu.utils.events import EventEmitter
from photon_ml_tpu.utils.logging_utils import setup_photon_logger
from photon_ml_tpu.utils.profiling import maybe_trace
from photon_ml_tpu.utils.timer import PhaseTimer

STAGES = ["INIT", "PREPROCESSED", "TRAINED", "VALIDATED", "DIAGNOSED"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-glm-driver",
        description="Train GLMs over a regularization-weight grid "
                    "(reference flag names from ml/OptionNames.scala)")
    p.add_argument("--training-data-directory", required=True)
    p.add_argument("--validating-data-directory", default=None)
    p.add_argument("--output-directory", required=True)
    p.add_argument("--task", required=True,
                   choices=[t.value for t in TaskType])
    p.add_argument("--format", default="AVRO", choices=["AVRO", "LIBSVM"])
    p.add_argument("--max-num-iterations", type=int, default=80)
    p.add_argument("--regularization-weights", default="10",
                   help="comma-separated λ grid")
    p.add_argument("--regularization-type", default="L2",
                   choices=[t.value for t in RegularizationType])
    p.add_argument("--elastic-net-alpha", type=float, default=None)
    p.add_argument("--optimizer", default="LBFGS",
                   choices=[t.value for t in OptimizerType])
    p.add_argument("--tolerance", type=float, default=1e-6)
    p.add_argument("--intercept", default="true",
                   choices=["true", "false"], help="add intercept term")
    p.add_argument("--normalization-type", default="NONE",
                   choices=[t.value for t in NormalizationType])
    p.add_argument("--coefficient-box-constraints", default=None,
                   help="JSON constraint string (GLMSuite format)")
    p.add_argument("--ingest-workers", default="auto",
                   help="Avro decode worker processes: 'auto' (usable "
                        "cores) or an int; >= 2 decodes file shards in "
                        "parallel with byte-identical output, 1 forces "
                        "single-process decode")
    p.add_argument("--offheap-indexmap-dir", default=None,
                   help="pre-built feature index stores (the reference's "
                        "partitioned PalDB paldb-partition-<ns>-<N>.dat "
                        "stores, OptionNames.OFFHEAP_INDEXMAP_DIR, or this "
                        "package's <ns>.json) — skips the Avro index scan; "
                        "uses the 'global' namespace, or the only one "
                        "present")
    p.add_argument("--offheap-indexmap-namespace", default=None,
                   help="store namespace to use when the directory holds "
                        "several (defaults to 'global' or the only one)")
    p.add_argument("--selected-features-file", default=None,
                   help="Avro file of name/term records restricting the "
                        "feature set (GLMSuite selectedFeaturesFile)")
    p.add_argument("--summarization-output-dir", default=None,
                   help="write per-feature statistics as "
                        "FeatureSummarizationResultAvro here "
                        "(ml/Driver.scala summarizeFeatures)")
    p.add_argument("--validate-data", default="VALIDATE_FULL",
                   choices=[t.value for t in DataValidationType])
    p.add_argument("--diagnostic-mode", default="NONE",
                   choices=["NONE", "TRAIN", "VALIDATE", "ALL"],
                   help="which diagnostics to run "
                        "(ml/diagnostics/DiagnosticMode.scala)")
    p.add_argument("--num-bootstrap-samples", type=int, default=4)
    p.add_argument("--compute-variance", default="false",
                   choices=["true", "false"])
    p.add_argument("--warm-start", default="true", choices=["true", "false"])
    p.add_argument("--job-name", default="photon-ml-tpu")
    p.add_argument("--event-listeners", default=None,
                   help="comma-separated listener class paths")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64"])
    p.add_argument("--feature-storage-dtype", default=None,
                   choices=["bfloat16"],
                   help="store DENSE features at half width (bfloat16) "
                        "with solver-dtype accumulation — ~2x on the "
                        "bandwidth-bound fixed-effect solve; see "
                        "docs/F32_PARITY.md for the precision bounds")
    p.add_argument("--profile-output-dir", default=None,
                   help="write a jax.profiler trace of the train phase here "
                        "(view with XProf/TensorBoard)")
    return p


def _read_selected_features(path: str) -> set:
    """Selected-feature keys from an Avro file of name/term records
    (GLMSuite.getSelectedFeatureSetFromFile, io/GLMSuite.scala:133-150)."""
    from photon_ml_tpu.data.index_map import feature_key
    from photon_ml_tpu.io.avro_codec import read_container

    return {feature_key(r["name"], r.get("term") or "")
            for r in read_container(path)}


def _write_feature_summary(out_dir: Path, summary, imap) -> None:
    """Per-feature statistics as FeatureSummarizationResultAvro
    (util/IOUtils.scala:270-330: max/min/mean/normL1/normL2/numNonzeros/
    variance keyed by feature name+term)."""
    from photon_ml_tpu.data.index_map import split_key

    records = []
    for i in range(len(summary.mean)):
        key = imap.get_feature_name(i) or str(i)
        name, term = split_key(key)
        records.append({
            "featureName": name,
            "featureTerm": term or None,
            "metrics": {
                "max": float(summary.max[i]),
                "min": float(summary.min[i]),
                "mean": float(summary.mean[i]),
                "normL1": float(summary.norm_l1[i]),
                "normL2": float(summary.norm_l2[i]),
                "numNonzeros": float(summary.num_nonzeros[i]),
                "variance": float(summary.variance[i]),
            },
        })
    out_dir.mkdir(parents=True, exist_ok=True)
    write_container(out_dir / "part-00000.avro",
                    schemas.FEATURE_SUMMARIZATION_RESULT, records)


def _load(path: str, fmt: str, add_intercept: bool, task: TaskType,
          index_map: IndexMap | None = None,
          num_raw_features: int | None = None,
          selected_features: set | None = None,
          ingest_workers="auto"):
    """index_map / num_raw_features: pass the training map (AVRO) or the
    training feature width before intercept (LIBSVM) when loading validation
    data, so columns decode identically (the reference shares one feature
    index across splits)."""
    if fmt == "AVRO":
        mat, y, off, w, _, imap = read_labeled_points(
            path, index_map=index_map, add_intercept=add_intercept,
            selected_features=selected_features,
            ingest_workers=ingest_workers)
        return mat, y, off, w, imap
    if selected_features is not None:
        raise ValueError(
            "--selected-features-file requires --format AVRO "
            "(LIBSVM features have no name/term keys)")
    files = sorted(Path(path).glob("*")) if Path(path).is_dir() else \
        [Path(path)]
    mats, ys = [], []
    for f in files:
        if f.is_file():
            m, y = read_libsvm(
                f, add_intercept=False,
                map_negative_labels=task.is_classification)
            mats.append(m)
            ys.append(y)
    import scipy.sparse as sp

    d = max(m.shape[1] for m in mats)
    if num_raw_features is not None:
        # Validation width is dictated by training: features unseen at
        # training time are dropped (the shared index has no slot for them).
        d = num_raw_features
        mats = [m[:, :d] if m.shape[1] > d else m for m in mats]
    mats = [sp.csr_matrix((m.data, m.indices, m.indptr), shape=(m.shape[0], d))
            for m in mats]
    mat = sp.vstack(mats, format="csr")
    if add_intercept:
        mat = sp.hstack([mat, np.ones((mat.shape[0], 1))], format="csr")
    y = np.concatenate(ys)
    imap = IdentityIndexMap(mat.shape[1], intercept_last=add_intercept)
    return mat, y, np.zeros(len(y)), np.ones(len(y)), imap


def _run_diagnostics(mode, out_dir, task, trained, metrics_by_lambda,
                     mat, y, off, w, imap, vdata, train_kwargs,
                     num_bootstrap_samples):
    """DIAGNOSED stage (reference: ml/Driver.scala:524-551 — training
    diagnostics run against training data, validation diagnostics against
    the validation set; everything lands in one report document)."""
    summary = BasicStatisticalSummary.compute(mat)
    feature_names = [imap.get_feature_name(i) or str(i)
                     for i in range(mat.shape[1])]
    lambdas = list(train_kwargs["regularization_weights"])

    def subset_trainer(train_idx, holdout_idx, warm, eval_train=True):
        """(λ, model, train metrics, holdout metrics) per grid point —
        the curried trainModel closure of BootstrapTraining/FittingDiagnostic.
        eval_train=False skips the train-split scoring pass (bootstrap only
        consumes holdout metrics)."""
        init = warm.get(max(lambdas)) if warm else None
        results = train_glm_models(
            mat[train_idx], y[train_idx], task,
            offsets=off[train_idx], weights=w[train_idx],
            initial_model=init,
            **train_kwargs)
        out = []
        for t in results:
            means, _ = t.model.coefficients.to_numpy()
            train_metrics = {}
            if eval_train:
                train_scores = np.asarray(mat[train_idx] @ means).ravel()
                train_metrics = evaluate_glm(
                    task, train_scores, y[train_idx],
                    off[train_idx], w[train_idx])
            hold_scores = np.asarray(mat[holdout_idx] @ means).ravel()
            out.append((
                t.reg_weight, t.model, train_metrics,
                evaluate_glm(task, hold_scores, y[holdout_idx],
                             off[holdout_idx], w[holdout_idx])))
        return out

    fitting_by_lambda = {}
    bootstrap_by_lambda = {}
    if mode.train_enabled:
        fitting_by_lambda = fitting_diagnostic(
            mat.shape[0], mat.shape[1], subset_trainer)
        if num_bootstrap_samples > 1:
            def bootstrap_trainer(train_idx, holdout_idx, warm):
                return [(lam, model, hold)
                        for lam, model, _, hold
                        in subset_trainer(train_idx, holdout_idx, warm,
                                          eval_train=False)]

            bootstrap_by_lambda = bootstrap_training(
                mat.shape[0], bootstrap_trainer,
                num_bootstrap_samples=num_bootstrap_samples)

    report = DiagnosticReport(system={
        "task": task.value,
        "numRows": int(mat.shape[0]),
        "numFeatures": int(mat.shape[1]),
        "lambdas": lambdas,
        "diagnosticMode": mode.value,
    })
    for t in trained:
        means, _ = t.model.coefficients.to_numpy()
        chapter = ModelDiagnosticReport(
            model_description=t.model.model_class_name,
            reg_weight=t.reg_weight,
            metrics=metrics_by_lambda.get(t.reg_weight, {}))
        chapter.feature_importance = [
            expected_magnitude_importance(
                means, summary, feature_names).to_dict(),
            variance_importance(means, summary, feature_names).to_dict(),
        ]
        if t.reg_weight in fitting_by_lambda:
            chapter.fitting = fitting_by_lambda[t.reg_weight].to_dict()
        if t.reg_weight in bootstrap_by_lambda:
            chapter.bootstrap = bootstrap_by_lambda[t.reg_weight].to_dict()
        if mode.validate_enabled and vdata is not None:
            vmat, vy, voff, vw = vdata
            vscores = np.asarray(vmat @ means).ravel() + voff
            predictions = np.asarray(
                t.model.mean_of_score(vscores))
            chapter.prediction_error_independence = \
                prediction_error_independence(vy, predictions).to_dict()
            if task == TaskType.LOGISTIC_REGRESSION:
                chapter.hosmer_lemeshow = hosmer_lemeshow_diagnostic(
                    vy, predictions, vmat.shape[1]).to_dict()
        report.models.append(chapter)

    write_report(report, out_dir)


def run(argv=None) -> dict:
    from photon_ml_tpu.cli import _honor_jax_platforms_env

    _honor_jax_platforms_env()
    args = build_parser().parse_args(argv)
    out_dir = Path(args.output_directory)
    out_dir.mkdir(parents=True, exist_ok=True)
    logger = setup_photon_logger(out_dir)
    task = TaskType(args.task)
    add_intercept = args.intercept == "true"
    timer = PhaseTimer()
    stages = ["INIT"]

    emitter = EventEmitter()
    for cp in (args.event_listeners or "").split(","):
        if cp.strip():
            emitter.register_listener_by_name(cp.strip())
    emitter.send_event(TrainingStartEvent(args.job_name))
    t_start = time.perf_counter()

    import jax
    import jax.numpy as jnp

    if args.dtype == "float64":
        # Without this, jnp.asarray(..., float64) silently yields float32
        # and the whole solve runs at the wrong precision.
        jax.config.update("jax_enable_x64", True)
    dtype = jnp.float64 if args.dtype == "float64" else jnp.float32
    storage_dtype = (jnp.bfloat16
                     if args.feature_storage_dtype == "bfloat16" else None)

    # ---- preprocess ------------------------------------------------------
    with timer.time("preprocess"):
        selected = (_read_selected_features(args.selected_features_file)
                    if args.selected_features_file else None)
        preloaded_map = None
        if args.offheap_indexmap_dir:
            if args.format != "AVRO":
                raise ValueError(
                    "--offheap-indexmap-dir requires --format AVRO")
            from photon_ml_tpu.data.paldb import (
                discover_store_namespaces,
                load_store_namespace,
            )

            store_dir = Path(args.offheap_indexmap_dir)
            namespaces = discover_store_namespaces(store_dir)
            ns = args.offheap_indexmap_namespace or (
                "global" if "global" in namespaces
                else next(iter(namespaces)) if len(namespaces) == 1
                else None)
            if ns is None or ns not in namespaces:
                raise ValueError(
                    f"--offheap-indexmap-dir holds namespaces "
                    f"{sorted(namespaces)}; pick one with "
                    "--offheap-indexmap-namespace")
            # Parse only the selected namespace (a dir can hold several
            # multi-million-feature shards).
            preloaded_map = load_store_namespace(store_dir, ns,
                                                 namespaces[ns])
            if add_intercept and preloaded_map.intercept_index < 0:
                raise ValueError(
                    f"feature index store {ns!r} has no intercept key but "
                    "--intercept is true — rebuild the store with an "
                    "intercept or pass --intercept false")
            logger.info("loaded feature index store %r (%d features) "
                        "from %s", ns, len(preloaded_map), store_dir)
        mat, y, off, w, imap = _load(
            args.training_data_directory, args.format, add_intercept, task,
            index_map=preloaded_map, selected_features=selected,
            ingest_workers=args.ingest_workers)
        logger.info("loaded %d rows x %d features", *mat.shape)
        validate_data(task, mat, y, off, w,
                      DataValidationType(args.validate_data))
        norm = None
        if args.normalization_type != "NONE" or args.summarization_output_dir:
            summary = BasicStatisticalSummary.compute(mat)
            if args.summarization_output_dir:
                _write_feature_summary(
                    Path(args.summarization_output_dir), summary, imap)
                logger.info("feature statistics written to %s",
                            args.summarization_output_dir)
        if args.normalization_type != "NONE":
            norm = build_normalization_context(
                args.normalization_type, summary,
                intercept_id=imap.intercept_index)
        lb = ub = None
        if args.coefficient_box_constraints:
            cmap = parse_constraint_string(
                args.coefficient_box_constraints, imap)
            lb, ub = constraint_arrays(cmap, len(imap),
                                       imap.intercept_index)
    stages.append("PREPROCESSED")

    # ---- train -----------------------------------------------------------
    lambdas = [float(s) for s in args.regularization_weights.split(",")]
    reg_ctx = RegularizationContext(
        RegularizationType(args.regularization_type),
        args.elastic_net_alpha)
    with timer.time("train"), maybe_trace(args.profile_output_dir):
        trained = train_glm_models(
            mat, y, task,
            regularization_weights=lambdas,
            regularization_context=reg_ctx,
            optimizer_type=OptimizerType(args.optimizer),
            max_iterations=args.max_num_iterations,
            tolerance=args.tolerance,
            offsets=off, weights=w, normalization=norm,
            lower_bounds=lb, upper_bounds=ub,
            warm_start=args.warm_start == "true",
            compute_variances=args.compute_variance == "true",
            dtype=dtype,
            storage_dtype=storage_dtype)
    stages.append("TRAINED")
    for t in trained:
        emitter.send_event(PhotonOptimizationLogEvent(
            t.reg_weight, int(t.result.iterations),
            t.result.reason_enum().summary, float(t.result.value)))

    # ---- validate + select ----------------------------------------------
    best_lambda = lambdas[0]
    metrics_by_lambda = {}
    if args.validating_data_directory:
        with timer.time("validate"):
            vmat, vy, voff, vw, _ = _load(
                args.validating_data_directory, args.format, add_intercept,
                task, index_map=imap if args.format == "AVRO" else None,
                num_raw_features=(mat.shape[1] - int(add_intercept)
                                  if args.format == "LIBSVM" else None),
                ingest_workers=args.ingest_workers)
            if vmat.shape[1] != mat.shape[1]:
                raise ValueError(
                    f"validation feature dim {vmat.shape[1]} != "
                    f"training {mat.shape[1]}")
            scored = {}
            for t in trained:
                means, _ = t.model.coefficients.to_numpy()
                scored[t.reg_weight] = np.asarray(vmat @ means).ravel()
            best_lambda, _ = select_best_model(task, scored, vy, voff, vw)
            for t in trained:
                metrics_by_lambda[t.reg_weight] = evaluate_glm(
                    task, scored[t.reg_weight], vy, voff, vw,
                    num_coefficients=mat.shape[1])
            metric_names = sorted(
                {m for ms in metrics_by_lambda.values() for m in ms})
            (out_dir / "validation-metrics.json").write_text(
                json.dumps({
                    "metrics": {str(k): v
                                for k, v in metrics_by_lambda.items()},
                    "metricMetadata": {
                        name: METRIC_METADATA[name].to_dict()
                        for name in metric_names
                        if name in METRIC_METADATA},
                }, indent=2))
        stages.append("VALIDATED")

    # ---- diagnose --------------------------------------------------------
    diag_mode = DiagnosticMode(args.diagnostic_mode)
    if diag_mode is not DiagnosticMode.NONE:
        with timer.time("diagnose"):
            _run_diagnostics(
                diag_mode, out_dir, task, trained, metrics_by_lambda,
                mat, y, off, w, imap,
                vdata=(vmat, vy, voff, vw)
                if args.validating_data_directory else None,
                train_kwargs=dict(
                    regularization_weights=lambdas,
                    regularization_context=reg_ctx,
                    optimizer_type=OptimizerType(args.optimizer),
                    max_iterations=args.max_num_iterations,
                    tolerance=args.tolerance, normalization=norm,
                    lower_bounds=lb, upper_bounds=ub,
                    warm_start=args.warm_start == "true", dtype=dtype,
                    storage_dtype=storage_dtype),
                num_bootstrap_samples=args.num_bootstrap_samples)
        stages.append("DIAGNOSED")
        logger.info("diagnostics written to model-diagnostic.{json,html}")

    # ---- write models ----------------------------------------------------
    with timer.time("write"):
        by_lambda = {t.reg_weight: t for t in trained}
        best = by_lambda[best_lambda]
        best_dir = out_dir / "best-model"
        best_dir.mkdir(exist_ok=True)
        write_text_model(best_dir / "model.txt", best.model, imap,
                         best.reg_weight)
        write_container(best_dir / "model.avro",
                        schemas.BAYESIAN_LINEAR_MODEL,
                        [glm_to_avro_record("best", best.model, imap)])
        all_dir = out_dir / "all-models"
        for t in trained:
            d = all_dir / str(t.reg_weight)
            d.mkdir(parents=True, exist_ok=True)
            write_text_model(d / "model.txt", t.model, imap, t.reg_weight)
        imap.save(out_dir / "feature-index.json")

    duration = time.perf_counter() - t_start
    summary = {
        "jobName": args.job_name,
        "task": task.value,
        "stages": stages,
        "numRows": int(mat.shape[0]),
        "numFeatures": int(mat.shape[1]),
        "lambdas": lambdas,
        "bestLambda": best_lambda,
        "convergence": {
            str(t.reg_weight): {
                "iterations": int(t.result.iterations),
                "reason": t.result.reason_enum().summary,
                "finalObjective": float(t.result.value)}
            for t in trained},
        "validationMetrics": {str(k): v
                              for k, v in metrics_by_lambda.items()},
        "phaseSeconds": timer.phases,
        "totalSeconds": duration,
    }
    (out_dir / "summary.json").write_text(json.dumps(summary, indent=2))
    emitter.send_event(TrainingFinishEvent(args.job_name, duration))
    emitter.clear_listeners()
    logger.info("done in %.1fs; best lambda = %g", duration, best_lambda)
    return summary


def main() -> None:
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
