"""GLM training driver — the TPU counterpart of the reference's
spark-submit entry (ml/Driver.scala:70-638, flags from ml/Params.scala:42-203
/ ml/OptionNames.scala; defaults preserved: 80 iterations, λ=[10], LBFGS,
L2, tolerance 1e-6, intercept on).

Staged pipeline: INIT -> PREPROCESSED -> TRAINED -> VALIDATED -> DIAGNOSED.
Outputs under --output-directory:
  log-message.txt, best-model/{model.txt,model.avro},
  all-models/<λ>/..., validation-metrics.json, summary.json
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

from photon_ml_tpu.data.avro_reader import read_labeled_points
from photon_ml_tpu.data.index_map import IdentityIndexMap, IndexMap
from photon_ml_tpu.data.libsvm import read_libsvm
from photon_ml_tpu.data.normalization import build_normalization_context
from photon_ml_tpu.data.stats import BasicStatisticalSummary
from photon_ml_tpu.data.validators import validate_data
from photon_ml_tpu.estimators.model_selection import select_best_model
from photon_ml_tpu.estimators.model_training import train_glm_models
from photon_ml_tpu.evaluation.validation import evaluate_glm
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import write_container
from photon_ml_tpu.io.model_io import glm_to_avro_record, write_text_model
from photon_ml_tpu.optimization.config import (
    OptimizerType,
    RegularizationContext,
    RegularizationType,
    constraint_arrays,
    parse_constraint_string,
)
from photon_ml_tpu.types import DataValidationType, NormalizationType, TaskType
from photon_ml_tpu.utils import (
    PhotonOptimizationLogEvent,
    TrainingFinishEvent,
    TrainingStartEvent,
)
from photon_ml_tpu.utils.events import EventEmitter
from photon_ml_tpu.utils.logging_utils import setup_photon_logger
from photon_ml_tpu.utils.timer import PhaseTimer

STAGES = ["INIT", "PREPROCESSED", "TRAINED", "VALIDATED"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-glm-driver",
        description="Train GLMs over a regularization-weight grid "
                    "(reference flag names from ml/OptionNames.scala)")
    p.add_argument("--training-data-directory", required=True)
    p.add_argument("--validating-data-directory", default=None)
    p.add_argument("--output-directory", required=True)
    p.add_argument("--task", required=True,
                   choices=[t.value for t in TaskType])
    p.add_argument("--format", default="AVRO", choices=["AVRO", "LIBSVM"])
    p.add_argument("--max-num-iterations", type=int, default=80)
    p.add_argument("--regularization-weights", default="10",
                   help="comma-separated λ grid")
    p.add_argument("--regularization-type", default="L2",
                   choices=[t.value for t in RegularizationType])
    p.add_argument("--elastic-net-alpha", type=float, default=None)
    p.add_argument("--optimizer", default="LBFGS",
                   choices=[t.value for t in OptimizerType])
    p.add_argument("--tolerance", type=float, default=1e-6)
    p.add_argument("--intercept", default="true",
                   choices=["true", "false"], help="add intercept term")
    p.add_argument("--normalization-type", default="NONE",
                   choices=[t.value for t in NormalizationType])
    p.add_argument("--coefficient-box-constraints", default=None,
                   help="JSON constraint string (GLMSuite format)")
    p.add_argument("--validate-data", default="VALIDATE_FULL",
                   choices=[t.value for t in DataValidationType])
    p.add_argument("--compute-variance", default="false",
                   choices=["true", "false"])
    p.add_argument("--warm-start", default="true", choices=["true", "false"])
    p.add_argument("--job-name", default="photon-ml-tpu")
    p.add_argument("--event-listeners", default=None,
                   help="comma-separated listener class paths")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64"])
    return p


def _load(path: str, fmt: str, add_intercept: bool, task: TaskType,
          index_map: IndexMap | None = None,
          num_raw_features: int | None = None):
    """index_map / num_raw_features: pass the training map (AVRO) or the
    training feature width before intercept (LIBSVM) when loading validation
    data, so columns decode identically (the reference shares one feature
    index across splits)."""
    if fmt == "AVRO":
        mat, y, off, w, _, imap = read_labeled_points(
            path, index_map=index_map, add_intercept=add_intercept)
        return mat, y, off, w, imap
    files = sorted(Path(path).glob("*")) if Path(path).is_dir() else \
        [Path(path)]
    mats, ys = [], []
    for f in files:
        if f.is_file():
            m, y = read_libsvm(
                f, add_intercept=False,
                map_negative_labels=task.is_classification)
            mats.append(m)
            ys.append(y)
    import scipy.sparse as sp

    d = max(m.shape[1] for m in mats)
    if num_raw_features is not None:
        # Validation width is dictated by training: features unseen at
        # training time are dropped (the shared index has no slot for them).
        d = num_raw_features
        mats = [m[:, :d] if m.shape[1] > d else m for m in mats]
    mats = [sp.csr_matrix((m.data, m.indices, m.indptr), shape=(m.shape[0], d))
            for m in mats]
    mat = sp.vstack(mats, format="csr")
    if add_intercept:
        mat = sp.hstack([mat, np.ones((mat.shape[0], 1))], format="csr")
    y = np.concatenate(ys)
    imap = IdentityIndexMap(mat.shape[1], intercept_last=add_intercept)
    return mat, y, np.zeros(len(y)), np.ones(len(y)), imap


def run(argv=None) -> dict:
    args = build_parser().parse_args(argv)
    out_dir = Path(args.output_directory)
    out_dir.mkdir(parents=True, exist_ok=True)
    logger = setup_photon_logger(out_dir)
    task = TaskType(args.task)
    add_intercept = args.intercept == "true"
    timer = PhaseTimer()
    stages = ["INIT"]

    emitter = EventEmitter()
    for cp in (args.event_listeners or "").split(","):
        if cp.strip():
            emitter.register_listener_by_name(cp.strip())
    emitter.send_event(TrainingStartEvent(args.job_name))
    t_start = time.perf_counter()

    import jax
    import jax.numpy as jnp

    if args.dtype == "float64":
        # Without this, jnp.asarray(..., float64) silently yields float32
        # and the whole solve runs at the wrong precision.
        jax.config.update("jax_enable_x64", True)
    dtype = jnp.float64 if args.dtype == "float64" else jnp.float32

    # ---- preprocess ------------------------------------------------------
    with timer.time("preprocess"):
        mat, y, off, w, imap = _load(
            args.training_data_directory, args.format, add_intercept, task)
        logger.info("loaded %d rows x %d features", *mat.shape)
        validate_data(task, mat, y, off, w,
                      DataValidationType(args.validate_data))
        norm = None
        if args.normalization_type != "NONE":
            summary = BasicStatisticalSummary.compute(mat)
            norm = build_normalization_context(
                args.normalization_type, summary,
                intercept_id=imap.intercept_index)
        lb = ub = None
        if args.coefficient_box_constraints:
            cmap = parse_constraint_string(
                args.coefficient_box_constraints, imap)
            lb, ub = constraint_arrays(cmap, len(imap),
                                       imap.intercept_index)
    stages.append("PREPROCESSED")

    # ---- train -----------------------------------------------------------
    lambdas = [float(s) for s in args.regularization_weights.split(",")]
    reg_ctx = RegularizationContext(
        RegularizationType(args.regularization_type),
        args.elastic_net_alpha)
    with timer.time("train"):
        trained = train_glm_models(
            mat, y, task,
            regularization_weights=lambdas,
            regularization_context=reg_ctx,
            optimizer_type=OptimizerType(args.optimizer),
            max_iterations=args.max_num_iterations,
            tolerance=args.tolerance,
            offsets=off, weights=w, normalization=norm,
            lower_bounds=lb, upper_bounds=ub,
            warm_start=args.warm_start == "true",
            compute_variances=args.compute_variance == "true",
            dtype=dtype)
    stages.append("TRAINED")
    for t in trained:
        emitter.send_event(PhotonOptimizationLogEvent(
            t.reg_weight, int(t.result.iterations),
            t.result.reason_enum().summary, float(t.result.value)))

    # ---- validate + select ----------------------------------------------
    best_lambda = lambdas[0]
    metrics_by_lambda = {}
    if args.validating_data_directory:
        with timer.time("validate"):
            vmat, vy, voff, vw, _ = _load(
                args.validating_data_directory, args.format, add_intercept,
                task, index_map=imap if args.format == "AVRO" else None,
                num_raw_features=(mat.shape[1] - int(add_intercept)
                                  if args.format == "LIBSVM" else None))
            if vmat.shape[1] != mat.shape[1]:
                raise ValueError(
                    f"validation feature dim {vmat.shape[1]} != "
                    f"training {mat.shape[1]}")
            scored = {}
            for t in trained:
                means, _ = t.model.coefficients.to_numpy()
                scored[t.reg_weight] = np.asarray(vmat @ means).ravel()
            best_lambda, _ = select_best_model(task, scored, vy, voff, vw)
            for t in trained:
                metrics_by_lambda[t.reg_weight] = evaluate_glm(
                    task, scored[t.reg_weight], vy, voff, vw,
                    num_coefficients=mat.shape[1])
            (out_dir / "validation-metrics.json").write_text(
                json.dumps({str(k): v for k, v in metrics_by_lambda.items()},
                           indent=2))
        stages.append("VALIDATED")

    # ---- write models ----------------------------------------------------
    with timer.time("write"):
        by_lambda = {t.reg_weight: t for t in trained}
        best = by_lambda[best_lambda]
        best_dir = out_dir / "best-model"
        best_dir.mkdir(exist_ok=True)
        write_text_model(best_dir / "model.txt", best.model, imap,
                         best.reg_weight)
        write_container(best_dir / "model.avro",
                        schemas.BAYESIAN_LINEAR_MODEL,
                        [glm_to_avro_record("best", best.model, imap)])
        all_dir = out_dir / "all-models"
        for t in trained:
            d = all_dir / str(t.reg_weight)
            d.mkdir(parents=True, exist_ok=True)
            write_text_model(d / "model.txt", t.model, imap, t.reg_weight)
        imap.save(out_dir / "feature-index.json")

    duration = time.perf_counter() - t_start
    summary = {
        "jobName": args.job_name,
        "task": task.value,
        "stages": stages,
        "numRows": int(mat.shape[0]),
        "numFeatures": int(mat.shape[1]),
        "lambdas": lambdas,
        "bestLambda": best_lambda,
        "convergence": {
            str(t.reg_weight): {
                "iterations": int(t.result.iterations),
                "reason": t.result.reason_enum().summary,
                "finalObjective": float(t.result.value)}
            for t in trained},
        "validationMetrics": {str(k): v
                              for k, v in metrics_by_lambda.items()},
        "phaseSeconds": timer.phases,
        "totalSeconds": duration,
    }
    (out_dir / "summary.json").write_text(json.dumps(summary, indent=2))
    emitter.send_event(TrainingFinishEvent(args.job_name, duration))
    emitter.clear_listeners()
    logger.info("done in %.1fs; best lambda = %g", duration, best_lambda)
    return summary


def main() -> None:
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
