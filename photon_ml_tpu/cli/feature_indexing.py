"""Feature indexing job (reference: ml/FeatureIndexingJob.scala:59-350):
scan training Avro, build a name⊕term -> index map per feature shard, persist.
The reference writes partitioned PalDB stores; here a JSON map per shard is
sufficient (SURVEY §2.9)."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from photon_ml_tpu.data.avro_reader import build_index_map
from photon_ml_tpu.utils.logging_utils import setup_photon_logger


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-feature-indexing-job")
    p.add_argument("--data-path", required=True)
    p.add_argument("--partition-num", type=int, default=1,
                   help="accepted for reference-CLI compatibility; the JSON "
                        "store is single-partition")
    p.add_argument("--add-intercept", default="true",
                   choices=["true", "false"])
    p.add_argument("--output-dir", required=True)
    p.add_argument("--shard-name", default="global")
    p.add_argument("--save-name-and-term-sets", default="false",
                   choices=["true", "false"],
                   help="also persist per-section (name, term) text sets "
                        "(ml/avro/data/NameAndTermFeatureSetContainer.scala)")
    return p


def run(argv=None) -> Path:
    args = build_parser().parse_args(argv)
    out_dir = Path(args.output_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    logger = setup_photon_logger(out_dir)
    imap = build_index_map(args.data_path,
                           add_intercept=args.add_intercept == "true")
    out = out_dir / f"{args.shard_name}.json"
    imap.save(out)
    logger.info("indexed %d features -> %s", len(imap), out)
    if args.save_name_and_term_sets == "true":
        from photon_ml_tpu.data.index_map import INTERCEPT_KEY, split_key
        from photon_ml_tpu.data.name_and_term import (
            NameAndTermFeatureSetContainer,
        )

        # The index map already holds every (name, term) — no second scan.
        container = NameAndTermFeatureSetContainer({"features": {
            split_key(k) for k, _ in imap.key_items()
            if k != INTERCEPT_KEY}})
        set_dir = out_dir / "name-and-term-sets"
        container.save_as_text_files(set_dir)
        logger.info("feature sets -> %s", set_dir)
    return out


def main() -> None:
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
