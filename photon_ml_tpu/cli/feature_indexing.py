"""Feature indexing job (reference: ml/FeatureIndexingJob.scala:59-350):
scan training Avro, build a name⊕term -> index map per feature shard, persist.
``--format json`` (default) writes this package's JSON map per shard;
``--format paldb`` writes partitioned PalDB 1.1 stores exactly like the
reference (FeatureIndexingJob.scala:145-174 via PalDBIndexMapBuilder —
both directions per partition, Spark HashPartitioner, cumulative-offset
global indices), so downstream Photon-adjacent tooling can consume them."""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from photon_ml_tpu.data.avro_reader import build_index_map
from photon_ml_tpu.utils.logging_utils import setup_photon_logger


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-feature-indexing-job")
    p.add_argument("--data-path", required=True)
    p.add_argument("--partition-num", type=int, default=1,
                   help="PalDB store partition count (ignored by the "
                        "single-partition JSON format)")
    p.add_argument("--format", default="json", choices=["json", "paldb"],
                   help="index store format: this package's JSON map or "
                        "reference-compatible partitioned PalDB stores")
    p.add_argument("--add-intercept", default="true",
                   choices=["true", "false"])
    p.add_argument("--output-dir", required=True)
    p.add_argument("--shard-name", default="global")
    p.add_argument("--save-name-and-term-sets", default="false",
                   choices=["true", "false"],
                   help="also persist per-section (name, term) text sets "
                        "(ml/avro/data/NameAndTermFeatureSetContainer.scala)")
    return p


def run(argv=None) -> Path:
    args = build_parser().parse_args(argv)
    out_dir = Path(args.output_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    logger = setup_photon_logger(out_dir)
    imap = build_index_map(args.data_path,
                           add_intercept=args.add_intercept == "true")
    if args.format == "paldb":
        from photon_ml_tpu.data.paldb import build_paldb_index_stores

        # Re-index through the partitioned builder: per-partition local
        # indices + cumulative offsets, the layout PalDBIndexMap.load
        # expects (indices change from the scan order, as they do in the
        # reference where the partitioned store IS the index authority).
        names = [k for k, _ in sorted(imap.key_items(), key=lambda kv: kv[1])]
        imap = build_paldb_index_stores(out_dir, args.shard_name, names,
                                        num_partitions=args.partition_num)
        out = out_dir / f"paldb-partition-{args.shard_name}-0.dat"
        logger.info("indexed %d features -> %s (%d PalDB partitions)",
                    len(imap), out_dir, args.partition_num)
    else:
        out = out_dir / f"{args.shard_name}.json"
        imap.save(out)
        logger.info("indexed %d features -> %s", len(imap), out)
    if args.save_name_and_term_sets == "true":
        from photon_ml_tpu.data.index_map import INTERCEPT_KEY, split_key
        from photon_ml_tpu.data.name_and_term import (
            NameAndTermFeatureSetContainer,
        )

        # The index map already holds every (name, term) — no second scan.
        container = NameAndTermFeatureSetContainer({"features": {
            split_key(k) for k, _ in imap.key_items()
            if k != INTERCEPT_KEY}})
        set_dir = out_dir / "name-and-term-sets"
        container.save_as_text_files(set_dir)
        logger.info("feature sets -> %s", set_dir)
    return out


def main() -> None:
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
