"""GAME scoring driver (reference: ml/cli/game/scoring/Driver.scala:36-265):
load a saved GAME model, score a dataset, write ScoringResultAvro, optionally
evaluate."""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

from photon_ml_tpu.data.avro_reader import read_game_dataset
from photon_ml_tpu.evaluation import build_evaluator
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import write_container
from photon_ml_tpu.io.model_io import load_game_model
from photon_ml_tpu.utils.date_range import resolve_input_dirs
from photon_ml_tpu.utils.logging_utils import setup_photon_logger


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-game-scoring-driver")
    p.add_argument("--input-dirs", required=True)
    p.add_argument("--date-range", default=None,
                   help="yyyyMMdd-yyyyMMdd; expands daily/yyyy/MM/dd "
                        "subdirs of the input dirs")
    p.add_argument("--date-range-days-ago", default=None)
    p.add_argument("--game-model-input-dir", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--feature-index-dir", default=None,
                   help="feature index stores keyed by shard id: "
                        "<shard>.json maps or the reference's partitioned "
                        "PalDB stores (defaults to "
                        "<model-dir>/feature-indexes)")
    p.add_argument("--evaluators", default=None)
    p.add_argument("--id-types", default=None)
    return p


def run(argv=None) -> dict:
    from photon_ml_tpu.cli import _honor_jax_platforms_env

    _honor_jax_platforms_env()
    args = build_parser().parse_args(argv)
    out_dir = Path(args.output_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    logger = setup_photon_logger(out_dir)
    t0 = time.perf_counter()

    from photon_ml_tpu.data.paldb import load_feature_index_maps

    model_dir = Path(args.game_model_input_dir)
    index_dir = Path(args.feature_index_dir) if args.feature_index_dir else \
        model_dir / "feature-indexes"
    shard_maps = load_feature_index_maps(index_dir)
    model = load_game_model(model_dir, shard_maps)

    meta = json.loads((model_dir / "model-metadata.json").read_text())
    id_types = sorted(
        {c["randomEffectType"] for c in meta["coordinates"]
         if c["kind"] == "random"} |
        # MF coordinates key rows by both their entity axes.
        {c[k] for c in meta["coordinates"] if c["kind"] == "mf"
         for k in ("rowEffectType", "colEffectType")} |
        {s.strip() for s in (args.id_types or "").split(",") if s.strip()})

    inputs = resolve_input_dirs(
        args.input_dirs, date_range=args.date_range,
        date_range_days_ago=args.date_range_days_ago)
    data, _ = read_game_dataset(inputs, id_types=id_types,
                                feature_shard_maps=shard_maps)
    scores = model.score(data)
    logger.info("scored %d rows", data.num_rows)

    uids = data.uids if data.uids is not None else \
        np.asarray([str(i) for i in range(data.num_rows)])
    scores_dir = out_dir / "scores"
    scores_dir.mkdir(exist_ok=True)
    write_container(
        scores_dir / "part-00000.avro", schemas.SCORING_RESULT,
        [{"uid": str(u), "predictionScore": float(s + o),
          "label": float(l), "metadataMap": None}
         for u, s, o, l in zip(uids, scores, data.offsets, data.responses)])

    metrics = {}
    for spec in (args.evaluators or "").split(","):
        if spec.strip():
            ev = build_evaluator(spec.strip())
            metrics[ev.name] = ev.evaluate_dataset(scores, data)
    summary = {
        "numRows": int(data.num_rows),
        "metrics": metrics,
        "totalSeconds": time.perf_counter() - t0,
    }
    (out_dir / "metrics.json").write_text(json.dumps(summary, indent=2))
    logger.info("scoring done: %s", metrics)
    return summary


def main() -> None:
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
