"""GAME scoring driver (reference: ml/cli/game/scoring/Driver.scala:36-265):
load a saved GAME model, score a dataset, write ScoringResultAvro, optionally
evaluate.

Two execution shapes:

- default: the whole input is read into one GameDataset and scored in a
  single device dispatch (``DeviceGameScorer`` — dataset-resident, exact
  shapes), with a clean host-numpy fallback when a sub-model type is not
  device-scorable;
- ``--stream --batch-rows N``: arbitrarily large Avro inputs score in
  O(N) host memory through the streaming serving engine
  (photon_ml_tpu/serving/): model uploaded once, batches padded into
  static compile buckets, featureization of batch k+1 overlapped with
  the device dispatch of batch k, scores written per batch. Caveat:
  ``--evaluators`` additionally accumulates the per-row EVALUATION
  columns (score/label/offset/weight + entity-id strings) across the
  whole input — features never accumulate, but metric computation is
  O(total rows); omit evaluators to keep streaming strictly bounded.

A third shape, ``--serve``, replays the input as CONCURRENT requests
through the async serving front-end (photon_ml_tpu/serving/frontend.py):
the decoded input is sliced into ``--request-rows``-row requests,
``--serve-concurrency`` requesters submit them over an event loop, and
the front-end coalesces whatever lands inside ``--coalesce-ms`` into
shared bucket dispatches. Scores are identical to the other paths; what
changes is the execution shape — this is the serving-traffic harness
(admission control, queue-wait/coalesce telemetry, per-request P50/P99
in metrics.json ``frontend``), see docs/SCALE.md §Serving front-end.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.cli.obs import DriverObservability, add_observability_args
from photon_ml_tpu.data.avro_reader import read_game_dataset
from photon_ml_tpu.evaluation import build_evaluator
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import write_container
from photon_ml_tpu.io.model_io import load_game_model
from photon_ml_tpu.telemetry import span
from photon_ml_tpu.utils.date_range import resolve_input_dirs
from photon_ml_tpu.utils.logging_utils import setup_photon_logger


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-game-scoring-driver")
    p.add_argument("--input-dirs", required=True)
    p.add_argument("--date-range", default=None,
                   help="yyyyMMdd-yyyyMMdd; expands daily/yyyy/MM/dd "
                        "subdirs of the input dirs")
    p.add_argument("--date-range-days-ago", default=None)
    p.add_argument("--game-model-input-dir", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--feature-index-dir", default=None,
                   help="feature index stores keyed by shard id: "
                        "<shard>.json maps or the reference's partitioned "
                        "PalDB stores (defaults to "
                        "<model-dir>/feature-indexes)")
    p.add_argument("--evaluators", default=None)
    p.add_argument("--id-types", default=None)
    p.add_argument("--stream", action="store_true",
                   help="score through the streaming serving engine in "
                        "bounded memory (O(batch-rows x prefetch depth) "
                        "rows resident; note --evaluators still "
                        "accumulates per-row evaluation columns)")
    p.add_argument("--batch-rows", type=int, default=4096,
                   help="rows per streamed scoring batch (--stream only)")
    p.add_argument("--feeder", choices=["auto", "native", "python"],
                   default="auto",
                   help="--stream decode path: the native C block "
                        "decoder ('auto' falls back to the byte-"
                        "identical python record loop when the "
                        "extension is unbuilt or the schema doesn't "
                        "fit; 'native' errors instead; 'python' forces "
                        "the record loop)")
    p.add_argument("--prefetch-batches", type=int, default=2,
                   help="batches the --stream feeder decodes ahead on a "
                        "background thread (0 = synchronous decode; "
                        "peak resident batches stay bounded by this "
                        "depth + 2)")
    p.add_argument("--serve", action="store_true",
                   help="replay the input as concurrent per-request "
                        "traffic through the async serving front-end "
                        "(request coalescing + admission control; "
                        "mutually exclusive with --stream)")
    p.add_argument("--serve-concurrency", type=int, default=16,
                   help="concurrent closed-loop requesters in --serve "
                        "mode")
    p.add_argument("--coalesce-ms", type=float, default=2.0,
                   help="--serve bounded coalesce window in "
                        "milliseconds (0 = adaptive drain)")
    p.add_argument("--request-rows", type=int, default=1,
                   help="rows per replayed request in --serve mode "
                        "(1 = the single-row serving shape)")
    p.add_argument("--max-pending", type=int, default=1024,
                   help="--serve admission bound (requests admitted and "
                        "unfinished); raised to --serve-concurrency if "
                        "lower, so the closed-loop replay never sheds")
    p.add_argument("--listen", default=None, metavar="ADDR",
                   help="network serving mode (implies --serve): instead "
                        "of replaying the input, open the protocol front "
                        "door on ADDR (PORT, :PORT or HOST:PORT; port 0 "
                        "= ephemeral, written to <output-dir>/net_port) "
                        "speaking HTTP/1.1 JSON (POST /score) AND the "
                        "length-prefixed binary framing on one port, "
                        "both into the front-end's admission path "
                        "(docs/SCALE.md §Serving network front door)")
    p.add_argument("--serve-seconds", type=float, default=None,
                   metavar="S",
                   help="--listen lifetime: serve for S seconds, then "
                        "drain and write the summary (default: until "
                        "SIGINT; the drain still runs)")
    p.add_argument("--adaptive-admission", action="store_true",
                   help="--listen SLO-adaptive admission: a controller "
                        "reads the declared --slo objectives' per-tick "
                        "burn rate and retunes the live shed threshold "
                        "and coalesce window with hysteresis "
                        "(serving/adaptive.py; requires at least one "
                        "--slo)")
    p.add_argument("--distmon", action="store_true",
                   help="distribution observability (--stream/--serve): "
                        "per-model score sketch updated at scatter-back "
                        "(one vectorized update per settled group, "
                        "< 2%% overhead; a no-op without the flag), "
                        "PSI/KS drift scores computed on scrape against "
                        "the model's embedded referenceDistributions "
                        "snapshot (trained with --distmon), exposed as "
                        "serving.model.<label>.score_drift_psi/_ks "
                        "gauges (SLO-able via --slo "
                        "'drift=value:serving.model.default."
                        "score_drift_psi<=0.25'), live /distz with "
                        "--obs-port, and a distributions metrics.json "
                        "block (docs/OBSERVABILITY.md §Distributions & "
                        "drift)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write a Chrome trace-event JSON of the run's "
                        "pipeline spans here (load in Perfetto — "
                        "docs/OBSERVABILITY.md)")
    add_observability_args(p)
    return p


def _maybe_enable_cpu_x64():
    """On CPU, enable x64 for this driver process (when not already on)
    BEFORE the model loads, so coefficients and scores keep the f64
    precision the pre-device host-numpy path always had; on real
    accelerators x64 stays off and scoring runs f32 (the serving
    dtype)."""
    import jax

    if not jax.config.jax_enable_x64 and jax.default_backend() == "cpu":
        try:
            jax.config.update("jax_enable_x64", True)
        except Exception:  # noqa: BLE001 — precision upgrade best-effort
            pass


def _scoring_dtype():
    import jax
    import jax.numpy as jnp

    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _device_scores(model, data, logger):
    """Score a resident dataset on device; host-numpy fallback when a
    sub-model family is not device-scorable (same scores either way).

    The fallback is restricted to the DOCUMENTED contract — the typed
    ``UnsupportedSubModelError`` the scorers raise at construction for a
    sub-model family without a device kernel (or a snapshot past the
    densification ceiling). A bare ``TypeError`` — from construction OR
    dispatch — is a real engine bug and must surface instead of
    silently degrading every score to the slow host path
    (tests/test_cli_drivers.py::test_game_scoring_engine_bug_surfaces)."""
    from photon_ml_tpu.models.device_scoring import DeviceGameScorer
    from photon_ml_tpu.serving.kernels import UnsupportedSubModelError

    try:
        scorer = DeviceGameScorer(model, data, dtype=_scoring_dtype())
    except UnsupportedSubModelError as e:
        logger.info("device scorer unavailable for this model (%s); "
                    "falling back to host numpy scoring", e)
        return model.score(data), "host"
    return np.asarray(scorer.score(model), np.float64), "device"


def run(argv=None) -> dict:
    from photon_ml_tpu.cli import _honor_jax_platforms_env

    _honor_jax_platforms_env()
    _maybe_enable_cpu_x64()
    args = build_parser().parse_args(argv)
    out_dir = Path(args.output_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    logger = setup_photon_logger(out_dir)
    t0 = time.perf_counter()
    # Per-run telemetry: phase spans + registry snapshot in metrics.json
    # (plus --trace-out for Perfetto) — docs/OBSERVABILITY.md.
    telemetry.reset()
    # Same contract as the training driver: trace sampling is on when
    # anything consumes traces — --trace-out, or the live plane's
    # /tracez (federation merges the tail per process).
    telemetry.enable(trace=bool(args.trace_out)
                     or args.obs_port is not None)
    # Live observability plane (docs/OBSERVABILITY.md §Live endpoints):
    # flight recorder armed for the whole run, HTTP endpoints when
    # --obs-port is given (a --serve process becomes scrapeable).
    # Construction/start INSIDE the try: a bad --slo spec or an occupied
    # --obs-port must still unwind through the finally below (obs.stop()
    # reverses whatever start() got through — recorder install, SIGTERM
    # handler — before the failure).
    obs = None
    try:
        obs = DriverObservability(args, out_dir,
                                  role="scoring").start()
        # Root span: module imports, logging, and glue between the named
        # phases land in `driver` SELF time — the stage table sums to
        # the whole run (attributed_wall_frac >= 0.9 even on millisecond
        # runs) instead of leaving silent gaps.
        with span("driver"):
            summary = _run_scoring(args, out_dir, logger, obs)

        wall = time.perf_counter() - t0
        summary["total_seconds"] = wall
        _apply_legacy_aliases(summary)
        obs.finish(summary)
        summary["telemetry"] = telemetry.attribution_summary(wall)
        if args.trace_out:
            telemetry.export_chrome_trace(args.trace_out)
            logger.info("pipeline trace written to %s (load in Perfetto)",
                        args.trace_out)
        (out_dir / "metrics.json").write_text(
            json.dumps(summary, indent=2))
        logger.info("scoring done: %s", summary["metrics"])
        return summary
    except BaseException as e:
        # Unhandled fault: the spans above have already unwound, so the
        # flight ring's last events cover the failing stage.
        if obs is not None:
            obs.dump_fault(e, logger)
        raise
    finally:
        # Exception (incl. the --stream SystemExit paths) or not: don't
        # leave a process-wide recorder or server armed for whatever
        # runs next in this process.
        if obs is not None:
            obs.stop()
        telemetry.disable()


# snake_case canonical -> deprecated camelCase alias, kept one release
# behind (docs/OBSERVABILITY.md §Schema) — ONE table, so a new key can't
# silently miss its twin.
_LEGACY_ALIASES = {
    "num_rows": "numRows",
    "num_batches": "numBatches",
    "batch_rows": "batchRows",
    "scoring_path": "scoringPath",
    "total_seconds": "totalSeconds",
}


def _apply_legacy_aliases(summary: dict) -> dict:
    for snake, camel in _LEGACY_ALIASES.items():
        if snake in summary:
            summary[camel] = summary[snake]
    return summary


def _run_scoring(args, out_dir, logger, obs) -> dict:
    from photon_ml_tpu.data.paldb import load_feature_index_maps

    # Flag contradictions fail BEFORE the model loads: a bad invocation
    # should not pay (or need) a model-directory read to be diagnosed.
    if args.listen is not None:
        args.serve = True  # --listen IS the network serving shape
    if args.stream and args.serve:
        raise SystemExit("--stream and --serve are mutually exclusive: "
                         "--stream is the bounded-memory bulk path, "
                         "--serve the concurrent-request replay harness")
    if args.adaptive_admission and args.listen is None:
        raise SystemExit("--adaptive-admission retunes a live network "
                         "front door; pass --listen")
    if args.adaptive_admission and not args.slo:
        raise SystemExit("--adaptive-admission steers on the declared "
                         "--slo objectives; pass at least one --slo")
    if args.distmon and not (args.stream or args.serve):
        raise SystemExit("--distmon attaches score sketches to the "
                         "streaming engine's scatter-back; pass "
                         "--stream or --serve")

    model_dir = Path(args.game_model_input_dir)
    index_dir = Path(args.feature_index_dir) if args.feature_index_dir else \
        model_dir / "feature-indexes"
    with span("load_model"):
        shard_maps = load_feature_index_maps(index_dir)
        model = load_game_model(model_dir, shard_maps)
    # Liveness vs readiness split: the model is resident, so this
    # process can serve — /readyz flips 200 here, while /healthz was
    # answering "alive" from the moment the server came up.
    obs.mark_ready("model_loaded")

    with span("setup"):
        meta = json.loads((model_dir / "model-metadata.json").read_text())
        id_types = sorted(
            {c["randomEffectType"] for c in meta["coordinates"]
             if c["kind"] == "random"} |
            # MF coordinates key rows by both their entity axes.
            {c[k] for c in meta["coordinates"] if c["kind"] == "mf"
             for k in ("rowEffectType", "colEffectType")} |
            {s.strip() for s in (args.id_types or "").split(",")
             if s.strip()})

        inputs = resolve_input_dirs(
            args.input_dirs, date_range=args.date_range,
            date_range_days_ago=args.date_range_days_ago)

        evaluators = [build_evaluator(s.strip())
                      for s in (args.evaluators or "").split(",")
                      if s.strip()]
        scores_dir = out_dir / "scores"
        scores_dir.mkdir(exist_ok=True)
        scores_path = scores_dir / "part-00000.avro"

    # The model's embedded reference distributions (stamped by a
    # --stream-train --distmon run) — what serving drift-scores
    # against. None for models trained without --distmon.
    reference = meta.get("referenceDistributions")
    if args.serve:
        summary = _run_serve(args, inputs, id_types, shard_maps, model,
                             evaluators, scores_path, logger, obs,
                             reference)
    elif args.stream:
        summary = _run_stream(args, inputs, id_types, shard_maps, model,
                              evaluators, scores_path, logger, obs,
                              reference)
    else:
        with span("ingest"):
            data, _ = read_game_dataset(inputs, id_types=id_types,
                                        feature_shard_maps=shard_maps)
        with span("score"):
            scores, path_used = _device_scores(model, data, logger)
        logger.info("scored %d rows (%s path)", data.num_rows, path_used)

        with span("write_scores"):
            uids = data.uids if data.uids is not None else \
                np.asarray([str(i) for i in range(data.num_rows)])
            write_container(
                scores_path, schemas.SCORING_RESULT,
                [{"uid": str(u), "predictionScore": float(s + o),
                  "label": float(l), "metadataMap": None}
                 for u, s, o, l in zip(uids, scores, data.offsets,
                                       data.responses)])
        with span("evaluate"):
            metrics = {ev.name: ev.evaluate_dataset(scores, data)
                       for ev in evaluators}
        summary = {
            "num_rows": int(data.num_rows),
            "metrics": metrics,
            "scoring_path": path_used,
        }
    return summary


def _attach_score_monitor(args, engine, label, reference, obs):
    """--distmon: hang a ScoreDistributionMonitor off the engine's
    scatter-back settle, register /distz + the drift-gauge scrape hook
    (drift computes on scrape — /metrics, /statusz, /distz, heartbeat —
    and once more at finish before the SLO block). Returns the monitor
    (None without the flag: the settle path stays a no-op branch)."""
    if not args.distmon:
        return None
    from photon_ml_tpu.data.distmon import ScoreDistributionMonitor

    mon = ScoreDistributionMonitor(label, reference=reference)
    engine.score_monitor = mon
    obs.add_dist_provider("serving", lambda: {label: mon.snapshot()})
    obs.add_scrape_hook("score_drift", mon.publish_gauges)
    obs.add_sketch_provider("serving", mon.sketch_states)
    return mon


def _run_stream(args, inputs, id_types, shard_maps, model, evaluators,
                scores_path, logger, obs, reference=None) -> dict:
    """Bounded-memory scoring through the three-stage decode -> H2D ->
    dispatch pipeline (serving engine `score_container_stream`: the
    block-stream feeder decodes + featureizes batch k+1 on its prefetch
    thread while batch k's dispatch is in flight), with incremental
    ScoringResultAvro writes. Only evaluation columns (when evaluators are
    requested) accumulate across batches — never features — so metrics
    cost O(total rows) of scalars/id strings while feature memory stays
    O(batch_rows x (prefetch + pipeline depth))."""
    from photon_ml_tpu.serving import (
        StreamingGameScorer,
        UnsupportedSubModelError,
    )

    try:
        with span("setup_engine"):
            engine = StreamingGameScorer(model, dtype=_scoring_dtype())
    except UnsupportedSubModelError as e:
        # Only the documented not-device-scorable contract exits cleanly;
        # any other TypeError is an engine bug and propagates.
        raise SystemExit(
            f"--stream requires a device-scorable model: {e}") from e
    score_mon = _attach_score_monitor(args, engine, "default",
                                      reference, obs)

    try:
        # Stream construction scans the container block index (real I/O)
        # — covered so tiny runs still attribute >= 90% of wall time.
        with span("setup_stream"):
            scored = engine.score_container_stream(
                inputs, id_types=id_types, feature_shard_maps=shard_maps,
                batch_rows=args.batch_rows, feeder=args.feeder,
                prefetch_depth=args.prefetch_batches)
    except RuntimeError as e:
        raise SystemExit(str(e)) from e
    logger.info("streamed scoring: %s feeder, prefetch depth %d",
                scored.stream.decode_path, scored.stream.prefetch_depth)
    from photon_ml_tpu.evaluation.validation import StreamedEvalAccumulator

    counters = {"rows": 0, "batches": 0}
    acc = StreamedEvalAccumulator(id_types) if evaluators else None

    def scored_records():
        for ds, scores in scored:
            counters["rows"] += ds.num_rows
            counters["batches"] += 1
            if acc is not None:
                acc.add(ds, scores)
            uids = ds.uids if ds.uids is not None else \
                np.asarray([str(i) for i in range(ds.num_rows)])
            for u, s, o, l in zip(uids, scores, ds.offsets, ds.responses):
                yield {"uid": str(u), "predictionScore": float(s + o),
                       "label": float(l), "metadataMap": None}

    # One phase span over the whole pipeline consumption; the per-stage
    # split (decode / featureize / dispatch / device_wait / ...) nests
    # inside it, decode on the prefetch thread's own trace track.
    with span("score"):
        write_container(scores_path, schemas.SCORING_RESULT,
                        scored_records())
    logger.info("scored %d rows in %d streamed batches (batch-rows=%d)",
                counters["rows"], counters["batches"], args.batch_rows)

    with span("evaluate"):
        metrics = acc.metrics(evaluators) if acc is not None else {}
    summary = {
        "num_rows": counters["rows"],
        "metrics": metrics,
        "scoring_path": "streaming-engine",
        "num_batches": counters["batches"],
        "batch_rows": args.batch_rows,
        "feeder": scored.stream.stats(),
        "engine": engine.stats(),
    }
    if score_mon is not None:
        score_mon.publish_gauges()
        summary["distributions"] = {"default": score_mon.snapshot()}
    return summary


def _run_serve(args, inputs, id_types, shard_maps, model, evaluators,
               scores_path, logger, obs, reference=None) -> dict:
    """Concurrent-request replay through the async serving front-end:
    the decoded input splits into ``--request-rows``-row requests,
    ``--serve-concurrency`` closed-loop requesters submit them on an
    event loop, and the front-end coalesces each ``--coalesce-ms``
    window into shared bucket dispatches. Unlike --stream this harness
    holds the decoded requests (and their scores) in memory — it
    exercises the serving shape, not the bounded-memory one."""
    from photon_ml_tpu.data.avro_reader import iter_game_dataset_batches
    from photon_ml_tpu.evaluation.validation import StreamedEvalAccumulator
    from photon_ml_tpu.serving import (
        FrontendConfig,
        ServingFrontend,
        UnsupportedSubModelError,
    )

    if args.request_rows < 1:
        raise SystemExit("--request-rows must be >= 1")
    try:
        with span("setup_engine"):
            frontend = ServingFrontend(
                {"default": model}, dtype=_scoring_dtype(),
                config=FrontendConfig(
                    coalesce_window_s=args.coalesce_ms / 1e3,
                    max_pending=max(args.max_pending,
                                    args.serve_concurrency)))
    except UnsupportedSubModelError as e:
        raise SystemExit(
            f"--serve requires a device-scorable model: {e}") from e
    # /statusz carries the front-end's live stats() — per-model serving
    # stats, admission counters, and the shared executable cache's
    # tracing-guard counts (docs/OBSERVABILITY.md §Live endpoints).
    obs.add_status_provider("frontend", frontend.stats)
    score_mon = _attach_score_monitor(args, frontend.engine("default"),
                                      "default", reference, obs)

    if args.listen is not None:
        summary = _run_listen(args, frontend, logger, obs)
        if score_mon is not None:
            score_mon.publish_gauges()
            summary["distributions"] = {"default": score_mon.snapshot()}
        return summary

    with span("ingest"):
        requests = []
        for ds in iter_game_dataset_batches(
                inputs, id_types=id_types, feature_shard_maps=shard_maps,
                batch_rows=args.batch_rows, feeder=args.feeder,
                prefetch_depth=args.prefetch_batches):
            for a in range(0, ds.num_rows, args.request_rows):
                requests.append(ds.subset(np.arange(
                    a, min(a + args.request_rows, ds.num_rows))))
    logger.info("serving replay: %d requests (%d rows each), "
                "concurrency %d, coalesce window %.1f ms",
                len(requests), args.request_rows, args.serve_concurrency,
                args.coalesce_ms)

    with span("score"):
        results, info = frontend.replay(
            requests, concurrency=args.serve_concurrency)
    assert info["shed"] == 0, \
        "closed-loop replay can never shed (max_pending >= concurrency)"
    if info["errors"]:
        raise SystemExit(
            f"--serve: {info['errors']} requests failed "
            "(see log; scores would be incomplete)")

    acc = StreamedEvalAccumulator(id_types) if evaluators else None
    counters = {"rows": 0}

    def scored_records():
        uid_base = 0
        for ds, scores in zip(requests, results):
            counters["rows"] += ds.num_rows
            if acc is not None:
                acc.add(ds, scores)
            uids = ds.uids if ds.uids is not None else np.asarray(
                [str(uid_base + i) for i in range(ds.num_rows)])
            uid_base += ds.num_rows
            for u, s, o, l in zip(uids, scores, ds.offsets, ds.responses):
                yield {"uid": str(u), "predictionScore": float(s + o),
                       "label": float(l), "metadataMap": None}

    with span("write_scores"):
        write_container(scores_path, schemas.SCORING_RESULT,
                        scored_records())
    with span("evaluate"):
        metrics = acc.metrics(evaluators) if acc is not None else {}
    summary = {
        "num_rows": counters["rows"],
        "metrics": metrics,
        "scoring_path": "async-frontend",
        "num_requests": len(requests),
        "request_rows": args.request_rows,
        "coalesce_window_ms": args.coalesce_ms,
        "concurrency": args.serve_concurrency,
        "frontend": frontend.stats(),
    }
    if score_mon is not None:
        score_mon.publish_gauges()
        summary["distributions"] = {"default": score_mon.snapshot()}
    return summary


def _parse_listen(addr: str):
    """'PORT', ':PORT' or 'HOST:PORT' -> (host, port); SystemExit on
    anything else (CLI validation, not a fault)."""
    addr = addr.strip()
    if ":" in addr:
        host, _, port = addr.rpartition(":")
        host = host or "127.0.0.1"
    else:
        host, port = "127.0.0.1", addr
    try:
        return host, int(port)
    except ValueError:
        raise SystemExit(f"bad --listen address {addr!r} "
                         "(PORT, :PORT or HOST:PORT)") from None


def _run_listen(args, frontend, logger, obs) -> dict:
    """--listen: open the network front door over the front-end and
    serve real sockets instead of replaying the input (which is ignored
    — requests arrive over the wire). The bound port lands in
    <output-dir>/net_port the moment the listener is up; the drain on
    exit (--serve-seconds elapsed or SIGINT) lets every admitted
    request settle and flush before the summary is written."""
    import asyncio

    from photon_ml_tpu.serving.adaptive import AdaptiveAdmission
    from photon_ml_tpu.serving.netserver import NetServer, NetServerConfig

    host, port = _parse_listen(args.listen)
    out_dir = Path(args.output_dir)
    report = {}

    async def serve() -> None:
        async with frontend:
            server = await NetServer(
                frontend, NetServerConfig(host=host, port=port)).start()
            ctl = None
            try:
                if args.adaptive_admission:
                    ctl = await AdaptiveAdmission(
                        frontend, slo_specs=args.slo).start()
                    obs.add_status_provider("adaptive_admission",
                                            ctl.stats)
                obs.add_status_provider("netserver", server.stats)
                (out_dir / "net_port").write_text(str(server.port))
                obs.mark_ready("serving")
                logger.info(
                    "serving on %s:%d (HTTP/1.1 + binary framing)%s",
                    host, server.port,
                    " with SLO-adaptive admission"
                    if ctl is not None else "")
                if args.serve_seconds is not None:
                    await asyncio.sleep(args.serve_seconds)
                else:
                    while True:
                        await asyncio.sleep(3600)
            finally:
                if ctl is not None:
                    await ctl.stop()
                    report["adaptive_admission"] = ctl.stats()
                await server.close()
                report["net"] = server.stats()

    with span("serve"):
        try:
            asyncio.run(serve())
        except KeyboardInterrupt:
            logger.info("interrupted; network front door drained")
    return {
        "num_rows": 0,  # rows served are in frontend/engine stats
        "metrics": {},
        "scoring_path": "netserver",
        "listen": f"{host}:{port}",
        **report,
        "frontend": frontend.stats(),
    }


def main() -> None:
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
