"""Replica-fleet router CLI: the binary-framing front over N
``--serve --listen`` scoring processes (serving/router.py — least-
pending request spreading, pure passthrough, no JAX in-process).

    python -m photon_ml_tpu.cli.net_router \\
        --listen :7001 --backend 127.0.0.1:7002 --backend 127.0.0.1:7003

The router process is deliberately tiny (asyncio + struct only — it
never imports jax/numpy): in the fleet bench it shares a core with the
loadgen while every replica burns its own. ``--port-file`` writes the
bound port (plain int) the moment the listener is up, the same
handshake the scoring driver's ``net_port`` file gives a harness.
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from pathlib import Path
from typing import List, Tuple


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-net-router")
    p.add_argument("--listen", default=":0", metavar="ADDR",
                   help="PORT, :PORT or HOST:PORT (0 = ephemeral; see "
                        "--port-file)")
    p.add_argument("--backend", action="append", required=True,
                   metavar="HOST:PORT",
                   help="a replica's binary-framing address "
                        "(repeatable; at least one)")
    p.add_argument("--policy", choices=["least_pending", "round_robin"],
                   default="least_pending",
                   help="request spreading policy (least_pending breaks "
                        "ties round-robin)")
    p.add_argument("--port-file", default=None, metavar="PATH",
                   help="write the bound port here (plain int) once "
                        "listening")
    p.add_argument("--serve-seconds", type=float, default=None,
                   metavar="S",
                   help="serve for S seconds then drain (default: until "
                        "SIGINT)")
    return p


def _parse_addr(addr: str, flag: str) -> Tuple[str, int]:
    addr = addr.strip()
    if ":" in addr:
        host, _, port = addr.rpartition(":")
        host = host or "127.0.0.1"
    else:
        host, port = "127.0.0.1", addr
    try:
        return host, int(port)
    except ValueError:
        raise SystemExit(f"bad {flag} address {addr!r} "
                         "(PORT, :PORT or HOST:PORT)") from None


def run(argv=None) -> dict:
    from photon_ml_tpu.serving.router import ReplicaRouter, RouterConfig

    args = build_parser().parse_args(argv)
    host, port = _parse_addr(args.listen, "--listen")
    backends: List[Tuple[str, int]] = [
        _parse_addr(b, "--backend") for b in args.backend]
    report = {}

    async def serve() -> None:
        router = await ReplicaRouter(
            backends, RouterConfig(host=host, port=port,
                                   policy=args.policy)).start()
        try:
            if args.port_file:
                Path(args.port_file).write_text(str(router.port))
            if args.serve_seconds is not None:
                await asyncio.sleep(args.serve_seconds)
            else:
                while True:
                    await asyncio.sleep(3600)
        finally:
            await router.close()
            report["router"] = router.stats()

    try:
        asyncio.run(serve())
    except KeyboardInterrupt:
        pass
    return report.get("router", {})


def main() -> None:
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
