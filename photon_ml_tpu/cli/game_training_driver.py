"""GAME training driver (reference: ml/cli/game/training/Driver.scala:43-298,
params from ml/estimators/GameParams.scala:40-427).

Coordinate mini-DSLs preserved from the reference:
  --fixed-effect-data-configurations   name:featureShardId
  --random-effect-data-configurations  name:reType,shardId,numPartitions,
                                       activeBound,passiveBound,ratio[,proj]
  --fixed-effect-optimization-configurations / --random-effect-...:
                                       name:maxIter,tol,λ,rate,optimizer,reg
                                       (| separates grid points)
  --updating-sequence                  comma-separated coordinate names
Outputs: <output-dir>/best/ (saved GAME model), metrics.json, log.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from photon_ml_tpu import telemetry
from photon_ml_tpu.cli.obs import DriverObservability, add_observability_args
from photon_ml_tpu.data.avro_reader import read_game_dataset
from photon_ml_tpu.data.random_effect import RandomEffectDataConfiguration
from photon_ml_tpu.estimators.game_estimator import (
    FactoredRandomEffectSpec,
    FixedEffectSpec,
    GameEstimator,
    RandomEffectSpec,
)
from photon_ml_tpu.evaluation import build_evaluator
from photon_ml_tpu.io.model_io import save_game_model
from photon_ml_tpu.optimization.config import (
    FactoredRandomEffectOptimizationConfiguration,
    GLMOptimizationConfiguration,
)
from photon_ml_tpu.telemetry import span
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils.date_range import resolve_input_dirs
from photon_ml_tpu.utils.events import (
    EventEmitter,
    PhotonOptimizationLogEvent,
    TrainingFinishEvent,
    TrainingStartEvent,
)
from photon_ml_tpu.utils.logging_utils import setup_photon_logger
from photon_ml_tpu.utils.profiling import maybe_trace


def _parse_named(values, what):
    out = {}
    for item in values or []:
        name, _, rest = item.partition(":")
        if not rest:
            raise ValueError(f"bad {what} {item!r}: expected 'name:...'")
        out[name.strip()] = rest.strip()
    return out


def _positive_int(s: str) -> int:
    v = int(s)
    if v < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
    return v


def _parse_mesh_shape(s: str) -> tuple:
    """'RxC' -> (R, C): data-axis x model-axis device extents."""
    parts = s.strip().lower().split("x")
    if len(parts) != 2:
        raise argparse.ArgumentTypeError(
            f"bad mesh shape {s!r} (expected RxC, e.g. 2x2, 4x1)")
    try:
        r, c = int(parts[0]), int(parts[1])
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"bad mesh shape {s!r} (expected RxC, e.g. 2x2, 4x1)")
    if r < 1 or c < 1:
        raise argparse.ArgumentTypeError(
            f"mesh extents must be >= 1, got {r}x{c}")
    return (r, c)


def _mesh_shape(args) -> tuple | None:
    """Resolved (data, model) mesh extents: --mesh-shape RxC, or the
    back-compat --mesh-devices N == Nx1; None when neither is given."""
    if args.mesh_shape is not None:
        return args.mesh_shape
    if args.mesh_devices is not None:
        return (args.mesh_devices, 1)
    return None


_BYTE_SUFFIXES = {"K": 1 << 10, "M": 1 << 20, "G": 1 << 30, "T": 1 << 40}


def parse_byte_size(s: str) -> int:
    """'512M', '8G', '1048576' -> bytes."""
    s = s.strip().upper().removesuffix("B")
    mult = 1
    if s and s[-1] in _BYTE_SUFFIXES:
        mult = _BYTE_SUFFIXES[s[-1]]
        s = s[:-1]
    try:
        v = int(float(s) * mult)
    except (ValueError, OverflowError):  # OverflowError: 'inf', '1e999'
        raise argparse.ArgumentTypeError(
            f"bad byte size {s!r} (expected e.g. 512M, 8G, 1048576)")
    if v < 1:
        raise argparse.ArgumentTypeError(f"byte size must be >= 1, got {v}")
    return v


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-game-training-driver",
        description="Train GAME models (fixed + random effects)")
    p.add_argument("--train-input-dirs", required=True)
    p.add_argument("--validate-input-dirs", default=None)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--task-type", required=True,
                   choices=[t.value for t in TaskType])
    p.add_argument("--fixed-effect-data-configurations", nargs="*",
                   default=[], metavar="name:featureShardId")
    p.add_argument("--fixed-effect-optimization-configurations", nargs="*",
                   default=[], metavar="name:optConfig[|optConfig...]")
    p.add_argument("--random-effect-data-configurations", nargs="*",
                   default=[], metavar="name:reDataConfig")
    p.add_argument("--random-effect-optimization-configurations", nargs="*",
                   default=[], metavar="name:optConfig[|optConfig...]")
    p.add_argument("--factored-random-effect-data-configurations", nargs="*",
                   default=[], metavar="name:reDataConfig")
    p.add_argument("--factored-random-effect-optimization-configurations",
                   nargs="*", default=[],
                   metavar="name:reOpt;latentOpt;mfMaxIter,numFactors[|...]")
    p.add_argument("--updating-sequence", required=True,
                   help="comma-separated coordinate order")
    p.add_argument("--train-date-range", default=None,
                   help="yyyyMMdd-yyyyMMdd; expands daily/yyyy/MM/dd "
                        "subdirs of the train input dirs")
    p.add_argument("--train-date-range-days-ago", default=None,
                   help="start-end in days ago, e.g. 90-1")
    p.add_argument("--validate-date-range", default=None)
    p.add_argument("--validate-date-range-days-ago", default=None)
    # >= 1 enforced: the stream-train λ-grid loop reads the last
    # tracker after its solves, and 0 iterations never made a model on
    # any path anyway.
    p.add_argument("--num-iterations", type=_positive_int, default=1)
    p.add_argument("--checkpoint-dir", default=None,
                   help="resumable coordinate-descent checkpoints land "
                        "here; a rerun resumes from the latest")
    p.add_argument("--checkpoint-interval", type=_positive_int, default=1,
                   help="coordinate updates between checkpoints (>=1)")
    p.add_argument("--evaluators", default=None,
                   help="comma-separated evaluator specs (first selects)")
    p.add_argument("--id-types", default=None,
                   help="extra entity id columns to read from metadataMap "
                        "(defaults to the random-effect types)")
    p.add_argument("--ingest-workers", default="auto",
                   help="Avro decode worker processes: 'auto' (usable "
                        "cores) or an int; >= 2 decodes file shards in "
                        "parallel with byte-identical output, 1 forces "
                        "single-process decode")
    p.add_argument("--feature-index-dir", default=None,
                   help="pre-built feature index stores keyed by shard id: "
                        "the reference's partitioned PalDB stores "
                        "(paldb-partition-<shard>-<N>.dat, "
                        "ml/util/PalDBIndexMap.scala) or this package's "
                        "<shard>.json stores; replaces the Avro-scan "
                        "index-building pass")
    p.add_argument("--profile-output-dir", default=None,
                   help="write a jax.profiler trace of training here "
                        "(view with XProf/TensorBoard)")
    p.add_argument("--save-all-models", default="false",
                   choices=["true", "false"],
                   help="model-output-mode ALL vs BEST")
    p.add_argument("--stream-train", action="store_true",
                   help="out-of-core training: ingest the training Avro "
                        "through the block-streaming C-decoded pipeline "
                        "in --batch-rows batches (host memory stays "
                        "O(batch)) instead of one-shot-materializing it. "
                        "Without --hbm-budget the shards assemble into "
                        "the exact one-shot device batch (byte-identical "
                        "model, fused solvers); with --hbm-budget the "
                        "solve streams over a device shard cache with "
                        "replay-aware spill. Supports a single "
                        "fixed-effect "
                        "coordinate")
    p.add_argument("--batch-rows", type=_positive_int, default=4096,
                   help="rows per streamed ingest batch (and per cached "
                        "device shard in --hbm-budget mode)")
    p.add_argument("--hbm-budget", default=None, metavar="BYTES",
                   type=parse_byte_size,
                   help="device-memory budget for cached feature blocks "
                        "(e.g. 512M, 8G): furthest-next-use shards "
                        "spill to host column buffers and re-upload "
                        "overlapped with the accumulate. Selects the "
                        "sharded streaming solve (L2 LBFGS/TRON only). "
                        "With --mesh-devices the budget is PER DEVICE")
    p.add_argument("--grid-batched", choices=["auto", "on", "off"],
                   default="auto",
                   help="batch the λ₂ grid into ONE streamed sweep "
                        "(--stream-train --hbm-budget): coefficients "
                        "stack to [G, d] and every feature pass over "
                        "the shard cache advances ALL G grid points "
                        "through vmapped per-bucket kernels, so a "
                        "sweep costs the slowest point's pass count "
                        "instead of the sum over points (~G× less "
                        "decode + re-upload traffic). 'auto' (default) "
                        "batches when the grid has > 1 point and is "
                        "batchable (homogeneous LBFGS/TRON, L2 only); "
                        "'on' forces batching and errors when it "
                        "can't; 'off' keeps the sequential per-λ "
                        "sweep. G=1 batched delegates to the scalar "
                        "streamed solver (bit-identical model bytes), "
                        "and exact selection ties break to the "
                        "smallest λ on every path "
                        "(docs/SCALE.md §Batched λ-grid)")
    p.add_argument("--mesh-devices", type=_positive_int, default=None,
                   metavar="N",
                   help="fold the --hbm-budget streaming solve over a "
                        "1-D mesh of the first N devices: cached shards "
                        "place round-robin (shard i on device i mod N), "
                        "per-shard partials accumulate on their own "
                        "device, and the fold combines in fixed shard "
                        "order — the model is bit-identical for every "
                        "N (docs/SCALE.md §Training memory envelope). "
                        "Requires --stream-train; N > 1 additionally "
                        "requires --hbm-budget. N=1 is exactly the "
                        "single-device fold. Equivalent to "
                        "--mesh-shape Nx1")
    p.add_argument("--mesh-shape", type=_parse_mesh_shape, default=None,
                   metavar="RxC",
                   help="fold the --hbm-budget streaming solve over a "
                        "2-D (data x model) mesh of R x C devices: "
                        "cached shards place round-robin over the R "
                        "data rows AND split into C column blocks of "
                        "the coefficient dimension, one per model-axis "
                        "device — no device holds a full-width "
                        "coefficient vector (per-device HBM ~ "
                        "budget/(R*C), docs/SCALE.md). Margins chain "
                        "across each row's devices, gradients "
                        "re-assemble by deterministic column concat, "
                        "so the model is bit-identical for every "
                        "shape in {1x1, 2x1, 1x2, 2x2, ...}. "
                        "Back-compat: --mesh-devices N == Nx1 (pass "
                        "one of the two). Requires --stream-train; "
                        "R*C > 1 additionally requires --hbm-budget")
    p.add_argument("--spill-dtype", choices=["f32", "bf16"],
                   default="f32",
                   help="--hbm-budget spill-buffer encoding: 'f32' "
                        "(default) spills evicted feature blocks as the "
                        "raw padded f32/i32 triplet (re-uploads are the "
                        "evicted bytes — today's bitwise guarantees); "
                        "'bf16' spills bfloat16 values + delta-encoded "
                        "u8/u16 indices (~1/3 of the f32 spill bytes "
                        "AND per-epoch re-upload traffic; restore "
                        "decodes back to f32 on device, with documented "
                        "parity bounds vs the f32-spill model — "
                        "docs/SCALE.md)")
    p.add_argument("--spill-source", choices=["buffer", "redecode"],
                   default="buffer",
                   help="where evicted --hbm-budget blocks come back "
                        "from: 'buffer' (default) re-uploads host spill "
                        "buffers (host RAM O(dataset)); 'redecode' "
                        "keeps NO host copy — cache misses re-decode "
                        "the covering Avro container blocks "
                        "(prefetch-overlapped with the accumulate), so "
                        "host memory is O(budget + one block) and "
                        "trainable size is disk-bounded")
    p.add_argument("--feeder", choices=["auto", "native", "python"],
                   default="auto",
                   help="--stream-train decode path (see "
                        "data/block_stream.py); 'python' forces the "
                        "byte-identical record-loop fallback")
    p.add_argument("--prefetch-batches", type=int, default=2,
                   help="decode-ahead depth of the --stream-train feeder "
                        "(and spill re-upload look-ahead); 0 disables")
    p.add_argument("--distmon", action="store_true",
                   help="distribution observability (--stream-train "
                        "only): streaming label/weight/offset/feature "
                        "sketches piggybacked on the decode pass (zero "
                        "extra feature passes; snapshots bitwise-"
                        "identical across residency/feeder/prefetch "
                        "configs), per-λ convergence rings, a "
                        "data_quality metrics.json block, live /distz "
                        "(with --obs-port), and a reference "
                        "distribution snapshot (label + training-score "
                        "quantiles) stamped into the model artifact "
                        "for serving-side drift scoring "
                        "(docs/OBSERVABILITY.md §Distributions & "
                        "drift)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write a Chrome trace-event JSON of the run's "
                        "pipeline spans here (load in Perfetto — "
                        "docs/OBSERVABILITY.md)")
    p.add_argument("--job-name", default="photon-game-training",
                   help="job name carried on Training{Start,Finish} "
                        "events")
    p.add_argument("--event-listeners", default=None,
                   help="comma-separated EventListener class paths "
                        "registered by name (utils/events.py) — the "
                        "reference's listener registration, e.g. "
                        "my.module.MyListener")
    add_observability_args(p)
    return p


def run(argv=None) -> dict:
    from photon_ml_tpu.cli import _honor_jax_platforms_env

    _honor_jax_platforms_env()
    args = build_parser().parse_args(argv)
    out_dir = Path(args.output_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    logger = setup_photon_logger(out_dir)
    task = TaskType(args.task_type)
    t0 = time.perf_counter()
    # The driver owns this process's telemetry: per-run metrics + stage
    # spans land in metrics.json (and --trace-out); library code is
    # instrumented but silent outside a driver (docs/OBSERVABILITY.md).
    telemetry.reset()
    # Trace sampling is on whenever something will consume traces: a
    # --trace-out export, or the live plane (--obs-port serves /tracez
    # and federation merges it — a plane whose trace tail is always
    # empty breaks the fleet aggregator's per-process attribution).
    telemetry.enable(trace=bool(args.trace_out)
                     or args.obs_port is not None)
    # Live observability plane (docs/OBSERVABILITY.md §Live endpoints):
    # flight recorder armed for the whole run; with --obs-port a
    # multi-hour --stream-train becomes scrapeable, with a 1 Hz
    # heartbeat refreshing liveness gauges / registry deltas / SLO
    # burn between solver iterations. Construction/start INSIDE the
    # try: a bad --slo spec or occupied --obs-port must still unwind
    # through the finally below.
    obs = None
    emitter = EventEmitter()
    try:
        obs = DriverObservability(args, out_dir, heartbeat_s=1.0,
                                  role="training").start()
        for cp in (args.event_listeners or "").split(","):
            if cp.strip():
                emitter.register_listener_by_name(cp.strip())
        emitter.send_event(TrainingStartEvent(args.job_name))
        # Root span: config parsing, event emission, and glue between
        # the named phases land in `driver` SELF time (same scheme as
        # the scoring driver), so the stage table sums to the whole run
        # even on millisecond runs.
        with span("driver"):
            (sequence, results, best_configs, best_result, shard_maps,
             num_rows, stream_info, distmon_out) = _run_training(
                args, logger, task, emitter, obs)
            # Liveness vs readiness split: /readyz flips true only
            # after the solve succeeded (a just-booted process must
            # not scrape ready — docs/OBSERVABILITY.md §Federation).
            obs.mark_ready("solve_complete")
            _save_outputs(args, out_dir, logger, sequence, results,
                          best_configs, best_result, shard_maps,
                          extra_metadata=(
                              {"referenceDistributions":
                               distmon_out["reference"]}
                              if distmon_out is not None else None))
        summary = _write_summary(args, out_dir, logger, task, sequence,
                                 t0, results, best_configs, best_result,
                                 num_rows, stream_info, obs,
                                 data_quality=(
                                     distmon_out["data_quality"]
                                     if distmon_out is not None
                                     else None))
        emitter.send_event(
            TrainingFinishEvent(args.job_name, summary["totalSeconds"]))
        return summary
    except BaseException as e:
        # Unhandled fault: the phase spans have already unwound, so the
        # flight ring's last events cover the failing stage.
        if obs is not None:
            obs.dump_fault(e, logger)
        raise
    finally:
        # Exception or not: close listeners and disarm the process-wide
        # recorder/server so whatever runs next in this process starts
        # clean.
        emitter.clear_listeners()
        if obs is not None:
            obs.stop()
        telemetry.disable()


def _run_training(args, logger, task, emitter, obs):
    """Config parse + train (one-shot estimator or --stream-train);
    returns everything the save/summary tail needs. ``obs`` (the
    driver's observability plane) lets the stream-train path register
    live /statusz providers as its components come up."""
    fe_data = _parse_named(args.fixed_effect_data_configurations,
                           "fixed-effect data config")
    fe_opt = _parse_named(args.fixed_effect_optimization_configurations,
                          "fixed-effect optimization config")
    re_data = {
        name: RandomEffectDataConfiguration.parse(cfg)
        for name, cfg in _parse_named(
            args.random_effect_data_configurations,
            "random-effect data config").items()}
    re_opt = _parse_named(args.random_effect_optimization_configurations,
                          "random-effect optimization config")
    fre_data = {
        name: RandomEffectDataConfiguration.parse(cfg)
        for name, cfg in _parse_named(
            args.factored_random_effect_data_configurations,
            "factored-random-effect data config").items()}
    fre_opt = _parse_named(
        args.factored_random_effect_optimization_configurations,
        "factored-random-effect optimization config")

    sequence = [s.strip() for s in args.updating_sequence.split(",")]
    for name in sequence:
        if name not in fe_data and name not in re_data \
                and name not in fre_data:
            raise ValueError(
                f"updating-sequence entry {name!r} has no data configuration")

    id_types = sorted(
        {c.random_effect_type for c in re_data.values()} |
        {c.random_effect_type for c in fre_data.values()} |
        {s.strip() for s in (args.id_types or "").split(",") if s.strip()})

    preloaded_maps = None
    if args.feature_index_dir:
        from photon_ml_tpu.data.paldb import load_feature_index_maps

        preloaded_maps = load_feature_index_maps(args.feature_index_dir)
        logger.info(
            "loaded feature index stores from %s: %s", args.feature_index_dir,
            {k: len(v) for k, v in sorted(preloaded_maps.items())})

    train_inputs = resolve_input_dirs(
        args.train_input_dirs,
        date_range=args.train_date_range,
        date_range_days_ago=args.train_date_range_days_ago)

    def parse_grid(s: str):
        return [GLMOptimizationConfiguration.parse(part)
                for part in s.split("|")]

    def opt_grid(table, name, flag):
        if name not in table:
            raise ValueError(
                f"coordinate {name!r} has no optimization configuration — "
                f"pass it via {flag} (have {sorted(table) or 'none'})")
        return parse_grid(table[name])

    evaluators = [build_evaluator(s.strip())
                  for s in (args.evaluators or "").split(",") if s.strip()]

    if args.mesh_shape is not None and args.mesh_devices is not None:
        raise ValueError(
            "--mesh-shape and --mesh-devices are two spellings of the "
            "same mesh (--mesh-devices N == --mesh-shape Nx1); pass one")
    mesh_rc = _mesh_shape(args)
    if mesh_rc is not None and not args.stream_train:
        raise ValueError(
            "--mesh-devices/--mesh-shape apply to the --stream-train "
            "solve; pass --stream-train (and --hbm-budget for a mesh "
            "of > 1 device)")
    if mesh_rc is not None and mesh_rc[0] * mesh_rc[1] > 1 \
            and args.hbm_budget is None:
        raise ValueError(
            "a mesh of > 1 device requires --hbm-budget: the device "
            "fold runs over the sharded shard-cache solve (the "
            "resident assembled path is a single fused device batch)")
    if args.grid_batched != "auto" and not args.stream_train:
        raise ValueError(
            "--grid-batched applies to the --stream-train λ-grid "
            "sweep; pass --stream-train (the one-shot estimator "
            "trains the grid one combination at a time)")
    if args.grid_batched == "on" and args.hbm_budget is None:
        raise ValueError(
            "--grid-batched on requires --hbm-budget: the batched "
            "sweep runs over the sharded shard-cache solve (the "
            "resident assembled path reuses the fused one-shot "
            "solvers, which already share the device batch across "
            "the grid)")
    if args.spill_dtype != "f32" and args.hbm_budget is None:
        raise ValueError(
            "--spill-dtype applies to --hbm-budget spill buffers; pass "
            "--stream-train --hbm-budget (the resident assembled path "
            "never spills)")
    if args.spill_source != "buffer" and args.hbm_budget is None:
        raise ValueError(
            "--spill-source applies to --hbm-budget eviction; pass "
            "--stream-train --hbm-budget (the resident assembled path "
            "never evicts)")
    if args.spill_source == "redecode" and args.spill_dtype != "f32":
        raise ValueError(
            "--spill-dtype bf16 compresses host spill buffers, but "
            "--spill-source redecode keeps none — the combination "
            "would silently train as f32; pick one")
    if args.distmon and not args.stream_train:
        raise ValueError(
            "--distmon piggybacks distribution sketches on the "
            "--stream-train decode pass; pass --stream-train (the "
            "one-shot path has data/stats.py BasicStatisticalSummary "
            "for one-shot statistics)")

    if args.stream_train:
        if re_data or len(sequence) != 1 \
                or (sequence[0] not in fe_data
                    and sequence[0] not in fre_data):
            raise ValueError(
                "--stream-train supports exactly one fixed-effect "
                "or factored-random-effect coordinate (plain random "
                "effects need entity grouping over the full dataset); "
                f"got sequence {sequence}")
        if sequence[0] in fre_data and _mesh_shape(args) is not None:
            raise ValueError(
                "--mesh-devices/--mesh-shape are not supported for "
                "streamed MF coordinates yet (the factor-table device "
                "fold is the noted follow-on); drop the flag")
        with maybe_trace(args.profile_output_dir):
            if sequence[0] in fre_data:
                (results, best_configs, best_result, shard_maps,
                 num_rows, stream_info, distmon_out) = _stream_train_mf(
                    args, logger, task, fre_data, fre_opt, sequence,
                    train_inputs, evaluators, preloaded_maps, emitter,
                    obs)
            else:
                (results, best_configs, best_result, shard_maps,
                 num_rows, stream_info, distmon_out) = _stream_train(
                    args, logger, task, fe_data, fe_opt, sequence,
                    train_inputs, evaluators, preloaded_maps, opt_grid,
                    emitter, obs)
        return (sequence, results, best_configs, best_result, shard_maps,
                num_rows, stream_info, distmon_out)

    logger.info("reading training data from %s (ingest workers: %s)",
                train_inputs, args.ingest_workers)
    with span("ingest"):
        data, shard_maps = read_game_dataset(
            train_inputs, id_types=id_types,
            feature_shard_maps=preloaded_maps,
            ingest_workers=args.ingest_workers)
        validation = None
        if args.validate_input_dirs:
            validate_inputs = resolve_input_dirs(
                args.validate_input_dirs,
                date_range=args.validate_date_range,
                date_range_days_ago=args.validate_date_range_days_ago)
            validation, _ = read_game_dataset(
                validate_inputs, id_types=id_types,
                feature_shard_maps=shard_maps,
                ingest_workers=args.ingest_workers)

    specs = []
    for name in sequence:
        if name in fe_data:
            shard = fe_data[name]
            if shard not in shard_maps:
                raise ValueError(
                    f"fixed-effect coordinate {name!r} references unknown "
                    f"feature shard {shard!r} (have {sorted(shard_maps)})")
            specs.append(FixedEffectSpec(
                name=name, feature_shard_id=shard,
                configs=opt_grid(
                    fe_opt, name,
                    "--fixed-effect-optimization-configurations")))
        elif name in fre_data:
            cfg = fre_data[name]
            if cfg.feature_shard_id not in shard_maps:
                raise ValueError(
                    f"factored-random-effect coordinate {name!r} references "
                    f"unknown feature shard {cfg.feature_shard_id!r}")
            if name not in fre_opt:
                raise ValueError(
                    f"coordinate {name!r} has no optimization configuration "
                    "— pass it via "
                    "--factored-random-effect-optimization-configurations")
            specs.append(FactoredRandomEffectSpec(
                name=name, data_config=cfg,
                configs=[FactoredRandomEffectOptimizationConfiguration
                         .parse(part)
                         for part in fre_opt[name].split("|")]))
        else:
            cfg = re_data[name]
            if cfg.feature_shard_id not in shard_maps:
                raise ValueError(
                    f"random-effect coordinate {name!r} references unknown "
                    f"feature shard {cfg.feature_shard_id!r}")
            imap = shard_maps[cfg.feature_shard_id]
            specs.append(RandomEffectSpec(
                name=name, data_config=cfg,
                configs=opt_grid(
                    re_opt, name,
                    "--random-effect-optimization-configurations"),
                intercept_col=(imap.intercept_index
                               if imap.intercept_index >= 0 else None)))

    estimator = GameEstimator(
        task_type=task, coordinate_specs=specs,
        num_iterations=args.num_iterations,
        validation_evaluators=evaluators)
    with maybe_trace(args.profile_output_dir), span("solve"):
        results = estimator.fit(
            data, validation_data=validation,
            checkpoint_dir=(Path(args.checkpoint_dir)
                            if args.checkpoint_dir else None),
            checkpoint_interval=args.checkpoint_interval)
    best_configs, best_result = estimator.select_best(results)
    return (sequence, results, best_configs, best_result, shard_maps,
            int(data.num_rows), None, None)


def _save_outputs(args, out_dir, logger, sequence, results,
                  best_configs, best_result, shard_maps,
                  extra_metadata=None) -> None:
    """Model + index-map save (the ``finalize`` phase) — shared by the
    one-shot and --stream-train paths (identical artifacts either
    way). ``extra_metadata`` merges extra model-metadata.json keys in
    (the --distmon ``referenceDistributions`` snapshot)."""
    from photon_ml_tpu.models.tracking import summarize_trackers

    # Aggregate per-entity optimizer telemetry (convergence-reason counts,
    # iteration/objective stats per coordinate per update) — the
    # operational summary the reference computes via RDD.stats() in
    # ml/optimization/game/*Tracker.scala.
    tracker_summary = summarize_trackers(best_result.trackers)
    for name, per_update in tracker_summary.items():
        if per_update:
            last = per_update[-1]
            logger.info(
                "coordinate %s (last update): %d solves, reasons %s, "
                "iterations mean %.1f max %d", name, last["numSolves"],
                last["convergenceReasons"], last["iterations"]["mean"],
                int(last["iterations"]["max"]))

    with span("finalize"):
        save_game_model(
            out_dir / "best", best_result.best_model, shard_maps,
            metadata_extras={
                "optimizationConfigurations": {
                    k: v.to_json() for k, v in best_configs.items()},
                "updatingSequence": sequence,
                "numIterations": args.num_iterations,
                "optimizationTrackers": tracker_summary,
                **(extra_metadata or {}),
            })
        # Persist the feature index maps next to the model so the scoring
        # driver can decode features identically (the reference ships
        # PalDB stores).
        index_dir = out_dir / "best" / "feature-indexes"
        index_dir.mkdir(parents=True, exist_ok=True)
        for shard, imap in shard_maps.items():
            imap.save(index_dir / f"{shard}.json")
        if args.save_all_models == "true":
            for i, (configs, result) in enumerate(results):
                save_game_model(
                    out_dir / "all" / str(i), result.model, shard_maps,
                    metadata_extras={
                        "optimizationConfigurations": {
                            k: v.to_json() for k, v in configs.items()}})


def _write_summary(args, out_dir, logger, task, sequence, t0, results,
                   best_configs, best_result, num_rows,
                   stream_info, obs, data_quality=None) -> dict:
    """metrics.json + trace export — runs AFTER the root ``driver`` span
    closed, so the telemetry block it snapshots includes the root's
    self time (the otherwise-unattributed driver glue)."""
    wall = time.perf_counter() - t0
    summary = {
        "taskType": task.value,
        "numRows": num_rows,
        "num_rows": num_rows,
        "updatingSequence": sequence,
        "numCombos": len(results),
        "bestConfigs": {k: v.to_string() for k, v in best_configs.items()},
        "objectiveHistory": best_result.objective_history,
        "validationHistory": best_result.validation_history,
        "coordinateSeconds": best_result.timings,
        "totalSeconds": wall,
        "total_seconds": wall,
    }
    if stream_info is not None:
        # ``stream_train`` is the canonical snake_case schema; the
        # deprecated camelCase ``streamTrain`` alias rode one release
        # behind and is now removed (docs/OBSERVABILITY.md §Schema).
        summary["stream_train"] = stream_info
    if data_quality is not None:
        # --distmon: sketch summaries, per-λ convergence tails and the
        # canonical state hash (the residency-independence witness) —
        # docs/OBSERVABILITY.md §Distributions & drift.
        summary["data_quality"] = data_quality
    obs.finish(summary)
    summary["telemetry"] = telemetry.attribution_summary(wall)
    if args.trace_out:
        telemetry.export_chrome_trace(args.trace_out)
        logger.info("pipeline trace written to %s (load in Perfetto)",
                    args.trace_out)
    (out_dir / "metrics.json").write_text(json.dumps(summary, indent=2))
    logger.info("GAME training done in %.1fs", summary["totalSeconds"])
    return summary


def _stream_validate_many(game_models, args, shard_maps, evaluators,
                          logger):
    """Bounded-memory validation of ALL grid models in ONE decode pass:
    the validation container streams once (`BlockGameStream`,
    `--batch-rows` batches) and every model's serving engine scores each
    decoded batch, accumulating ONLY the evaluation columns
    (`StreamedEvalAccumulator` — shared with the scoring driver's
    --stream path) — never features. A G-point grid therefore costs one
    decode + G scores per batch, not G full decode passes. An empty
    validation input yields empty metric dicts."""
    from photon_ml_tpu.data.block_stream import BlockGameStream
    from photon_ml_tpu.evaluation.validation import StreamedEvalAccumulator
    from photon_ml_tpu.serving import StreamingGameScorer

    validate_inputs = resolve_input_dirs(
        args.validate_input_dirs,
        date_range=args.validate_date_range,
        date_range_days_ago=args.validate_date_range_days_ago)
    id_types = sorted({ev.id_type for ev in evaluators
                       if getattr(ev, "id_type", None)})
    engines = [StreamingGameScorer(m) for m in game_models]
    accs = [StreamedEvalAccumulator(id_types) for _ in game_models]
    stream = BlockGameStream(
        validate_inputs, id_types=id_types, feature_shard_maps=shard_maps,
        batch_rows=args.batch_rows, feeder=args.feeder,
        prefetch_depth=max(0, args.prefetch_batches))
    for ds in stream:
        for engine, acc in zip(engines, accs):
            acc.add(ds, engine.score(ds))
    metrics = [acc.metrics(evaluators) for acc in accs]
    logger.info("streamed validation (%d rows, %s feeder, %d models): %s",
                stream.rows, stream.decode_path, len(engines), metrics)
    return metrics


def _solve_grid_batched(args, logger, name, shard, task, grid, cache,
                        mesh, monitor, lam_label):
    """--grid-batched sweep: ONE StreamingFixedEffectCoordinate hosts
    the whole λ-grid and :func:`solve_fixed_effect_grid` advances all
    G points per feature pass over the shard cache ([G, d]
    coefficients, vmapped per-bucket kernels). Observability stays
    per-λ: each grid point keeps its own trace context (annotated with
    its grid row), --distmon convergence ring, and training-score
    sketch sliced from the batched [G, rows] margins. Returns the same
    (configs, CoordinateDescentResult) pairs the sequential sweep
    builds, plus the shared sharded objective for stream_info."""
    import time as _time

    from photon_ml_tpu.algorithm.coordinate_descent import (
        CoordinateDescentResult,
    )
    from photon_ml_tpu.algorithm.coordinates import (
        StreamingFixedEffectCoordinate,
        solve_fixed_effect_grid,
    )
    from photon_ml_tpu.models.game_model import GameModel

    G = len(grid)
    logger.info("λ-grid sweep batched: %d points advance per feature "
                "pass (--grid-batched %s)", G, args.grid_batched)
    coord = StreamingFixedEffectCoordinate(
        name=name, cache=cache, feature_shard_id=shard, task_type=task,
        config=grid[0], mesh=mesh)
    t0 = _time.perf_counter()
    rings, margins_holder = None, []
    if monitor is not None:
        from photon_ml_tpu.optimization.convergence import ConvergenceRing

        rings = []
        for cfg in grid:
            ring = ConvergenceRing()
            monitor.add_ring(lam_label(cfg), ring)
            rings.append(ring)
    # One trace context per λ-grid point, exactly as the sequential
    # sweep mints them — a row's divergence fault carries ITS trace_id
    # (plus grid row + λ) into the flight dump, not the sweep's.
    ctxs = []
    for gi, cfg in enumerate(grid):
        ctx = telemetry.mint("solve")
        ctx.annotate(coordinate=name,
                     reg_weight=cfg.regularization_weight,
                     optimizer=str(cfg.optimizer_type),
                     grid_row=gi, grid_width=G)
        ctxs.append(ctx)
    models = None
    trackers_per = [[] for _ in grid]
    obj_hist_per = [[] for _ in grid]
    for _ in range(args.num_iterations):
        pairs = solve_fixed_effect_grid(
            coord, grid, models=models, trace_ctxs=ctxs,
            convergence_rings=rings, margins_out=margins_holder)
        models = [m for m, _ in pairs]
        for gi, (_, res) in enumerate(pairs):
            trackers_per[gi].append(res)
            obj_hist_per[gi].append(float(res.value))
    shared = coord.sharded_objective
    if monitor is not None and margins_holder:
        for gi, cfg in enumerate(grid):
            monitor.observe_scores(
                lam_label(cfg),
                shared.host_scores_from_margins(
                    shared.grid_row_margins(margins_holder, gi)))
    elapsed = _time.perf_counter() - t0
    results = []
    for gi, cfg in enumerate(grid):
        ctxs[gi].annotate(
            iterations=int(trackers_per[gi][-1].iterations),
            reason=trackers_per[gi][-1].reason_enum().summary)
        ctxs[gi].finish("ok")
        gm = GameModel({name: models[gi]}, task)
        # The sweep IS one solve: every grid point reports the shared
        # wall time (the whole point — G points for one sweep's clock).
        results.append(({name: cfg}, CoordinateDescentResult(
            model=gm, objective_history=obj_hist_per[gi],
            validation_history=[], best_model=gm, best_metric=None,
            trackers={name: trackers_per[gi]},
            timings={name: elapsed})))
    return results, shared


def _stream_train(args, logger, task, fe_data, fe_opt, sequence,
                  train_inputs, evaluators, preloaded_maps, opt_grid,
                  emitter, obs):
    """Out-of-core training path (--stream-train): block-streamed ingest
    (host memory O(batch_rows)) into either

    - the EXACT assembled device batch + the untouched fused solvers
      (no --hbm-budget; model bytes identical to the one-shot driver), or
    - a DeviceShardCache + sharded streaming accumulate solve
      (--hbm-budget; replay-aware feature-block spill, deterministic
      partials — resident and eviction-forced runs write identical
      bytes), optionally folded over a --mesh-devices 1-D device mesh
      (round-robin shard placement, per-device accumulate, fixed-order
      combine — every mesh size writes the same model bytes; the HBM
      budget binds per device).

    Validation (when requested) streams through the serving engine in
    both modes."""
    import time as _time

    from photon_ml_tpu.algorithm.coordinate_descent import (
        CoordinateDescentResult,
    )
    from photon_ml_tpu.algorithm.coordinates import (
        StreamingFixedEffectCoordinate,
        grid_batchable,
        solve_fixed_effect_grid,
    )
    from photon_ml_tpu.data.avro_reader import build_index_map
    from photon_ml_tpu.data.block_stream import BlockGameStream
    from photon_ml_tpu.data.shard_cache import (
        DeviceShardCache,
        assemble_fixed_effect_batch,
    )
    from photon_ml_tpu.models.game_model import GameModel

    name = sequence[0]
    shard = fe_data[name]
    grid = opt_grid(fe_opt, name,
                    "--fixed-effect-optimization-configurations")
    if preloaded_maps is not None:
        if shard not in preloaded_maps:
            raise ValueError(
                f"fixed-effect coordinate {name!r} references unknown "
                f"feature shard {shard!r} "
                f"(have {sorted(preloaded_maps)})")
        shard_maps = {shard: preloaded_maps[shard]}
    else:
        logger.info("building feature index for shard %r from %s",
                    shard, train_inputs)
        with span("build_index"):
            shard_maps = {shard: build_index_map(
                train_inputs, ingest_workers=args.ingest_workers)}

    monitor = None
    if args.distmon:
        from photon_ml_tpu.data.distmon import (
            MonitoredStream,
            StreamingDistributionMonitor,
        )

        # Distribution sketches ride the decode pass: every batch the
        # stream yields is observed on its way to the cache/assembler
        # (on the prefetch thread when the feeder prefetches), so the
        # statistics cost zero extra feature passes and their state is
        # fixed by shard order — residency/feeder/prefetch-independent
        # like the model bytes.
        monitor = StreamingDistributionMonitor(feature_shards=[shard])
        obs.add_dist_provider("training", monitor.snapshot)
        obs.add_scrape_hook("distmon", monitor.publish_gauges)
        obs.add_sketch_provider("training", monitor.sketch_states)

    def make_stream():
        s = BlockGameStream(
            train_inputs, id_types=[], feature_shard_maps=shard_maps,
            batch_rows=args.batch_rows, feeder=args.feeder,
            prefetch_depth=max(0, args.prefetch_batches))
        return s if monitor is None else MonitoredStream(s, monitor)

    def lam_label(cfg):
        return f"{name}:l2={cfg.regularization_weight:g}"

    budget = args.hbm_budget  # parsed to bytes by argparse
    if args.checkpoint_dir and budget is not None:
        logger.warning("--checkpoint-dir is not supported with "
                       "--hbm-budget streaming solves; ignoring")

    if budget is None:
        # -- resident: exact assembly + the one-shot estimator ------------
        logger.info("stream-train (resident): assembling %r from %s in "
                    "%d-row batches", shard, train_inputs, args.batch_rows)
        with span("ingest"):
            data = assemble_fixed_effect_batch(make_stream(), shard)
        estimator = GameEstimator(
            task_type=task,
            coordinate_specs=[FixedEffectSpec(
                name=name, feature_shard_id=shard, configs=grid)],
            num_iterations=args.num_iterations,
            validation_evaluators=evaluators)
        # One trace context per λ-grid point, like the spill path
        # below: the resident fit delegates the whole sweep to the
        # estimator, so every grid point's trace spans the shared fit
        # (the batched-sweep convention — G points, one clock). Without
        # these the resident path's /tracez tail is empty for the whole
        # run, which breaks the fleet aggregator's per-process trace
        # attribution.
        ctxs = [telemetry.mint("solve") for _ in grid]
        for ctx, cfg in zip(ctxs, grid):
            ctx.annotate(coordinate=name, mode="resident",
                         reg_weight=cfg.regularization_weight,
                         optimizer=str(cfg.optimizer_type),
                         grid_points=len(grid))
        with span("solve"):
            results = estimator.fit(
                data, validation_data=None,
                checkpoint_dir=(Path(args.checkpoint_dir)
                                if args.checkpoint_dir else None),
                checkpoint_interval=args.checkpoint_interval)
        for ctx in ctxs:
            ctx.finish("ok")
        num_rows = data.num_rows
        stream_info = {
            "mode": "resident-assembled",
            "batch_rows": args.batch_rows,
            "hbm_budget_bytes": None,
            "mesh_devices": args.mesh_devices,
            "mesh_shape": _mesh_shape(args),
            "spill_dtype": None,  # nothing spills on the resident path
            "spill_source": None,
            "feeder": {k: v for k, v in data.ingest_stats.items()},
            "cache": None,
            # The fused one-shot solvers already share the assembled
            # device batch across the grid; batching is a spill-path
            # concept.
            "grid_batched": False,
            "grid_points": len(grid),
        }
    else:
        # -- spill: sharded streaming accumulate over the device cache ----
        mesh = None
        devices = None
        mesh_rc = _mesh_shape(args)
        col_blocks = 1
        if mesh_rc is not None and mesh_rc[0] * mesh_rc[1] > 1:
            from photon_ml_tpu.parallel import (
                make_mesh_2d, mesh_fold_devices,
            )

            mesh = make_mesh_2d(mesh_rc[0], mesh_rc[1])
            devices = mesh_fold_devices(mesh)
            col_blocks = mesh_rc[1]
        logger.info("stream-train (spill, hbm budget %d bytes%s, "
                    "spill %s/%s): caching %r from %s in %d-row shards",
                    budget,
                    (f" PER DEVICE x {len(devices)} mesh devices "
                     f"({mesh_rc[0]} data x {mesh_rc[1]} model)"
                     if devices else ""), args.spill_dtype,
                    args.spill_source, shard, train_inputs,
                    args.batch_rows)
        fetcher = None
        if args.spill_source == "redecode":
            from photon_ml_tpu.data.block_stream import BlockRandomAccess

            # The out-of-core miss path: evicted blocks re-decode their
            # covering container blocks by global row range instead of
            # re-uploading host spill buffers.
            fetcher = BlockRandomAccess(
                train_inputs, id_types=[], feature_shard_maps=shard_maps,
                feeder=args.feeder)
        with span("ingest"):
            cache = DeviceShardCache.from_stream(
                make_stream(), shard, hbm_budget_bytes=budget,
                prefetch_depth=max(0, args.prefetch_batches),
                devices=devices, spill_dtype=args.spill_dtype,
                spill_source=args.spill_source, redecode_fetch=fetcher,
                col_blocks=col_blocks)
        # Live residency view: a multi-hour spill train's /statusz
        # shows hits/misses/evictions/spill bytes as they happen —
        # mirroring what --serve registers for frontend stats.
        obs.add_status_provider("shard_cache", cache.stats)
        results = []
        shared = None
        batchable, why_not = grid_batchable(grid)
        if args.grid_batched == "on" and not batchable:
            raise ValueError(
                f"--grid-batched on: λ-grid is not batchable: {why_not}")
        use_batched = batchable and (
            args.grid_batched == "on"
            or (args.grid_batched == "auto" and len(grid) > 1))
        if args.grid_batched == "auto" and len(grid) > 1 and not batchable:
            logger.info("λ-grid sweeps sequentially (%s)", why_not)
        with span("solve"):
            if use_batched:
                results, shared = _solve_grid_batched(
                    args, logger, name, shard, task, grid, cache, mesh,
                    monitor, lam_label)
            for cfg in (() if use_batched else grid):
                coord = StreamingFixedEffectCoordinate(
                    name=name, cache=cache, feature_shard_id=shard,
                    task_type=task, config=cfg, sharded_objective=shared,
                    mesh=mesh)
                shared = coord.sharded_objective
                t0 = _time.perf_counter()
                model, trackers, obj_hist = None, [], []
                # --distmon hooks: a live per-λ convergence ring (loss/
                # grad-norm/step per outer iteration, visible on /distz
                # mid-solve) and the solver's final margins, from which
                # training-score quantiles sketch without a scoring
                # pass.
                ring, margins_holder = None, None
                if monitor is not None:
                    from photon_ml_tpu.optimization.convergence import (
                        ConvergenceRing,
                    )

                    ring = ConvergenceRing()
                    monitor.add_ring(lam_label(cfg), ring)
                    margins_holder = []
                # One trace context per λ-grid point: the solve's
                # identity across its outer iterations — slow solves
                # land in the /tracez tail, and a divergence fault
                # carries this trace_id into the flight dump.
                ctx = telemetry.mint("solve")
                ctx.annotate(coordinate=name,
                             reg_weight=cfg.regularization_weight,
                             optimizer=str(cfg.optimizer_type))
                for _ in range(args.num_iterations):
                    model, res = coord.solve(
                        model, trace_ctx=ctx, convergence_ring=ring,
                        margins_out=margins_holder)
                    trackers.append(res)
                    obj_hist.append(float(res.value))
                if monitor is not None and margins_holder:
                    monitor.observe_scores(
                        lam_label(cfg),
                        shared.host_scores_from_margins(margins_holder))
                ctx.annotate(
                    iterations=int(trackers[-1].iterations),
                    reason=trackers[-1].reason_enum().summary)
                ctx.finish("ok")
                gm = GameModel({name: model}, task)
                results.append(({name: cfg}, CoordinateDescentResult(
                    model=gm, objective_history=obj_hist,
                    validation_history=[], best_model=gm,
                    best_metric=None, trackers={name: trackers},
                    timings={name: _time.perf_counter() - t0})))
        num_rows = cache.n_rows
        stream_info = {
            "mode": "spill",
            "batch_rows": args.batch_rows,
            "hbm_budget_bytes": budget,
            "mesh_devices": args.mesh_devices,
            "mesh_shape": mesh_rc,
            "spill_dtype": args.spill_dtype,
            "spill_source": args.spill_source,
            "feeder": cache.ingest_stats,
            "cache": cache.stats(),
            "grid_batched": use_batched,
            "grid_points": len(grid),
            "trace_budgets": shared.trace_budgets(),
            "trace_counts": shared.guard.counts(),
        }
        if fetcher is not None:
            stream_info["redecode"] = {
                "decode_path": fetcher.decode_path,
                "payload_bytes_read": fetcher.payload_bytes_read,
                "blocks_decoded": fetcher.blocks_decoded,
                "rows_fetched": fetcher.rows_fetched,
            }

    if args.validate_input_dirs and evaluators:
        with span("validate"):
            all_metrics = _stream_validate_many(
                [res.model for _, res in results], args, shard_maps,
                evaluators, logger)
        for (_, res), metrics in zip(results, all_metrics):
            res.validation_history.append(metrics)

    # Per-λ optimization telemetry events — the streamed analog of the
    # glm_driver's per-model PhotonOptimizationLogEvent emission (the
    # listener registration existed; the streamed path never emitted).
    for configs, res in results:
        cfg = configs[name]
        trk = list(res.trackers.get(name) or [])
        last = trk[-1] if trk else None
        emitter.send_event(PhotonOptimizationLogEvent(
            reg_weight=cfg.regularization_weight,
            iterations=(int(last.iterations) if last is not None else 0),
            converged_reason=(last.reason_enum().summary
                              if last is not None else "unknown"),
            final_value=(float(last.value) if last is not None
                         else float("nan")),
            metrics=(res.validation_history[-1]
                     if res.validation_history else None)))

    from photon_ml_tpu.estimators.game_estimator import select_best_result

    best_configs, best_result = select_best_result(results, evaluators)

    distmon_out = None
    if monitor is not None:
        import jax.numpy as jnp
        import numpy as np

        best_label = lam_label(best_configs[name])
        if budget is None:
            # Resident path: the fused in-core solvers ran — rings
            # populate post-hoc from the tracker histories, and the
            # best model's training scores come from ONE matvec over
            # the already-resident assembled batch (device work only,
            # no decode pass).
            for configs, res in results:
                # EVERY solve's history appends to the λ's ring (not
                # just the last), matching the live streamed-solver
                # rings under --num-iterations > 1.
                for trk in res.trackers.get(name) or []:
                    monitor.ring_from_history(
                        lam_label(configs[name]),
                        np.asarray(trk.value_history),
                        np.asarray(trk.grad_norm_history))
            batch = data.fixed_effect_batch(shard)
            fe_model = best_result.best_model.models[name]
            w = jnp.asarray(
                np.asarray(fe_model.glm.coefficients.means),
                np.asarray(batch.labels).dtype)
            monitor.observe_scores(
                best_label, np.asarray(batch.features.matvec(w)))
        monitor.publish_gauges()
        distmon_out = {
            "data_quality": monitor.data_quality_block(),
            "reference": monitor.reference(score_label=best_label),
        }

    return (results, best_configs, best_result, shard_maps, num_rows,
            stream_info, distmon_out)


def _stream_train_mf(args, logger, task, fre_data, fre_opt, sequence,
                     train_inputs, evaluators, preloaded_maps, emitter,
                     obs):
    """Out-of-core MATRIX FACTORIZATION training (--stream-train with a
    factored-random-effect coordinate): observations stream through
    `BlockGameStream` (re-decoded per feature pass, host O(one block));
    factor tables live in a budgeted `DeviceFactorCache` (ALX-style
    pow-2 observation-count bucketing, replay-aware eviction, the PR-10
    f32/bf16/redecode spill tiers) so factor tables larger than
    ``--hbm-budget`` train to completion; alternating sweeps run the
    streamed ridge gamma pass + streamed L-BFGS projection refit
    (algorithm/coordinates.py StreamingFactoredRandomEffectCoordinate).
    λ-grid points with the same num_factors share one compiled
    objective, so the grid sweep never recompiles. The factor cache's
    residency stats register as a live /statusz provider."""
    import time as _time

    from photon_ml_tpu.algorithm.coordinate_descent import (
        CoordinateDescentResult,
    )
    from photon_ml_tpu.algorithm.coordinates import (
        StreamingFactoredRandomEffectCoordinate,
    )
    from photon_ml_tpu.data.avro_reader import build_index_map
    from photon_ml_tpu.data.block_stream import BlockGameStream
    from photon_ml_tpu.models.game_model import GameModel

    name = sequence[0]
    data_cfg = fre_data[name]
    shard = data_cfg.feature_shard_id
    re_type = data_cfg.random_effect_type
    if name not in fre_opt:
        raise ValueError(
            f"coordinate {name!r} has no optimization configuration — "
            "pass it via "
            "--factored-random-effect-optimization-configurations")
    grid = [FactoredRandomEffectOptimizationConfiguration.parse(part)
            for part in fre_opt[name].split("|")]

    if preloaded_maps is not None:
        if shard not in preloaded_maps:
            raise ValueError(
                f"factored coordinate {name!r} references unknown "
                f"feature shard {shard!r} "
                f"(have {sorted(preloaded_maps)})")
        shard_maps = {shard: preloaded_maps[shard]}
    else:
        logger.info("building feature index for shard %r from %s",
                    shard, train_inputs)
        with span("build_index"):
            shard_maps = {shard: build_index_map(
                train_inputs, ingest_workers=args.ingest_workers)}

    stream_holder = {}
    monitor = None
    if args.distmon:
        from photon_ml_tpu.data.distmon import (
            MonitoredStream,
            StreamingDistributionMonitor,
        )

        # MF re-decodes observations once per feature pass; the monitor
        # observes exactly ONE full pass (max_passes=1 on the first
        # stream) so every row counts once — the later passes replay
        # identical bytes (the PR 12 determinism contract), so one pass
        # IS the distribution.
        monitor = StreamingDistributionMonitor(
            feature_shards=[shard], id_types=[re_type])
        obs.add_dist_provider("training", monitor.snapshot)
        obs.add_scrape_hook("distmon", monitor.publish_gauges)
        obs.add_sketch_provider("training", monitor.sketch_states)

    def make_stream():
        s = BlockGameStream(
            train_inputs, id_types=[re_type],
            feature_shard_maps=shard_maps,
            batch_rows=args.batch_rows, feeder=args.feeder,
            prefetch_depth=max(0, args.prefetch_batches))
        stream_holder["last"] = s
        if monitor is not None and not stream_holder.get("observed"):
            stream_holder["observed"] = True
            return MonitoredStream(s, monitor, max_passes=1)
        return s

    budget = args.hbm_budget
    if args.checkpoint_dir:
        logger.warning("--checkpoint-dir is not supported with "
                       "--stream-train MF coordinates; ignoring")
    fetcher = None
    if budget is not None and args.spill_source == "redecode":
        from photon_ml_tpu.data.block_stream import BlockRandomAccess

        # Factor-shard misses re-derive from observations: the hook
        # re-decodes ONLY the covering container batches by global row
        # range (the PR-10 out-of-core miss path, re-pointed at the
        # factor tables' normal equations).
        fetcher = BlockRandomAccess(
            train_inputs, id_types=[re_type],
            feature_shard_maps=shard_maps, feeder=args.feeder)
    logger.info(
        "stream-train (mf%s): %r over %r entities from %s in %d-row "
        "batches", "" if budget is None else
        f", hbm budget {budget} bytes, spill {args.spill_dtype}/"
        f"{args.spill_source}", name, re_type, train_inputs,
        args.batch_rows)

    shared = {}  # num_factors -> StreamedMFObjective (kernel sharing)
    results = []

    def _factor_cache_status():
        # Live residency view, mirroring the shard-cache provider of
        # the fixed-effect spill path. Reads THROUGH the shared-
        # objective table so a grid spanning several num_factors values
        # (several caches) stays fully observable — single-k grids keep
        # the flat shard-cache-style schema.
        if len(shared) == 1:
            return next(iter(shared.values())).cache.stats()
        return {f"num_factors_{k}": o.cache.stats()
                for k, o in sorted(shared.items())}

    with span("solve"):
        for cfg in grid:
            coord = StreamingFactoredRandomEffectCoordinate(
                name=name, make_stream=make_stream,
                feature_shard_id=shard, random_effect_type=re_type,
                task_type=task, config=cfg.random_effect,
                latent_config=cfg.latent_factor, mf_config=cfg.mf,
                # seed 0 = GameEstimator.fit's default, so the streamed
                # B0 matches what the in-core driver path initializes
                # (parity tests compare the two end to end).
                seed=0,
                hbm_budget_bytes=budget,
                spill_dtype=(args.spill_dtype if budget is not None
                             else "f32"),
                spill_source=(args.spill_source if budget is not None
                              else "buffer"),
                mf_objective=shared.get(cfg.mf.num_factors),
                random_access=fetcher)
            if not shared:
                obs.add_status_provider("factor_cache",
                                        _factor_cache_status)
            shared[cfg.mf.num_factors] = coord.mf_objective
            t0 = _time.perf_counter()
            model, trackers, obj_hist = None, [], []
            ctx = telemetry.mint("solve")
            ctx.annotate(coordinate=name,
                         reg_weight=cfg.random_effect.regularization_weight,
                         num_factors=cfg.mf.num_factors,
                         mf_sweeps=cfg.mf.max_iterations)
            for _ in range(args.num_iterations):
                model, sweep_trackers = coord.solve(model, trace_ctx=ctx)
                trackers.extend(sweep_trackers)
                obj_hist.append(float(sweep_trackers[-1].value))
            ctx.annotate(
                iterations=int(trackers[-1].iterations),
                reason=trackers[-1].reason_enum().summary)
            ctx.finish("ok")
            gm = GameModel({name: model}, task)
            results.append(({name: cfg}, CoordinateDescentResult(
                model=gm, objective_history=obj_hist,
                validation_history=[], best_model=gm,
                best_metric=None, trackers={name: trackers},
                timings={name: _time.perf_counter() - t0})))

    first_obj = next(iter(shared.values()))
    num_rows = first_obj.n_rows
    stream_info = {
        "mode": "mf-stream",
        "batch_rows": args.batch_rows,
        "hbm_budget_bytes": budget,
        "mesh_devices": None,  # factor-table device fold: follow-on
        "mesh_shape": None,
        "spill_dtype": args.spill_dtype if budget is not None else None,
        "spill_source": (args.spill_source if budget is not None
                         else None),
        "feeder": (stream_holder["last"].stats()
                   if "last" in stream_holder else None),
        "cache": first_obj.cache.stats(),
        "plan": {
            "entities": first_obj.plan.num_entities,
            "shards": first_obj.plan.n_shards,
            "obs_bucket_histogram": {
                str(k): v for k, v in sorted(
                    first_obj.plan.obs_bucket_histogram().items())},
        },
        "trace_budgets": first_obj.trace_budgets(),
        "trace_counts": first_obj.guard.counts(),
    }
    if len(shared) > 1:
        # A grid spanning several num_factors values trains several
        # factor caches; the flat "cache" block above covers the first
        # — report the rest too so none is invisible post-run.
        stream_info["cache_by_num_factors"] = {
            str(k): o.cache.stats() for k, o in sorted(shared.items())}
    if fetcher is not None:
        stream_info["redecode"] = {
            "decode_path": fetcher.decode_path,
            "payload_bytes_read": fetcher.payload_bytes_read,
            "blocks_decoded": fetcher.blocks_decoded,
            "rows_fetched": fetcher.rows_fetched,
        }

    if args.validate_input_dirs and evaluators:
        with span("validate"):
            all_metrics = _stream_validate_many(
                [res.model for _, res in results], args, shard_maps,
                evaluators, logger)
        for (_, res), metrics in zip(results, all_metrics):
            res.validation_history.append(metrics)

    for configs, res in results:
        cfg = configs[name]
        trk = list(res.trackers.get(name) or [])
        last = trk[-1] if trk else None
        emitter.send_event(PhotonOptimizationLogEvent(
            reg_weight=cfg.random_effect.regularization_weight,
            iterations=(int(last.iterations) if last is not None else 0),
            converged_reason=(last.reason_enum().summary
                              if last is not None else "unknown"),
            final_value=(float(last.value) if last is not None
                         else float("nan")),
            metrics=(res.validation_history[-1]
                     if res.validation_history else None)))

    from photon_ml_tpu.estimators.game_estimator import select_best_result

    best_configs, best_result = select_best_result(results, evaluators)

    distmon_out = None
    if monitor is not None:
        monitor.publish_gauges()
        # MF reference carries label quantiles only (no cheap training-
        # score surface exists — scores need a full gather+dot pass);
        # serving drift degrades gracefully without a "score" block.
        distmon_out = {
            "data_quality": monitor.data_quality_block(),
            "reference": monitor.reference(),
        }

    return (results, best_configs, best_result, shard_maps, num_rows,
            stream_info, distmon_out)


def main() -> None:
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
