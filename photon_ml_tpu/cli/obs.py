"""Driver-side wiring of the live observability plane (shared by the
GAME training and scoring drivers — one implementation of the
``--obs-port`` / ``--flight-events`` / ``--slo`` contract).

The telemetry plane itself lives in ``photon_ml_tpu/telemetry/``
(exposition/recorder/slo modules); libraries never start a server or
install a recorder — those are process-lifecycle decisions, and the CLI
drivers own the process. This module is that ownership, factored out so
both drivers behave identically:

- ``--obs-port P`` starts an :class:`ObservabilityServer` on
  ``127.0.0.1:P`` (0 = ephemeral) for the duration of the run, serving
  ``/metrics`` (Prometheus text), ``/healthz``, ``/statusz`` and
  ``/debugz/dump``. The bound port is written to ``<output-dir>/obs_port``
  as soon as the server is up (so a harness launching the driver can
  scrape a live run without parsing logs) and reported in metrics.json
  under ``observability.port``.
- ``--flight-events N`` (default 4096; 0 disables) installs a
  :class:`FlightRecorder`: the last N completed spans + periodic registry
  deltas, dumped to ``<output-dir>/flight.json`` on an unhandled driver
  fault, on SIGTERM, and on demand via ``/debugz/dump``. The recorder is
  ON by default — it exists precisely for the fault nobody armed
  ``--trace-out`` for, and its per-span cost is one short-lock append on
  stage-granularity events.
- ``--slo SPEC`` (repeatable) declares objectives over existing registry
  metrics (telemetry/slo.py syntax); the tracker's burn-rate counters
  ride in ``/metrics``, its evaluation in ``/statusz`` and the
  metrics.json ``slo`` block.
"""

from __future__ import annotations

from pathlib import Path
from typing import Callable, Dict, Optional

from photon_ml_tpu.telemetry import (
    FlightRecorder,
    ObservabilityServer,
    SLOTracker,
    install_sigterm_dump,
    trace_tail,
    write_obs_descriptor,
)


def add_observability_args(p) -> None:
    """Attach the shared observability flags to a driver parser."""
    p.add_argument("--obs-port", type=int, default=None, metavar="PORT",
                   help="serve the live observability plane on "
                        "127.0.0.1:PORT for the duration of the run: "
                        "/metrics (Prometheus text; exemplars on "
                        "OpenMetrics-negotiated scrapes), /healthz, "
                        "/statusz (registry + stage attribution + "
                        "per-model serving stats + profiler table + "
                        "SLO), /tracez (tail-sampled request/solve "
                        "timelines), /distz (live label/feature/score "
                        "distributions + drift, with --distmon), "
                        "/debugz/dump (flight recorder). "
                        "0 binds an ephemeral port, written to "
                        "<output-dir>/obs_port and reported in "
                        "metrics.json (docs/OBSERVABILITY.md)")
    p.add_argument("--flight-events", type=int, default=4096, metavar="N",
                   help="flight-recorder ring size: the last N completed "
                        "spans + periodic registry deltas, dumped to "
                        "<output-dir>/flight.json on an unhandled driver "
                        "fault, on SIGTERM, and via /debugz/dump "
                        "(Perfetto-loadable). 0 disables the recorder")
    p.add_argument("--slo", action="append", default=None, metavar="SPEC",
                   help="declare a latency/availability objective over "
                        "existing metrics, e.g. "
                        "'p99:serving.frontend.request_latency_seconds"
                        "<=50ms' or 'shed=ratio:serving.frontend.rejected"
                        "/serving.frontend.admitted+serving.frontend."
                        "rejected<=0.02'; repeatable. Burn rates surface "
                        "in /metrics, /statusz and metrics.json slo")


class DriverObservability:
    """One driver run's observability plane: recorder + SLO tracker +
    HTTP server, built from the parsed args. Lifecycle::

        obs = DriverObservability(args, out_dir).start()
        try:
            ...  # the run; obs.add_status_provider() as components come up
            obs.finish(summary)      # slo/observability metrics.json blocks
        except BaseException as e:
            obs.dump_fault(e)        # flight.json evidence, then re-raise
            raise
        finally:
            obs.stop()

    ``heartbeat_s`` (the training driver passes 1.0) keeps liveness
    gauges, registry deltas and SLO evaluation ticking between scrapes
    during long solves; the scoring/serving driver leaves it None — its
    scrape traffic drives freshness.
    """

    def __init__(self, args, out_dir: Path,
                 heartbeat_s: Optional[float] = None,
                 role: str = "process"):
        self.out_dir = Path(out_dir)
        self.role = role
        self.flight_path = self.out_dir / "flight.json"
        self.recorder: Optional[FlightRecorder] = (
            FlightRecorder(max_events=args.flight_events)
            if args.flight_events > 0 else None)
        self.slo_tracker: Optional[SLOTracker] = (
            SLOTracker(args.slo) if args.slo else None)
        self.server: Optional[ObservabilityServer] = None
        if args.obs_port is not None:
            self.server = ObservabilityServer(
                port=args.obs_port, recorder=self.recorder,
                slo_tracker=self.slo_tracker, heartbeat_s=heartbeat_s,
                dump_path=self.flight_path, role=role,
                slo_specs=args.slo or [])
        self._restore_sigterm: Optional[Callable[[], None]] = None
        self._fault_dumped = False
        # Scrape hooks registered by the driver (--distmon gauge
        # refreshers): kept locally so finish() can refresh computed
        # gauges before the final SLO evaluation even when no server is
        # running, and registered with the server (when present) so
        # live scrapes and heartbeat ticks refresh them too.
        self._scrape_hooks: Dict[str, Callable[[], None]] = {}

    def start(self) -> "DriverObservability":
        if self.recorder is not None:
            self.recorder.install()
            self._restore_sigterm = install_sigterm_dump(
                self.recorder, self.flight_path)
        if self.server is not None:
            self.server.start()
            # Announce the bound port on disk the moment it exists: a
            # harness that launched this driver can scrape the LIVE run
            # (obs_port appears before model load / compiles) instead of
            # discovering the port post-mortem in metrics.json. Since
            # the federation PR this is a JSON descriptor
            # ({port, pid, role, start_unix}) so a FleetAggregator can
            # attribute the peer without racing its /healthz; legacy
            # plain-int parsing is preserved in read_obs_descriptor.
            write_obs_descriptor(self.out_dir / "obs_port",
                                 self.server.port, role=self.role)
        return self

    def mark_ready(self, reason: str = "ready") -> None:
        """Flip the /readyz probe true (after model load / first
        successful solve — the liveness/readiness split). No-op
        without a server."""
        if self.server is not None:
            self.server.set_ready(True, reason)

    def add_sketch_provider(self, name: str,
                            fn: Callable[[], dict]) -> None:
        """Expose mergeable sketch states under /snapshotz for the
        fleet aggregator (no-op without a server)."""
        if self.server is not None:
            self.server.add_sketch_provider(name, fn)

    def add_status_provider(self, name: str,
                            fn: Callable[[], dict]) -> None:
        """Expose a component's stats() under /statusz (no-op without a
        server — the provider contract is read-only either way)."""
        if self.server is not None:
            self.server.add_status_provider(name, fn)

    def add_dist_provider(self, name: str,
                          fn: Callable[[], dict]) -> None:
        """Expose a distribution snapshot under /distz (data/distmon.py;
        no-op without a server — metrics.json carries the final
        snapshot either way)."""
        if self.server is not None:
            self.server.add_distribution_provider(name, fn)

    def add_scrape_hook(self, name: str,
                        fn: Callable[[], None]) -> None:
        """Register a computed-gauge refresher: runs before every live
        scrape / heartbeat tick (when a server is up) and once in
        :meth:`finish` before the final SLO evaluation."""
        self._scrape_hooks[name] = fn
        if self.server is not None:
            self.server.add_scrape_hook(name, fn)

    def dump_fault(self, exc: BaseException, logger=None) -> None:
        """Unhandled-fault hook: leave flight.json evidence. SystemExit
        is an intentional CLI exit (argument validation, documented
        degradations) — no evidence needed; everything else (including
        KeyboardInterrupt on a wedged run) dumps. The span context
        managers have already unwound through the failing stage by the
        time the driver's except block runs, so the ring's last events
        cover it. A fault carrying a ``trace_id`` (e.g. the divergence
        watchdog's SolverDivergedError) tags the dump with it — the
        dump's ``flight.traces`` block holds that solve's tail-kept
        timeline."""
        if (self.recorder is None or self._fault_dumped
                or isinstance(exc, SystemExit)):
            return
        try:
            self.recorder.dump(self.flight_path,
                               reason=f"fault:{type(exc).__name__}",
                               trace_id=getattr(exc, "trace_id", None))
            self._fault_dumped = True
            if logger is not None:
                logger.error("flight recorder dumped to %s (%s)",
                             self.flight_path, type(exc).__name__)
        except Exception:  # noqa: BLE001 — evidence is best-effort
            pass

    def finish(self, summary: Dict) -> Dict:
        """Attach the ``slo`` and ``observability`` metrics.json blocks
        (call before the summary is written, while the server counters
        are final-ish)."""
        for fn in self._scrape_hooks.values():
            try:
                fn()  # final refresh: the slo block judges fresh gauges
            except Exception:  # noqa: BLE001 — summary is best-effort
                pass
        if self.slo_tracker is not None:
            summary["slo"] = self.slo_tracker.evaluate()
        if self.server is not None:
            # Final-scrape handshake: if a fleet aggregator has been
            # polling /snapshotz, hold the plane up (bounded) until one
            # more full snapshot renders AFTER the refresh + SLO
            # evaluation above — so the aggregator's last poll sees the
            # settled end-of-run state (trace tail included) instead of
            # racing stop(). A run nobody scraped returns immediately.
            self.server.await_final_scrape(timeout_s=2.0)
        if self.server is not None or self.recorder is not None:
            summary["observability"] = {
                "server": (self.server.summary()
                           if self.server is not None else None),
                "flight_recorder": (self.recorder.stats()
                                    if self.recorder is not None else None),
                "flight_path": (str(self.flight_path)
                                if self.recorder is not None
                                and self.recorder.dumps > 0 else None),
                # Tail-sampler counters (full timelines live on /tracez
                # and in flight dumps — metrics.json keeps the books).
                "trace_tail": trace_tail().counters(),
            }
        return summary

    def stop(self) -> None:
        """Idempotent teardown: restore SIGTERM, stop the server,
        detach the recorder from the process tracer."""
        if self._restore_sigterm is not None:
            self._restore_sigterm()
            self._restore_sigterm = None
        if self.server is not None:
            self.server.stop()
        if self.recorder is not None:
            self.recorder.uninstall()
