"""Command-line drivers (reference: ml/Driver.scala, ml/cli/game/)."""


def _honor_jax_platforms_env() -> None:
    """Make JAX_PLATFORMS authoritative for driver processes.

    Some environments install a sitecustomize that registers extra JAX
    platforms and overrides the platform selection at import time (e.g.
    a remote-TPU plugin forcing "tpu,cpu"); the env var alone is then
    silently ignored and a CPU-intended run hangs on remote-device init.
    Re-asserting the env value through jax.config before first backend
    use restores the documented env-var contract. No-op when the var is
    unset or backends are already initialized."""
    import os

    val = os.environ.get("JAX_PLATFORMS")
    if not val:
        return
    import jax

    try:
        jax.config.update("jax_platforms", val)
    except Exception as e:  # noqa: BLE001 - never block a driver, but say so
        import logging

        logging.getLogger("photon_ml_tpu").warning(
            "could not apply JAX_PLATFORMS=%s (%s) — the run may not use "
            "the intended backend", val, e)
