"""Command-line drivers (reference: ml/Driver.scala, ml/cli/game/)."""
