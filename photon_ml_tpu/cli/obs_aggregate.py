"""``photon-obs-aggregate`` — run a fleet observability aggregator.

The ``--obs-aggregate`` mode of the live plane: discovers peer
processes (training children, serving replicas, bench subprocesses) via
explicit ``--peers`` URLs and/or ``--peer-dirs`` output directories
containing ``obs_port`` descriptors, polls their ``/snapshotz`` on an
interval, and serves the MERGED ``/metrics``, ``/statusz``, ``/tracez``,
``/distz`` and ``/snapshotz`` (telemetry/federation.py semantics:
counters sum, histogram buckets add exactly, gauges by declared policy,
sketches via their deterministic merges, SLOs re-judged fleet-wide).

A dead peer degrades the plane (marked stale, last snapshot retained,
``fleet.peer.<id>.stale`` on ``/metrics``); ``/readyz`` answers 503
until at least one peer is fresh. ``Ctrl-C`` or ``--duration`` ends the
run; a final fleet summary JSON is written to ``--output-dir``.

Examples::

    photon-obs-aggregate --peer-dirs out/replicas --port 9009
    photon-obs-aggregate --peers http://127.0.0.1:9100 \
        --peers http://127.0.0.1:9101 --interval 1 --duration 30
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from photon_ml_tpu.telemetry import write_obs_descriptor
from photon_ml_tpu.telemetry.federation import FleetAggregator


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-obs-aggregate",
        description="Fleet observability aggregator: merge the live "
                    "planes of N peer processes into one pane of glass "
                    "(docs/OBSERVABILITY.md §Federation).")
    p.add_argument("--peers", action="append", default=[],
                   metavar="URL",
                   help="peer base URL (e.g. http://127.0.0.1:9100); "
                        "repeatable")
    p.add_argument("--peer-dirs", action="append", default=[],
                   metavar="DIR",
                   help="directory scanned (itself + one level of "
                        "subdirectories) every poll for obs_port "
                        "descriptor files; repeatable — late-booting "
                        "children are picked up automatically")
    p.add_argument("--port", type=int, default=0, metavar="PORT",
                   help="serve the merged plane on 127.0.0.1:PORT "
                        "(default 0 = ephemeral, announced in "
                        "<output-dir>/obs_port)")
    p.add_argument("--interval", type=float, default=2.0, metavar="S",
                   help="seconds between snapshot polls (default 2)")
    p.add_argument("--stale-after", type=float, default=None,
                   metavar="S",
                   help="seconds without a successful scrape before a "
                        "peer is stale (default 3x --interval)")
    p.add_argument("--timeout", type=float, default=2.0, metavar="S",
                   help="per-peer scrape timeout (default 2)")
    p.add_argument("--duration", type=float, default=None, metavar="S",
                   help="exit after S seconds (default: run until "
                        "interrupted)")
    p.add_argument("--output-dir", type=Path, default=Path("obs_fleet"),
                   metavar="DIR",
                   help="where obs_port and the final fleet summary "
                        "land (default ./obs_fleet)")
    return p


def run(argv=None) -> dict:
    args = build_parser().parse_args(argv)
    if not args.peers and not args.peer_dirs:
        build_parser().error("need at least one --peers URL or "
                             "--peer-dirs directory")
    args.output_dir.mkdir(parents=True, exist_ok=True)
    agg = FleetAggregator(
        peers=args.peers, peer_dirs=args.peer_dirs,
        interval_s=args.interval, stale_after_s=args.stale_after,
        port=args.port, timeout_s=args.timeout)
    agg.start()
    write_obs_descriptor(args.output_dir / "obs_port", agg.port,
                         role="aggregator")
    print(f"fleet aggregator on http://127.0.0.1:{agg.port} "
          f"(interval {args.interval:g}s; merged /metrics /statusz "
          f"/tracez /distz /snapshotz)", file=sys.stderr)
    t_end = (time.monotonic() + args.duration
             if args.duration is not None else None)
    try:
        while t_end is None or time.monotonic() < t_end:
            time.sleep(min(args.interval,
                           1.0 if t_end is None
                           else max(0.0, t_end - time.monotonic())))
    except KeyboardInterrupt:
        pass
    finally:
        summary = agg.summary()
        agg.stop()
        out = args.output_dir / "fleet_summary.json"
        out.write_text(json.dumps(summary, indent=2) + "\n")
        print(f"fleet summary written to {out}", file=sys.stderr)
    return summary


def main() -> None:
    run()


if __name__ == "__main__":
    main()
