"""Shard planner: index Avro container files at the block level and split
them into block-aligned byte-range shards.

An Avro object container file is a header followed by independent blocks
(count varint, byte-size varint, payload, 16-byte sync marker). Blocks are
self-contained — a worker that knows the file's codec, sync marker and a
block's byte offset can decode it without touching the header — so the
natural decode unit is a CONSECUTIVE run of blocks. Scanning the block
index reads only the two varints per block (payloads are seeked over), so
planning costs O(blocks) seeks, not O(bytes).

Two consumers share the index: `data/parallel_ingest.py` groups block runs
into byte-balanced shards decoded by a process pool (whole-file ingest),
and `data/block_stream.py` walks one file's run in order, cutting decoded
rows into bounded batches (streamed scoring) — same block scan, same
failure surface, different parallelism shape.

Shards never span files and carry a global sequence number; a consumer that
assembles results in sequence order reproduces the single-process row order
exactly (the worker-count-invariance contract of
data/parallel_ingest.py).

This is the single-host analog of the reference's executor-parallel decode
(ml/data/AvroDataReader.scala:86-214), where HDFS splits play the role of
the block-range shards.
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path
from typing import Any, List, Sequence


@dataclasses.dataclass(frozen=True)
class BlockSpan:
    """One container block: ``offset`` is the file position of its count
    varint; ``payload_bytes`` the (possibly compressed) payload size;
    ``count`` the records it holds."""

    offset: int
    payload_bytes: int
    count: int


@dataclasses.dataclass(frozen=True)
class FileBlockIndex:
    """Everything a worker needs to decode any block run of one file."""

    path: str
    codec: str  # "null" | "deflate"
    sync: bytes  # 16-byte sync marker
    schema_json: Any  # writer schema (parsed JSON), for layout compilation
    blocks: List[BlockSpan]

    @property
    def num_rows(self) -> int:
        return sum(b.count for b in self.blocks)

    @property
    def num_bytes(self) -> int:
        return sum(b.payload_bytes for b in self.blocks)


@dataclasses.dataclass(frozen=True)
class IngestShard:
    """A consecutive block run of one file, assigned to one worker.

    ``seq`` is the global assembly position: results concatenated in seq
    order are byte-identical to a single-process scan of the same paths.
    """

    seq: int
    path: str
    codec: str
    sync: bytes
    offset: int  # file position of the first block's count varint
    num_blocks: int
    num_rows: int
    num_bytes: int

    def label(self) -> str:
        """Human-readable shard name for error messages."""
        return (f"{os.path.basename(self.path)}"
                f"[@{self.offset}, {self.num_blocks} blocks, "
                f"{self.num_rows} rows]")


def scan_container_blocks(path) -> FileBlockIndex:
    """Index one container file's blocks without decompressing payloads.

    Raises ValueError naming the file and offset on any structural damage
    (truncated varint/payload, sync mismatch) — the same failures a decode
    would hit, surfaced before any worker pool spins up.
    """
    import json

    from photon_ml_tpu.io.avro_codec import MAGIC, _read_long, read_datum

    path = str(path)
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path}: not an Avro container file")
        meta = read_datum(f, {"type": "map", "values": "bytes"})
        schema_json = json.loads(meta["avro.schema"].decode())
        codec = meta.get("avro.codec", b"null").decode()
        if codec not in ("null", "deflate"):
            raise ValueError(f"{path}: unsupported codec {codec!r}")
        sync = f.read(16)
        if len(sync) != 16:
            raise ValueError(f"{path}: truncated header sync marker")

        blocks: List[BlockSpan] = []
        while True:
            offset = f.tell()
            first = f.read(1)
            if not first:
                break
            f.seek(-1, 1)
            try:
                count = _read_long(f)
                size = _read_long(f)
            except EOFError as e:
                raise ValueError(
                    f"{path}: truncated block header at offset {offset}: "
                    f"{e}") from e
            if count < 0 or size < 0:
                raise ValueError(
                    f"{path}: negative block header at offset {offset} "
                    f"(count={count}, size={size})")
            f.seek(size, 1)
            marker = f.read(16)
            if len(marker) != 16:
                raise ValueError(
                    f"{path}: truncated block payload/sync at offset "
                    f"{offset} (expected {size} payload bytes + sync)")
            if marker != sync:
                raise ValueError(
                    f"{path}: sync marker mismatch after block at offset "
                    f"{offset}")
            blocks.append(BlockSpan(offset, size, count))
    return FileBlockIndex(path=path, codec=codec, sync=sync,
                          schema_json=schema_json, blocks=blocks)


def read_block(f, codec: str, sync: bytes, path: str,
               expected=None):
    """Read ONE container block at the current file position: returns
    (record_count, decompressed_payload), consuming the trailing sync
    marker and verifying it.

    The single copy of the block-read idiom both decode consumers use
    (parallel_ingest worker loop, block_stream streaming loop).
    ``expected``: optional (count, payload_bytes, offset) from a prior
    scan — a mismatch means the file changed under the reader. All
    failures raise ValueError naming the file (and offset when known).
    """
    import zlib

    from photon_ml_tpu.io.avro_codec import _read_long

    count = _read_long(f)
    size = _read_long(f)
    where = ""
    if expected is not None:
        e_count, e_size, offset = expected
        where = f" at offset {offset}"
        if (count, size) != (e_count, e_size):
            raise ValueError(
                f"{path}: block header{where} changed under the reader "
                f"(scanned {e_count} rows/{e_size} bytes, read "
                f"{count}/{size})")
    payload = f.read(size)
    if len(payload) != size:
        raise ValueError(
            f"{path}: truncated block payload{where} (wanted {size} "
            f"bytes, got {len(payload)})")
    if f.read(16) != sync:
        raise ValueError(
            f"{path}: sync marker mismatch after block{where}")
    if codec == "deflate":
        try:
            payload = zlib.decompress(payload, -15)
        except zlib.error as e:
            raise ValueError(
                f"{path}: corrupt deflate payload in block{where}: "
                f"{e}") from e
    return count, payload


def plan_shards(indexes: Sequence[FileBlockIndex],
                num_shards: int) -> List[IngestShard]:
    """Group consecutive blocks into ~``num_shards`` byte-balanced shards.

    File order and within-file block order are preserved (seq numbers are
    assigned in scan order). Shards never cross file boundaries, so every
    shard has exactly one schema/codec/sync. Files smaller than the byte
    target still get their own shard; the result may therefore hold up to
    ``num_shards + len(indexes)`` entries and never fewer than
    ``len(indexes)`` (for non-empty files).
    """
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    total_bytes = sum(ix.num_bytes for ix in indexes)
    target = max(1, -(-total_bytes // num_shards))  # ceil

    shards: List[IngestShard] = []
    seq = 0
    for ix in indexes:
        run: List[BlockSpan] = []
        run_bytes = 0

        def flush():
            nonlocal run, run_bytes, seq
            if not run:
                return
            shards.append(IngestShard(
                seq=seq, path=ix.path, codec=ix.codec, sync=ix.sync,
                offset=run[0].offset, num_blocks=len(run),
                num_rows=sum(b.count for b in run), num_bytes=run_bytes))
            seq += 1
            run, run_bytes = [], 0

        for b in ix.blocks:
            run.append(b)
            run_bytes += b.payload_bytes
            if run_bytes >= target:
                flush()
        flush()
    return shards


def scan_paths(paths: Sequence) -> List[FileBlockIndex]:
    """Block indexes for a list of files, in the given order."""
    return [scan_container_blocks(Path(p)) for p in paths]
