"""Chunked, overlapped host->device upload for ingest-sized arrays.

Two problems with one ``jax.device_put`` of a multi-GB training matrix:
(1) the remote-TPU tunnel rejects single uploads beyond ~300 MB (HTTP 413 —
docs/SCALE.md §Remote-tunnel ingest caveat), and (2) the host-side staging
(densify / dtype-cast) of chunk k+1 could be running while chunk k is on
the wire, but a monolithic put serializes them.

``chunked_device_put`` splits on the leading axis and keeps at most
``depth`` transfers in flight (double-buffered by default): device_put is
async under JAX, so while chunk k transfers, the python loop is already
slicing/casting chunk k+1. The result — ``jnp.concatenate`` of the chunks —
is value-identical to a whole-array put.

``OverlappedUploader`` is the push-style variant for producers that emit
chunks over time (the multi-process decode pipeline: workers hand the
parent shard columns while later shards are still decoding —
data/parallel_ingest.py's ``column_consumer`` hook plugs straight into
``submit``).

``HostPrefetcher`` is the host-side dual of ``InFlightWindow``: where the
window bounds async DEVICE work already dispatched, the prefetcher bounds
host PRODUCTION of future work — a background thread runs an iterator
(e.g. block decode + featureize of batch k+1, data/block_stream.py) while
the consumer's loop body (device dispatch of batch k) executes, holding at
most ``depth`` finished items. Chaining the two gives the three-stage
decode → H2D → dispatch pipeline of streamed scoring.
"""

from __future__ import annotations

import queue
import threading
from collections import deque

import numpy as np

from photon_ml_tpu.telemetry import span

# Default per-transfer cap: comfortably under the tunnel's ~300 MB limit
# while big enough that per-put dispatch overhead stays negligible.
DEFAULT_CHUNK_BYTES = 128 << 20


def _rows_per_chunk(nbytes_per_row: int, chunk_bytes: int) -> int:
    return max(1, chunk_bytes // max(1, nbytes_per_row))


def chunked_device_put(x, dtype=None, device=None,
                       chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                       depth: int = 2):
    """Upload ``x`` (numpy or scipy-sparse-row-sliceable) in leading-axis
    chunks, ``depth`` transfers in flight; returns one device array equal
    to ``jnp.asarray(x, dtype)``.

    Sparse input is densified PER CHUNK (``.toarray()`` on the row slice),
    so the full dense host copy never materializes — the peak host
    footprint is the CSR plus ``depth`` chunks.

    Device-side peak is transiently ~2x the array during the final
    ``jnp.concatenate`` (chunks + destination). A donated
    dynamic-update-slice into a preallocated buffer would cap it at ~1x
    on TPU, but donation is ignored on CPU, where every functional
    update would copy the full buffer per chunk — deliberately not done
    until a workload actually hits the 2x ceiling.

    The whole call reports as one ``h2d`` telemetry span: device_put is
    async, so the span measures host staging + enqueue plus the
    window-bounding ``block_until_ready`` waits — the H2D stage of the
    decode -> H2D -> dispatch attribution, charged where the host
    actually spends the time.
    """
    with span("h2d"):
        return _chunked_device_put(x, dtype, device, chunk_bytes, depth)


def _chunked_device_put(x, dtype, device, chunk_bytes, depth):
    import jax
    import jax.numpy as jnp
    import scipy.sparse as sp

    sparse = sp.issparse(x)
    if sparse:
        x = x.tocsr()  # coo/dia/... aren't row-sliceable; csr is (no-op
        # for the csr matrices the ingest paths hand in)
    else:
        x = np.asarray(x)
    n = x.shape[0] if x.ndim else 0
    # Size chunks by the WIDER of source and target dtypes: the transfer
    # happens at the target width, so casting int8 -> f32 must not turn a
    # 128 MB host chunk into a 512 MB wire transfer.
    itemsize = np.dtype(np.float64).itemsize if sparse else x.dtype.itemsize
    if dtype is not None:
        try:
            itemsize = max(itemsize, np.dtype(dtype).itemsize)
        except TypeError:
            pass  # exotic dtype numpy can't size; host itemsize stands
    elems_per_row = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
    row_bytes = elems_per_row * itemsize
    total_bytes = n * row_bytes

    def put(chunk):
        if sparse:
            chunk = chunk.toarray()
        a = jnp.asarray(chunk, dtype)
        return a if device is None else jax.device_put(a, device)

    if x.ndim == 0 or n <= 1 or total_bytes <= chunk_bytes:
        return put(x)

    rows = _rows_per_chunk(row_bytes, chunk_bytes)
    parts = []
    in_flight: deque = deque()
    for start in range(0, n, rows):
        a = put(x[start:start + rows])
        parts.append(a)
        in_flight.append(a)
        if len(in_flight) >= depth:
            # Bound the in-flight window: wait for the OLDEST transfer so
            # chunk k+depth's host staging overlaps chunks k+1..k+depth-1
            # on the wire.
            jax.block_until_ready(in_flight.popleft())
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


class InFlightWindow:
    """Bounded window of in-flight async device work.

    The shared scheduling primitive behind every overlapped host<->device
    pipeline here: ``push(item, ready=...)`` enqueues a unit of async work
    and — once ``depth`` units are in flight — BLOCKS on the oldest one
    and returns its item (else None). The caller's loop body between
    pushes (slicing/casting the next chunk, featureizing the next request
    batch) thereby overlaps the transfers/dispatches already on the wire.
    Used by ``OverlappedUploader`` (decode ‖ H2D) and the serving
    engine's featureize -> H2D -> score pipeline (host work for batch k+1
    ‖ device dispatch of batch k).
    """

    def __init__(self, depth: int = 2):
        self._depth = max(1, depth)
        self._q: deque = deque()

    def __len__(self) -> int:
        return len(self._q)

    def push(self, item, ready=None):
        """Enqueue ``item``; block on/return the oldest item when the
        window is full, else None. ``ready`` (default: item itself) is
        what jax.block_until_ready waits on — pass the device arrays when
        item is a richer record."""
        import jax

        self._q.append((item, item if ready is None else ready))
        if len(self._q) >= self._depth:
            old_item, old_ready = self._q.popleft()
            # ``device_wait``: the ONE place device execution meets the
            # host — this block_until_ready already existed to bound the
            # window, so a span here attributes device-bound time
            # without adding a sync (docs/OBSERVABILITY.md span rules).
            with span("device_wait"):
                jax.block_until_ready(old_ready)
            return old_item
        return None

    def drain(self):
        """Yield the remaining items oldest-first, blocking on each."""
        import jax

        while self._q:
            item, ready = self._q.popleft()
            with span("device_wait"):
                jax.block_until_ready(ready)
            yield item


class HostPrefetcher:
    """Bounded background-thread prefetch of an iterator.

    ``iter(HostPrefetcher(src, depth))`` yields ``src``'s items in order
    while a daemon thread keeps producing ahead, blocking once ``depth``
    finished items wait unconsumed — so the producer can never run the
    host out of memory. Items RESIDENT at any instant are bounded by
    ``depth`` (queued) + 1 (in the producer's hand, blocked on a full
    queue) + 1 (held by the consumer) = ``depth + 2``; ``peak_resident``
    records the high-water mark of the first two terms plus the
    consumer's (so its bound is exactly ``depth + 2``).

    Producer exceptions re-raise in the consumer at the position they
    occurred; abandoning the iterator mid-stream (``close()``/GC of the
    generator) stops the producer promptly via a poll-stop flag rather
    than leaving it blocked on a full queue forever.
    """

    _POLL_S = 0.05

    def __init__(self, src, depth: int = 2):
        self._src = src
        self._depth = max(1, depth)
        self.peak_resident = 0

    def __iter__(self):
        q: "queue.Queue[tuple]" = queue.Queue(maxsize=self._depth)
        stop = threading.Event()
        lock = threading.Lock()
        in_flight = [0]  # produced, not yet handed to the consumer

        def put(msg) -> bool:
            while not stop.is_set():
                try:
                    q.put(msg, timeout=self._POLL_S)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for item in self._src:
                    with lock:
                        in_flight[0] += 1
                        # +1: the item the consumer currently holds.
                        self.peak_resident = max(self.peak_resident,
                                                 in_flight[0] + 1)
                    if not put(("item", item)):
                        return
                put(("done", None))
            except BaseException as e:  # noqa: BLE001 — re-raised below
                put(("err", e))

        t = threading.Thread(target=produce, daemon=True,
                             name="host-prefetch")
        t.start()
        try:
            while True:
                # ``prefetch_wait``: consumer blocked on the producer —
                # the feeder-bound share of the stall attribution (its
                # dual, device-bound, is InFlightWindow's device_wait).
                with span("prefetch_wait"):
                    kind, val = q.get()
                if kind == "done":
                    break
                if kind == "err":
                    raise val
                with lock:
                    in_flight[0] -= 1
                yield val
        finally:
            stop.set()


class OverlappedUploader:
    """Push-style double-buffered feeder: ``submit(host_chunk)`` starts an
    async device transfer and returns immediately (unless ``depth``
    transfers are already in flight); ``collect()`` waits and concatenates.

    The producer (e.g. the parallel-decode assembly loop) keeps decoding
    while submitted chunks ride the wire — H2D of chunk k overlaps decode
    of chunk k+1, which is the whole point.

    Chunks are copied at submit time (``jnp.asarray``), so callers may hand
    in views over transient buffers (shared-memory segments included).
    """

    def __init__(self, dtype=None, device=None, depth: int = 2,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES):
        self._dtype = dtype
        self._device = device
        self._depth = max(1, depth)
        self._chunk_bytes = chunk_bytes
        self._parts: list = []
        self._window = InFlightWindow(depth)

    def submit(self, chunk) -> None:
        a = chunked_device_put(chunk, self._dtype, self._device,
                               self._chunk_bytes, self._depth)
        self._parts.append(a)
        self._window.push(a)

    def collect(self):
        """Device concatenation of everything submitted (None if empty)."""
        import jax.numpy as jnp

        if not self._parts:
            return None
        out = (self._parts[0] if len(self._parts) == 1
               else jnp.concatenate(self._parts, axis=0))
        self._parts = []
        self._window = InFlightWindow(self._depth)
        return out
