"""Streaming distribution monitoring: per-batch data/model statistics
piggybacked on the decode passes the pipeline already pays for
(docs/OBSERVABILITY.md §Distributions & drift).

Three pieces, all built on the deterministic mergeable sketches in
telemetry/sketches.py:

- :class:`StreamingDistributionMonitor` — the ``--stream-train
  --distmon`` accumulator: label/offset/weight moments + quantiles,
  per-feature-shard value sketches (over the CSR nonzeros the decoder
  produced — zero extra feature passes), bounded top-K heavy hitters
  per entity-id column, and per-λ solver convergence rings. Updates are
  observed per decoded batch via :class:`MonitoredStream`, so batch
  boundaries — and therefore sketch state — are fixed by the shard
  order: snapshots are residency/feeder/prefetch-INDEPENDENT, bitwise
  (``serialize()``/``state_sha256``), the same discipline as the PR 5/10
  model-byte guarantees. The monitor is lock-guarded so a live /distz
  scrape can read mid-ingest.
- :class:`MonitoredStream` — a transparent iterator wrapper: observes
  each yielded batch, delegates every attribute to the wrapped stream
  (``stats()``, ``decode_path``, ...), so the shard cache / assembler
  consume it exactly like a bare ``BlockGameStream``. With prefetch the
  observation runs on the producer thread, overlapped like the decode
  it rides on.
- :class:`ScoreDistributionMonitor` — the serving-side score sketch:
  one per resident model, fed at scatter-back by the engine settle
  (one vectorized update + one lock per settled GROUP — the PR 11
  deferred-settle overhead recipe), with PSI/KS drift computed lazily
  against the model's embedded reference snapshot on scrape and
  published as ``serving.model.<label>.score_drift_psi`` / ``_ks``
  gauges — which the ``--slo`` value objective can alert on with no new
  alerting code. The disabled path is a no-op BY CONSTRUCTION: engines
  carry ``score_monitor = None`` and skip the call entirely.

Nothing in this module runs inside jitted code, and none of it runs at
all unless a driver constructed a monitor (``--distmon``).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry.sketches import (
    MomentsSketch,
    QuantileSketch,
    TopKSketch,
    _canonical_json,
    ks,
    psi,
)

#: Reference-snapshot schema version stamped into model artifacts
#: (model-metadata.json ``referenceDistributions``).
REFERENCE_VERSION = 1


class _ColumnSketch:
    """Moments + quantiles over one scalar column."""

    def __init__(self, relative_accuracy: float):
        self.moments = MomentsSketch()
        self.quantiles = QuantileSketch(relative_accuracy)

    def update(self, values) -> None:
        self.moments.update(values)
        self.quantiles.update(values)

    def summary(self) -> dict:
        return {"moments": self.moments.summary(),
                "quantiles": self.quantiles.summary()}

    def state(self) -> dict:
        return {"moments": self.moments.state(),
                "quantiles": self.quantiles.state()}


class StreamingDistributionMonitor:
    """Training-side distribution accumulator (module docstring).

    ``feature_shards`` may be empty: shard names are adopted (sorted)
    from the first observed batch. ``top_k`` bounds the per-id-type
    heavy-hitter summaries. One instance per driver run; all methods are
    thread-safe (decode thread writes, scrape threads read)."""

    def __init__(self, feature_shards: Sequence[str] = (),
                 id_types: Sequence[str] = (),
                 relative_accuracy: float = 0.01, top_k: int = 16):
        self.relative_accuracy = float(relative_accuracy)
        self.top_k = int(top_k)
        self.rows = 0
        self.batches = 0
        self._lock = threading.Lock()
        self._columns = {name: _ColumnSketch(self.relative_accuracy)
                         for name in ("label", "offset", "weight")}
        self._shards: Dict[str, _ColumnSketch] = {
            s: _ColumnSketch(self.relative_accuracy)
            for s in sorted(feature_shards)}
        self._entities: Dict[str, TopKSketch] = {
            t: TopKSketch(self.top_k) for t in sorted(id_types)}
        self._scores: Dict[str, _ColumnSketch] = {}
        self._rings: Dict[str, object] = {}
        # Headline gauges, mirrored to /metrics on publish_gauges()
        # (scrape-hook refreshed; data.dist.* is a gauge-only family —
        # dev_scripts/metric_names.py enforces this statically).
        self._g_rows = telemetry.gauge("data.dist.rows")
        self._g_batches = telemetry.gauge("data.dist.batches")
        self._g_label_mean = telemetry.gauge("data.dist.label_mean")
        self._g_label_p50 = telemetry.gauge("data.dist.label_p50")
        self._g_label_p99 = telemetry.gauge("data.dist.label_p99")
        self._g_weight_mean = telemetry.gauge("data.dist.weight_mean")
        self._g_offset_mean = telemetry.gauge("data.dist.offset_mean")

    # -- ingest-side observation -------------------------------------------

    def observe_batch(self, ds) -> None:
        """Fold one decoded GameDataset batch in (called per batch by
        :class:`MonitoredStream` — on the prefetch thread when the
        feeder prefetches). Vectorized numpy over columns the decode
        already materialized; never touches the feature matrices beyond
        their existing ``.data`` nonzeros."""
        n = int(ds.num_rows)
        if n == 0:
            return
        with self._lock:
            self.rows += n
            self.batches += 1
            self._columns["label"].update(ds.responses)
            self._columns["offset"].update(ds.offsets)
            self._columns["weight"].update(ds.weights)
            if not self._shards:
                self._shards = {
                    s: _ColumnSketch(self.relative_accuracy)
                    for s in sorted(ds.feature_shards)}
            for name, sk in self._shards.items():
                mat = ds.feature_shards.get(name)
                if mat is not None and mat.nnz:
                    sk.update(mat.data)
            for etype, col in sorted(ds.id_columns.items()):
                tk = self._entities.get(etype)
                if tk is None:
                    tk = self._entities[etype] = TopKSketch(self.top_k)
                codes, counts = np.unique(col.codes, return_counts=True)
                tk.update(col.vocabulary[codes], counts)

    def observe_scores(self, label: str, values) -> None:
        """Fold a training-score vector (model margins, offsets
        excluded) for one λ-grid point — fed from the solver's final
        margins (optimization/glm_lbfgs.py ``margins_out``), so it
        costs no feature pass."""
        v = np.asarray(values, np.float64).ravel()
        v = v[np.isfinite(v)]
        with self._lock:
            sk = self._scores.get(label)
            if sk is None:
                sk = self._scores[label] = _ColumnSketch(
                    self.relative_accuracy)
            sk.update(v)

    def add_ring(self, label: str, ring) -> None:
        """Attach a per-λ :class:`ConvergenceRing`
        (optimization/convergence.py) so /distz and the metrics.json
        ``data_quality`` block carry the solve's loss/grad-norm/step
        tail."""
        with self._lock:
            self._rings[label] = ring

    def ring_from_history(self, label: str, values, grad_norms) -> None:
        """Append one solve's ``value_history``/``grad_norm_history``
        to the label's ring, get-or-create (the fused in-core solvers
        cannot ring live from inside their ``lax.while_loop``).
        APPENDING — not replacing — keeps the post-hoc path structurally
        identical to the live streamed-solver rings under
        ``--num-iterations > 1``: every warm-started re-solve's entries
        land in one ring, iteration indexes restarting at each solve
        boundary (warm restarts really do restart the count)."""
        from photon_ml_tpu.optimization.convergence import ConvergenceRing

        with self._lock:
            ring = self._rings.get(label)
            if ring is None:
                ring = self._rings[label] = ConvergenceRing()
        vs = np.asarray(values, np.float64)
        gs = np.asarray(grad_norms, np.float64)
        for i, (v, g) in enumerate(zip(vs, gs)):
            if np.isnan(v) and np.isnan(g):
                break  # histories are NaN-padded past `iterations`
            ring.append(i, v, g, None)

    # -- views -------------------------------------------------------------

    def snapshot(self) -> dict:
        """Full human-readable view (the /distz training payload)."""
        with self._lock:
            return {
                "rows": self.rows,
                "batches": self.batches,
                "relative_accuracy": self.relative_accuracy,
                "columns": {k: v.summary()
                            for k, v in sorted(self._columns.items())},
                "feature_shards": {k: v.summary()
                                   for k, v in sorted(self._shards.items())},
                "entities": {k: v.summary()
                             for k, v in sorted(self._entities.items())},
                "training_scores": {k: v.summary()
                                    for k, v in sorted(self._scores.items())},
                "convergence": {k: r.snapshot()
                                for k, r in sorted(self._rings.items())},
            }

    def serialize(self) -> bytes:
        """Canonical bytes of the STREAM-observed state only (columns,
        feature shards, entities, row/batch counts). Deliberately
        excludes training-score sketches and convergence rings: those
        derive from the SOLVE (resident vs spill paths legitimately
        differ in float detail), while the stream-observed state is the
        residency/feeder/prefetch-independence contract the CLI tests
        pin bitwise."""
        with self._lock:
            return _canonical_json({
                "rows": self.rows,
                "batches": self.batches,
                "relative_accuracy": self.relative_accuracy,
                "columns": {k: v.state()
                            for k, v in sorted(self._columns.items())},
                "feature_shards": {k: v.state()
                                   for k, v in sorted(self._shards.items())},
                "entities": {k: v.state()
                             for k, v in sorted(self._entities.items())},
            })

    def state_sha256(self) -> str:
        return hashlib.sha256(self.serialize()).hexdigest()

    def sketch_states(self) -> dict:
        """Flat ``{key: sketch_state}`` map for /snapshotz federation
        (telemetry/federation.py): dotted keys like
        ``columns.label.quantiles`` / ``entities.<type>``, each value a
        ``sketch_from_state``-reconstructible state dict, so the fleet
        aggregator merges equal keys across training children with the
        sketches' own deterministic merges."""
        with self._lock:
            out = {}
            for name, col in sorted(self._columns.items()):
                out[f"columns.{name}.moments"] = col.moments.state()
                out[f"columns.{name}.quantiles"] = col.quantiles.state()
            for name, col in sorted(self._shards.items()):
                out[f"feature_shards.{name}.moments"] = \
                    col.moments.state()
                out[f"feature_shards.{name}.quantiles"] = \
                    col.quantiles.state()
            for name, sk in sorted(self._entities.items()):
                out[f"entities.{name}"] = sk.state()
            return out

    def data_quality_block(self) -> dict:
        """The metrics.json ``data_quality`` block: sketch summaries +
        per-λ convergence tails + the canonical state hash (the
        residency-independence witness)."""
        out = self.snapshot()
        out["state_sha256"] = self.state_sha256()
        return out

    def reference(self, score_label: Optional[str] = None) -> dict:
        """The reference-distribution snapshot stamped into the model
        artifact (label quantiles + the chosen λ's training-score
        quantiles when available) — what serving drift-scores against."""
        with self._lock:
            ref = {
                "version": REFERENCE_VERSION,
                "relative_accuracy": self.relative_accuracy,
                "rows": self.rows,
                "label": self._columns["label"].quantiles.state(),
                "label_summary":
                    self._columns["label"].quantiles.summary(),
            }
            sk = self._scores.get(score_label) if score_label else None
            if sk is not None:
                ref["score"] = sk.quantiles.state()
                ref["score_summary"] = sk.quantiles.summary()
                ref["score_label"] = score_label
            return ref

    def publish_gauges(self) -> None:
        """Refresh the headline ``data.dist.*`` gauges (scrape hook /
        driver-finish)."""
        with self._lock:
            label = self._columns["label"]
            weight = self._columns["weight"]
            offset = self._columns["offset"]
            self._g_rows.set(self.rows)
            self._g_batches.set(self.batches)
            if label.moments.count:
                self._g_label_mean.set(label.moments.mean)
                self._g_label_p50.set(label.quantiles.quantile(0.5))
                self._g_label_p99.set(label.quantiles.quantile(0.99))
            if weight.moments.count:
                self._g_weight_mean.set(weight.moments.mean)
            if offset.moments.count:
                self._g_offset_mean.set(offset.moments.mean)


class MonitoredStream:
    """Iterator wrapper observing each yielded batch into a
    :class:`StreamingDistributionMonitor`; every other attribute
    delegates to the wrapped stream, so cache/assembler consumers
    (``DeviceShardCache.from_stream``, ``assemble_fixed_effect_batch``)
    see the stream contract unchanged — zero extra decode or feature
    passes, observation rides the pass that was already happening.

    ``max_passes`` bounds how many full iterations are OBSERVED (later
    passes yield untouched): the streamed-MF path re-decodes the same
    container once per feature pass, and every pass replays identical
    bytes — so one observed pass is the distribution, counted once.
    None (default) observes every pass (the fixed-effect ingest
    iterates exactly once anyway)."""

    def __init__(self, stream, monitor: StreamingDistributionMonitor,
                 max_passes: Optional[int] = None):
        self._stream = stream
        self._monitor = monitor
        self._max_passes = max_passes
        self._passes = 0

    def __iter__(self):
        observe = (self._max_passes is None
                   or self._passes < self._max_passes)
        self._passes += 1
        if not observe:
            yield from self._stream
            return
        for ds in self._stream:
            self._monitor.observe_batch(ds)
            yield ds

    def __getattr__(self, name):
        return getattr(self._stream, name)


class ScoreDistributionMonitor:
    """Per-model serving score distribution + drift vs the model's
    embedded reference (module docstring).

    ``reference`` is the ``referenceDistributions`` block of
    model-metadata.json (or None — the sketch still accumulates, drift
    reads None). The current-score sketch uses the REFERENCE's
    relative accuracy when one is embedded, so the two CDFs share a
    bucket grid."""

    def __init__(self, label: str, reference: Optional[dict] = None,
                 relative_accuracy: float = 0.01):
        self.label = label
        self.reference = reference
        acc = relative_accuracy
        self._ref_sketch = None
        if reference is not None and reference.get("score") is not None:
            self._ref_sketch = QuantileSketch.from_state(
                reference["score"])
            acc = self._ref_sketch.relative_accuracy
        self._lock = threading.Lock()
        self._sketch = _ColumnSketch(acc)
        self.non_finite = 0
        # Deferred-settle buffer (the PR 11 recipe): the engine settle
        # only APPENDS the group's score vector under the lock; sketch
        # folding happens in one vectorized update per ~flush_rows rows
        # (and before any read), so per-group hot-path cost is a copy +
        # a list append regardless of group size, and the fold
        # amortizes to the large-batch sketch rate. Serving moments are
        # therefore flush-granular rather than group-granular — live
        # traffic has no bit-stability contract (training does, and
        # the training monitor never buffers). 64k f64 buffered rows =
        # 512 KB bounded host memory per model.
        self.flush_rows = 65536
        self._buffer: List[np.ndarray] = []
        self._buffered = 0
        pre = f"serving.model.{label}."
        self._g_psi = telemetry.gauge(pre + "score_drift_psi")
        self._g_ks = telemetry.gauge(pre + "score_drift_ks")
        self._g_rows = telemetry.gauge(pre + "score_dist_rows")

    def observe(self, scores) -> None:
        """Buffer one settled group's score vector (one lock + one
        small copy per GROUP — called from the engine settle). Folding
        into the sketches is deferred to the flush threshold / the next
        read. Non-finite scores are counted at flush, not raised: a
        corrupt score must not poison the serving path that produced
        it."""
        v = np.asarray(scores, np.float64).ravel()
        if v.size == 0:
            return
        with self._lock:
            self._buffer.append(v.copy())
            self._buffered += v.size
            if self._buffered >= self.flush_rows:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buffer:
            return
        v = (self._buffer[0] if len(self._buffer) == 1
             else np.concatenate(self._buffer))
        self._buffer = []
        self._buffered = 0
        finite = np.isfinite(v)
        bad = int(v.size - finite.sum())
        if bad:
            self.non_finite += bad
            v = v[finite]
        if v.size:
            self._sketch.update(v)

    def _drift_locked(self) -> Optional[dict]:
        # Caller holds self._lock and has flushed. One lock scope per
        # published view, so drift/scores/rows in a payload always
        # describe the SAME flushed state (a concurrent settle cannot
        # land between them).
        if self._ref_sketch is None:
            return None
        cur = self._sketch.quantiles
        if cur.count == 0:
            return None
        return {
            "psi": psi(self._ref_sketch, cur),
            "ks": ks(self._ref_sketch, cur),
            "rows": cur.count,
            "reference_rows": self._ref_sketch.count,
            "reference_label": (self.reference or {}).get(
                "score_label"),
        }

    def drift(self) -> Optional[dict]:
        """PSI + KS of the live score sketch against the embedded
        reference; None without a reference or before any scores."""
        with self._lock:
            self._flush_locked()
            return self._drift_locked()

    def publish_gauges(self) -> None:
        """Refresh the drift gauges (registered as a scrape hook, so
        drift is computed against the CURRENT sketch on every /metrics,
        /statusz, /distz scrape and heartbeat tick — which is what lets
        an ``--slo`` value objective burn on drift)."""
        with self._lock:
            self._flush_locked()
            d = self._drift_locked()
            rows = self._sketch.quantiles.count
        self._g_rows.set(rows)
        if d is not None:
            self._g_psi.set(d["psi"])
            self._g_ks.set(d["ks"])

    def sketch_states(self) -> dict:
        """Flat ``{key: sketch_state}`` map for /snapshotz federation:
        the live score sketch of this model, keyed under its label so
        the aggregator merges same-model replicas and keeps different
        models apart."""
        with self._lock:
            self._flush_locked()
            return {
                f"{self.label}.scores.moments":
                    self._sketch.moments.state(),
                f"{self.label}.scores.quantiles":
                    self._sketch.quantiles.state(),
            }

    def snapshot(self) -> dict:
        """The /distz serving payload for this model (scores, counters
        and drift all read under ONE lock scope — mutually
        consistent)."""
        with self._lock:
            self._flush_locked()
            return {
                "label": self.label,
                "scores": self._sketch.summary(),
                "non_finite_scores": self.non_finite,
                "reference": ((self.reference or {}).get("score_summary")
                              if self.reference else None),
                "drift": self._drift_locked(),
            }


__all__: List[str] = [
    "MonitoredStream",
    "REFERENCE_VERSION",
    "ScoreDistributionMonitor",
    "StreamingDistributionMonitor",
]
