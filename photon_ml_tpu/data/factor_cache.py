"""Device-resident factor-table cache for out-of-core MATRIX FACTORIZATION.

The GAME MF coordinate (`FactoredRandomEffectCoordinate`) materializes
both factor tables densely and solves fully in-core, capping the MF leg
at HBM while fixed and random effects already train out-of-core (PRs
5/7/10). This module is the factor-side half of the streamed MF
subsystem (ops/mf_alternating.py is the solver half): per-entity latent
factor shards held in a `DeviceShardCache`-style cache so factor tables
larger than HBM train to completion.

**ALX-style planning** (`plan_factors`, PAPERS.md "ALX: Large Scale
Matrix Factorization on TPUs"): entities are bucketed by OBSERVATION
COUNT into power-of-two density classes — ALX's density-based bucketing,
which groups entities whose per-entity solves have similar work so a
batched shard wastes no padding on wildly mixed densities — then each
class is cut into shards of at most ``entities_per_shard`` entities,
padded to a pow-2 entity axis (``e_pad``). The resulting shard list is a
pure function of (vocabulary, counts), so the fixed shard order — the
replay order of every alternating sweep — is deterministic.

**Residency** (`DeviceFactorCache`): each shard's gamma table
(``f32[e_pad, k]``) is the evictable unit. ``hbm_budget_bytes`` bounds
the factor bytes resident on device; eviction is replay-aware over the
fixed alternating-sweep order (the sweep writes shards 0..n-1 in the
gamma pass and reads them 0..n-1 at model assembly — a cyclic scan, so
the victim is the resident shard whose next use is furthest in the
cyclic order, exactly the Belady-on-cyclic-replay rule
`DeviceShardCache` proved out; plain LRU is a guaranteed thrash on
cyclic replay).

**Spill tiers** — the PR-10 hierarchy, re-pointed at factors:

- ``spill_dtype="f32"`` (default): evicted gamma tables spill to raw
  f32 host buffers; restore re-uploads the evicted bytes verbatim, so
  every replay/residency bitwise guarantee holds unchanged.
- ``spill_dtype="bf16"``: factors are quantized to bfloat16 AT WRITE —
  every shard takes the same bf16 round trip whether or not it ever
  spills, so a bf16 train is deterministic AND residency-independent
  (eviction history cannot touch the model bits); evicted shards spill
  the bf16 bytes (half of f32) and restore widens back to f32 on
  device, keeping the solver kernels' dtype contract untouched.
- ``spill_source="redecode"``: NO host copy — an evicted shard is
  dropped, and a cache miss re-derives it FROM OBSERVATIONS through the
  ``redecode`` hook (ops/mf_alternating.py re-runs the shard's batched
  normal-equation solve against the sweep's projection matrix over the
  re-decoded covering observation batches). Because the per-sweep gamma
  solve is an exact ridge solve — a pure function of (observations, B)
  with no warm start — the re-derived bytes are bit-for-bit the evicted
  ones. bf16 + redecode is rejected, exactly like the feature cache
  (the combination would silently train f32 while reporting bf16).

The reference's analog is the per-iteration factor RDD join of
FactoredRandomEffectCoordinate.scala; ALX instead shards the embedding
tables across chips — here the shard axis is residency over time on one
budgeted device, with the same static-shape bucket discipline.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, List, Optional

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.serving.buckets import next_pow2
from photon_ml_tpu.utils.vocab import SortedVocab

# Registry mirrors of the per-instance ``_stats`` (no-ops while
# telemetry is off); names are part of the metrics.json snapshot schema
# (docs/OBSERVABILITY.md).
_M_HITS = telemetry.counter("data.factor_cache.hits")
_M_MISSES = telemetry.counter("data.factor_cache.misses")
_M_EVICTIONS = telemetry.counter("data.factor_cache.evictions")
_M_REUPLOAD_BYTES = telemetry.counter("data.factor_cache.bytes_reuploaded")
_M_SPILL_WRITTEN = telemetry.counter("data.factor_cache.spill_bytes_written")
_M_REDECODES = telemetry.counter("data.factor_cache.redecodes")
_G_DEVICE_BYTES = telemetry.gauge("data.factor_cache.device_bytes")
_G_PEAK_BYTES = telemetry.gauge("data.factor_cache.peak_device_bytes")
_G_SPILL_HOST = telemetry.gauge("data.factor_cache.spill_bytes_host")

FACTOR_SPILL_DTYPES = ("f32", "bf16")
FACTOR_SPILL_SOURCES = ("buffer", "redecode")


# ---------------------------------------------------------------------------
# ALX-style planning: observation-count classes -> pow-2 padded shards
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FactorShardSpec:
    """One factor shard: a pow-2-padded slice of one observation-count
    class. ``codes`` are GLOBAL entity codes (indexes into the plan's
    vocabulary), ascending — the slot order inside the shard."""

    index: int
    obs_bucket: int  # pow-2 observation-count class (next_pow2(count))
    codes: np.ndarray  # i64[n_entities], ascending
    e_pad: int  # pow-2 padded entity axis

    @property
    def n_entities(self) -> int:
        return len(self.codes)


@dataclasses.dataclass
class FactorPlan:
    """Deterministic entity -> (shard, slot) assignment.

    ``vocabulary`` is the SORTED unique entity-name array (the same
    ordering `GameDataset.build` / `np.unique` produces, so plan codes
    and in-core model codes agree); ``counts[c]`` is entity c's
    observation count. Zero-observation entities are planned too — they
    ride the smallest density class and solve to exactly zero factors
    (ridge normal equations with A = 0, b = 0)."""

    vocabulary: np.ndarray
    counts: np.ndarray
    shards: List[FactorShardSpec]
    shard_of_code: np.ndarray  # i32[n_codes]
    slot_of_code: np.ndarray  # i32[n_codes]

    def __post_init__(self):
        self._sorted = SortedVocab.build(self.vocabulary)

    @property
    def num_entities(self) -> int:
        return len(self.vocabulary)

    @property
    def n_shards(self) -> int:
        return len(self.shards)

    def codes_of(self, names) -> np.ndarray:
        """Global entity codes for a batch of names (-1 when unknown —
        the standard missing-join semantics)."""
        return self._sorted.codes_of(names)

    def obs_bucket_histogram(self) -> Dict[int, int]:
        """entities per pow-2 observation-count class (the ALX density
        histogram — reported in stream_train telemetry)."""
        out: Dict[int, int] = {}
        for s in self.shards:
            out[s.obs_bucket] = out.get(s.obs_bucket, 0) + s.n_entities
        return out


def plan_factors(vocabulary, counts, entities_per_shard: int = 512,
                 min_entities_pad: int = 8) -> FactorPlan:
    """Bucket entities ALX-style by observation count, then shard.

    Classes are ``next_pow2(count)`` (zero-count entities join the
    smallest class); within a class entities keep ascending code order;
    each class is cut into runs of at most ``entities_per_shard`` and
    padded to ``e_pad = next_pow2(len)`` (>= ``min_entities_pad``).
    Everything is sorted, so the plan — and the fixed shard replay
    order — is a pure function of its inputs."""
    vocabulary = np.asarray(vocabulary)
    counts = np.asarray(counts, np.int64)
    if len(vocabulary) != len(counts):
        raise ValueError(
            f"vocabulary has {len(vocabulary)} entities, counts has "
            f"{len(counts)}")
    if entities_per_shard < 1:
        raise ValueError(
            f"entities_per_shard must be >= 1, got {entities_per_shard}")
    n = len(vocabulary)
    # Vectorized next_pow2 over the whole counts column (the per-entity
    # python loop was O(entities) interpreter work at a subsystem whose
    # target is millions of entities): frexp is exact for ints < 2^53 —
    # v = m * 2^e with m in [0.5, 1), so next_pow2(v) is 2^(e-1) when v
    # is itself a power of two (m == 0.5) and 2^e otherwise.
    v = np.maximum(counts, 1).astype(np.float64)
    m, e = np.frexp(v)
    cls_of = np.where(m == 0.5, np.left_shift(np.int64(1), e - 1),
                      np.left_shift(np.int64(1), e))
    order = np.lexsort((np.arange(n, dtype=np.int64), cls_of))

    shards: List[FactorShardSpec] = []
    shard_of = np.full(n, -1, np.int32)
    slot_of = np.full(n, -1, np.int32)
    classes, starts = np.unique(cls_of[order], return_index=True)
    bounds = list(starts) + [n]
    for ci, cls in enumerate(classes):
        codes = order[bounds[ci]:bounds[ci + 1]]  # ascending by code
        for start in range(0, len(codes), entities_per_shard):
            run = codes[start:start + entities_per_shard]
            e_pad = max(next_pow2(len(run)), min_entities_pad)
            idx = len(shards)
            shards.append(FactorShardSpec(
                index=idx, obs_bucket=int(cls), codes=run, e_pad=e_pad))
            shard_of[run] = idx
            slot_of[run] = np.arange(len(run), dtype=np.int32)
    return FactorPlan(vocabulary=vocabulary, counts=counts, shards=shards,
                      shard_of_code=shard_of, slot_of_code=slot_of)


def count_stream_entities(stream, re_type: str):
    """One bounded-memory pass over a GameDataset stream: the global
    entity vocabulary (sorted unique names — the `np.unique` order the
    in-core path uses) and per-entity observation counts. Host state is
    O(entities), never O(rows). Returns
    ``(vocabulary, counts, n_rows, n_features_by_shard)``."""
    vocab = np.zeros(0, dtype="U1")
    cts = np.zeros(0, np.int64)
    n_rows = 0
    d_by_shard: Dict[str, int] = {}
    for ds in stream:
        if ds.num_rows == 0:
            continue
        col = ds.id_columns.get(re_type)
        if col is None:
            raise ValueError(
                f"stream batches carry no {re_type!r} id column — pass "
                "id_types=[random_effect_type] to the stream")
        names, per = np.unique(col.vocabulary[col.codes],
                               return_counts=True)
        # Vectorized running merge: host state stays O(entities), and
        # no per-name python loop runs (the batch's unique names fold
        # into the running sorted vocabulary in one unique + add).
        all_names = np.concatenate([vocab, names.astype(str)])
        all_counts = np.concatenate([cts, per.astype(np.int64)])
        vocab, inv = np.unique(all_names, return_inverse=True)
        cts = np.zeros(len(vocab), np.int64)
        np.add.at(cts, inv, all_counts)
        n_rows += ds.num_rows
        for s, mat in ds.feature_shards.items():
            d_by_shard[s] = mat.shape[1]
    if n_rows == 0:
        raise ValueError("stream yielded no rows to plan factors from")
    return vocab, cts, n_rows, d_by_shard


# ---------------------------------------------------------------------------
# Spill codec: f32 verbatim / bf16 half-width, widened back on device
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FactorSpill:
    """Host spill record of one evicted factor shard: the ``f32`` tag
    holds the evicted bytes verbatim; ``bf16`` holds the half-width
    quantized table (lossless w.r.t. the resident copy, which was
    quantized at write). Consumed ONLY by
    :func:`restore_spilled_factors`."""

    enc: np.ndarray  # f32[e_pad, k] | bfloat16[e_pad, k]
    dtype_tag: str  # "f32" | "bf16"

    @property
    def nbytes(self) -> int:
        return self.enc.nbytes


@functools.lru_cache(maxsize=1)
def _widen_jit():
    """One process-wide jitted bf16 -> f32 widen (built on first
    restore so importing this module never imports jax); traces once
    per (e_pad, k)."""
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda g: g.astype(jnp.float32))


@functools.lru_cache(maxsize=1)
def _quantize_jit():
    """One process-wide jitted f32 -> bf16 -> f32 round trip — the
    write-time quantization that makes bf16 factor trains
    residency-independent (every write takes it, evicted or not)."""
    import jax
    import jax.numpy as jnp

    return jax.jit(
        lambda g: g.astype(jnp.bfloat16).astype(jnp.float32))


def encode_factor_spill(gamma_host: np.ndarray,
                        spill_dtype: str) -> FactorSpill:
    """Host gamma table -> spill record. ``gamma_host`` is the np view
    of the (already write-quantized, for bf16) resident table, so the
    bf16 cast here is lossless and the round trip restores the exact
    resident bits."""
    if spill_dtype not in FACTOR_SPILL_DTYPES:
        raise ValueError(
            f"spill_dtype must be one of {FACTOR_SPILL_DTYPES}, got "
            f"{spill_dtype!r}")
    if spill_dtype == "f32":
        return FactorSpill(enc=np.asarray(gamma_host, np.float32),
                           dtype_tag="f32")
    import ml_dtypes

    return FactorSpill(
        enc=np.asarray(gamma_host).astype(ml_dtypes.bfloat16),
        dtype_tag="bf16")


def restore_spilled_factors(spill: FactorSpill):
    """The ONE blessed spill -> device path for factors: f32 re-uploads
    the evicted bytes verbatim; bf16 uploads the half-width encoding
    and widens on device."""
    import jax.numpy as jnp

    if spill.dtype_tag == "f32":
        return jnp.asarray(spill.enc)
    return _widen_jit()(jnp.asarray(spill.enc))


# ---------------------------------------------------------------------------
# The cache: budgeted factor-shard residency with replay-aware eviction
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FactorShard:
    """One planned shard's residency state. ``gamma`` is the canonical
    device table (None = evicted); ``spill`` its host record (None for
    the redecode tier, where a miss re-derives from observations)."""

    spec: FactorShardSpec
    gamma: object = None  # device f32[e_pad, k] | None
    spill: Optional[FactorSpill] = None
    written: bool = False  # at least one sweep wrote this shard
    _k: int = 0  # num_factors, set by the cache at construction

    @property
    def factor_bytes(self) -> int:
        # Device-resident cost at the padded f32 shape (bf16 restore
        # widens back to f32, like the feature cache's contract).
        return 4 * self.spec.e_pad * self._k

    @property
    def spill_bytes(self) -> int:
        return 0 if self.spill is None else self.spill.nbytes


class DeviceFactorCache:
    """Budgeted device residency for the factor tables of one streamed
    MF coordinate (module docstring). The alternating sweep WRITES
    shards in fixed order (gamma pass) and READS them in the same order
    (model assembly; redecode re-derivation) — a cyclic scan, so
    eviction uses the same furthest-next-use rule as the feature
    cache. ``redecode`` (set per sweep via :meth:`set_redecode`) is the
    observation-side re-derivation hook: ``fn(shard_index) -> device
    f32[e_pad, k]``, required on a miss in the ``redecode`` tier.

    ``devices`` (optional) shards the factor tables over a model-axis
    device list: shard ``i`` lives on ``devices[i % len(devices)]``,
    mirroring the feature cache's round-robin. Placement happens at
    every write/restore boundary, so spill re-uploads and redecodes
    land back on the shard's home device. ``devices=None`` (the
    default) skips placement entirely — that path is byte-identical
    to the single-device cache."""

    def __init__(self, plan: FactorPlan, num_factors: int,
                 hbm_budget_bytes: Optional[int] = None,
                 spill_dtype: str = "f32",
                 spill_source: str = "buffer",
                 redecode: Optional[Callable] = None,
                 devices: Optional[List] = None):
        if spill_dtype not in FACTOR_SPILL_DTYPES:
            raise ValueError(
                f"spill_dtype must be one of {FACTOR_SPILL_DTYPES}, got "
                f"{spill_dtype!r}")
        if spill_source not in FACTOR_SPILL_SOURCES:
            raise ValueError(
                f"spill_source must be one of {FACTOR_SPILL_SOURCES}, "
                f"got {spill_source!r}")
        if spill_source == "redecode" and spill_dtype != "f32":
            raise ValueError(
                f"spill_dtype={spill_dtype!r} compresses host spill "
                "buffers, but spill_source='redecode' keeps none — the "
                "combination would silently train as f32 while "
                "reporting bf16; pick one")
        if num_factors < 1:
            raise ValueError(f"num_factors must be >= 1, got {num_factors}")
        self.plan = plan
        self.k = int(num_factors)
        self.hbm_budget_bytes = hbm_budget_bytes
        self.spill_dtype = spill_dtype
        self.spill_source = spill_source
        self._redecode = redecode
        self.devices = list(devices) if devices else None
        self._entries = [FactorShard(spec=s, _k=self.k)
                         for s in plan.shards]
        self._stats = {"hits": 0, "misses": 0, "evictions": 0,
                       "bytes_reuploaded": 0, "spill_bytes_written": 0,
                       "redecodes": 0}
        self.device_bytes = 0
        self.peak_device_bytes = 0
        _G_SPILL_HOST.set(0)

    # -- wiring ------------------------------------------------------------

    def _place(self, index: int, gamma):
        """Home-device placement for one shard's table (round-robin
        over ``devices``); identity when the cache is single-device."""
        if self.devices is None:
            return gamma
        import jax

        return jax.device_put(
            gamma, self.devices[index % len(self.devices)])

    def shard_device(self, index: int):
        """The home device of shard ``index``, or None when unplaced."""
        if self.devices is None:
            return None
        return self.devices[index % len(self.devices)]

    def set_redecode(self, fn: Optional[Callable]) -> None:
        """Install the observation-side re-derivation hook for the
        current sweep (the hook closes over the sweep's projection
        matrix, so the solver refreshes it every gamma pass)."""
        self._redecode = fn

    @property
    def n_shards(self) -> int:
        return len(self._entries)

    @property
    def entries(self) -> List[FactorShard]:
        return list(self._entries)

    @property
    def spill_bytes_host(self) -> int:
        return sum(e.spill_bytes for e in self._entries)

    def e_pad_buckets(self) -> set:
        return {e.spec.e_pad for e in self._entries}

    # -- residency ---------------------------------------------------------

    def write(self, index: int, gamma):
        """Commit one shard's freshly solved factors as the canonical
        copy (the gamma pass calls this in fixed shard order). bf16
        trains quantize HERE — at write, unconditionally — so the
        stored (and returned) table is identical whether or not the
        shard ever spills; callers must use the RETURNED array (not
        their input) for anything feeding the model bytes. Stale spill
        records are dropped (the new write supersedes them); the budget
        is enforced with this shard pinned."""
        import jax.numpy as jnp

        e = self._entries[index]
        gamma = jnp.asarray(gamma, jnp.float32)
        if gamma.shape != (e.spec.e_pad, self.k):
            raise ValueError(
                f"factor shard {index} write has shape {gamma.shape}, "
                f"expected {(e.spec.e_pad, self.k)}")
        if self.spill_dtype == "bf16":
            gamma = _quantize_jit()(gamma)
        gamma = self._place(index, gamma)
        if e.gamma is None:
            self.device_bytes += e.factor_bytes
        e.gamma = gamma
        if e.spill is not None:
            e.spill = None  # superseded by this write
            _G_SPILL_HOST.set(self.spill_bytes_host)
        e.written = True
        self.peak_device_bytes = max(self.peak_device_bytes,
                                     self.device_bytes)
        _G_PEAK_BYTES.set(self.peak_device_bytes)
        _G_DEVICE_BYTES.set(self.device_bytes)
        self._enforce_budget(pinned=index)
        return gamma

    def ensure(self, index: int):
        """Resident factors for one shard, restoring on a miss: buffer
        spill re-uploads the host record; the redecode tier re-derives
        from observations via the hook. Never-written shards raise —
        a read before the first gamma pass is a sequencing bug."""
        e = self._entries[index]
        if not e.written:
            raise RuntimeError(
                f"factor shard {index} was never written — run a gamma "
                "pass before reading factors")
        if e.gamma is not None:
            self._stats["hits"] += 1
            _M_HITS.inc()
            return e.gamma
        self._stats["misses"] += 1
        _M_MISSES.inc()
        if e.spill is not None:
            reupload = e.spill.nbytes
            with telemetry.span("factor_reupload"):
                gamma = restore_spilled_factors(e.spill)
        elif self._redecode is not None:
            reupload = e.factor_bytes
            self._stats["redecodes"] += 1
            _M_REDECODES.inc()
            with telemetry.span("factor_redecode"):
                gamma = self._redecode(index)
            import jax.numpy as jnp

            gamma = jnp.asarray(gamma, jnp.float32)
            if gamma.shape != (e.spec.e_pad, self.k):
                raise RuntimeError(
                    f"redecode hook returned shape {gamma.shape} for "
                    f"shard {index}, expected {(e.spec.e_pad, self.k)}")
        else:
            raise RuntimeError(
                f"factor shard {index} was evicted but has no spill "
                "record and no redecode hook (cache built without an "
                "hbm budget?)")
        self._stats["bytes_reuploaded"] += reupload
        _M_REUPLOAD_BYTES.inc(reupload)
        e.gamma = self._place(index, gamma)
        self.device_bytes += e.factor_bytes
        self.peak_device_bytes = max(self.peak_device_bytes,
                                     self.device_bytes)
        _G_PEAK_BYTES.set(self.peak_device_bytes)
        _G_DEVICE_BYTES.set(self.device_bytes)
        self._enforce_budget(pinned=index)
        return e.gamma

    def _enforce_budget(self, pinned: int) -> None:
        """Evict until within budget. Victim = resident shard whose
        next use is FURTHEST in the fixed cyclic sweep order from the
        shard in hand (the feature cache's Belady-on-cyclic-replay
        rule; the in-hand shard is never evicted). Eviction in the
        buffer tiers encodes a fresh spill record (factors MUTATE per
        sweep, unlike feature blocks — the record must capture the
        latest write); the redecode tier drops the table outright."""
        budget = self.hbm_budget_bytes
        if budget is None:
            return
        n = len(self._entries)
        cur = pinned if pinned >= 0 else 0
        if self.device_bytes <= budget:
            return
        resident = [e for e in self._entries
                    if e.gamma is not None and e.spec.index != pinned]
        resident.sort(key=lambda e: -((e.spec.index - cur) % n))
        while self.device_bytes > budget and resident:
            victim = resident.pop(0)
            # A victim with a live spill record was restored and never
            # rewritten (write() is the only place that clears spill),
            # so the record is still byte-identical — re-encoding would
            # pay a redundant device→host pull and double-count the
            # spill_bytes_written accounting.
            if self.spill_source == "buffer" and victim.spill is None:
                spill = encode_factor_spill(
                    np.asarray(victim.gamma), self.spill_dtype)
                victim.spill = spill
                self._stats["spill_bytes_written"] += spill.nbytes
                _M_SPILL_WRITTEN.inc(spill.nbytes)
            victim.gamma = None
            self.device_bytes -= victim.factor_bytes
            self._stats["evictions"] += 1
            _M_EVICTIONS.inc()
        _G_DEVICE_BYTES.set(self.device_bytes)
        _G_SPILL_HOST.set(self.spill_bytes_host)

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict:
        s = dict(self._stats)
        s.update({
            "shards": self.n_shards,
            "entities": self.plan.num_entities,
            "num_factors": self.k,
            "e_pad_buckets": sorted(self.e_pad_buckets()),
            "obs_bucket_histogram": {
                str(k): v
                for k, v in sorted(
                    self.plan.obs_bucket_histogram().items())},
            "hbm_budget_bytes": self.hbm_budget_bytes,
            "device_bytes": self.device_bytes,
            "peak_device_bytes": self.peak_device_bytes,
            "spill_dtype": self.spill_dtype,
            "spill_source": self.spill_source,
            "spill_bytes_host": self.spill_bytes_host,
            "devices": len(self.devices) if self.devices else None,
            "resident_shards": sum(1 for e in self._entries
                                   if e.gamma is not None),
        })
        return s
