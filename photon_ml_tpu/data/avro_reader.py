"""TrainingExampleAvro ingest: Avro files -> IndexMap + CSR + GameDataset.

Replaces the reference's AvroDataReader/GLMSuite Spark ingest
(ml/data/AvroDataReader.scala:53-436, ml/io/GLMSuite.scala:98-139): reads
records on the host, indexes (name, term) features, injects the intercept
column, and produces scipy CSR ready for device upload.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.io.avro_codec import read_container


def _avro_paths(path) -> List[Path]:
    """path: one file/dir, or a list of them (date-range resolution hands
    the readers a list of daily directories)."""
    if isinstance(path, (list, tuple)):
        out: List[Path] = []
        for p in path:
            out.extend(_avro_paths(p))
        return out
    p = Path(path)
    if p.is_dir():
        files = sorted(q for q in p.iterdir() if q.suffix == ".avro")
        if not files:
            raise FileNotFoundError(f"no .avro files under {p}")
        return files
    return [p]


def iter_records(path) -> Iterator[dict]:
    for f in _avro_paths(path):
        yield from read_container(f)


def _record_label(rec: dict) -> float:
    """Label under either field-name set: 'label' (TrainingExampleFieldNames)
    or 'response' (ResponsePredictionFieldNames) — the reference's two Avro
    input formats (ml/avro/TrainingExampleFieldNames.scala,
    ResponsePredictionFieldNames.scala, io/FieldNamesType.scala:22).
    Auto-detected per record instead of a --format-type flag."""
    v = rec.get("label")
    if v is None:
        v = rec.get("response")
    if v is None:
        raise ValueError(
            "record has neither a 'label' nor a 'response' field")
    return float(v)


def _record_features(rec: dict) -> Iterable[dict]:
    """Feature list, tolerating union-null arrays/entries (Pig-generated
    schemas wrap everything in [null, X] — e.g. the reference's
    poisson_test.avro fixture)."""
    return (f for f in (rec.get("features") or ()) if f is not None)


def _reject_duplicate_features(mat: sp.csr_matrix, index_map: IndexMap,
                               uids: Sequence, shard: str = "") -> None:
    """Hard-reject records carrying the same (name, term) feature twice,
    then canonicalize the matrix (sum_duplicates).

    Mirrors the reference's AvroDataReader validation
    (ml/data/AvroDataReader.scala:306-311: `require(duplicateFeatures
    .isEmpty, ...)`): the same input must produce the same error, not a
    silently different model (summing duplicates changes the fit).

    Detection is nearly free on the clean path: duplicates exist iff
    sum_duplicates shrinks nnz (the pre-call structure must be COPIED —
    sum_duplicates compacts indices/indptr in place). The O(nnz log nnz)
    labeling lexsort runs only on the terminal error path.
    """
    raw_indices = mat.indices.copy()
    raw_indptr = mat.indptr.copy()
    nnz_before = mat.nnz
    mat.sum_duplicates()
    if mat.nnz == nnz_before:
        return
    row_ids = np.repeat(np.arange(len(raw_indptr) - 1),
                        np.diff(raw_indptr))
    order = np.lexsort((raw_indices, row_ids))
    r = row_ids[order]
    c = raw_indices[order]
    dup = (r[1:] == r[:-1]) & (c[1:] == c[:-1])
    hits = np.nonzero(dup)[0][:5]
    details = []
    for i in hits:
        row, col = int(r[i]), int(c[i])
        uid = uids[row] if uids is not None and row < len(uids) else None
        details.append(
            f"row {row}" + (f" (uid {uid!r})" if uid else "")
            + f": feature {index_map.get_feature_name(col)!r}")
    where = f" in feature shard {shard!r}" if shard else ""
    raise ValueError(
        f"duplicate (name, term) features detected{where} — the reference "
        "rejects such records (AvroDataReader.scala:306-311): "
        + "; ".join(details))


def build_index_map(path, add_intercept: bool = True,
                    selected_features: Optional[set] = None,
                    ingest_workers=None) -> IndexMap:
    """Scan pass collecting distinct (name, term) keys — the analog of
    DefaultIndexMap generation / FeatureIndexingJob. ``selected_features``
    restricts the map to a whitelist of keys (the reference's
    createDefaultIndexMapLoader(avroRDD, selectedFeatures)).
    ``ingest_workers``: see read_labeled_points."""
    from photon_ml_tpu.data.fast_ingest import fast_ingest

    fast = fast_ingest(_avro_paths(path), {}, {}, collect_keys=True,
                       workers=ingest_workers)
    if fast is not None:
        keys = fast.collected_keys
        if selected_features is not None:
            keys &= selected_features
        return IndexMap.from_keys(keys, add_intercept=add_intercept)

    keys = set()
    for rec in iter_records(path):
        for f in _record_features(rec):
            key = feature_key(f["name"], f.get("term") or "")
            if selected_features is None or key in selected_features:
                keys.add(key)
    return IndexMap.from_keys(keys, add_intercept=add_intercept)


def read_labeled_points(
    path,
    index_map: Optional[IndexMap] = None,
    add_intercept: bool = True,
    selected_features: Optional[set] = None,
    ingest_workers=None,
) -> Tuple[sp.csr_matrix, np.ndarray, np.ndarray, np.ndarray,
           List[Optional[str]], IndexMap]:
    """Returns (features, labels, offsets, weights, uids, index_map).

    Unknown features (absent from a supplied index map) are dropped, like
    the reference's ingest. ``selected_features`` (keys) restricts columns
    (GLMSuite selected-features filtering).

    ``ingest_workers``: "auto"/None picks a worker count from the usable
    cores; >= 2 decodes file shards in a process pool with byte-identical
    output (data/parallel_ingest.py); 1 forces single-process decode.
    """
    if index_map is None:
        index_map = build_index_map(path, add_intercept=add_intercept,
                                    selected_features=selected_features,
                                    ingest_workers=ingest_workers)
    intercept_idx = index_map.intercept_index if add_intercept else -1

    from photon_ml_tpu.data.fast_ingest import fast_ingest

    fast = fast_ingest(
        _avro_paths(path), {"m": index_map}, {"m": intercept_idx},
        restrict_keys=selected_features, workers=ingest_workers)
    if fast is not None:
        data_, idx_, indptr_ = fast.shards["m"]
        mat = sp.csr_matrix((data_, idx_, indptr_),
                            shape=(len(fast.labels), len(index_map)))
        _reject_duplicate_features(mat, index_map, fast.uids)
        return (mat, fast.labels, fast.offsets, fast.weights, fast.uids,
                index_map)

    labels, offsets, weights, uids = [], [], [], []
    data, indices, indptr = [], [], [0]
    for rec in iter_records(path):
        labels.append(_record_label(rec))
        offsets.append(float(rec.get("offset") or 0.0))
        w = rec.get("weight")
        weights.append(1.0 if w is None else float(w))
        uids.append(rec.get("uid"))
        for f in _record_features(rec):
            key = feature_key(f["name"], f.get("term") or "")
            if selected_features is not None and key not in selected_features:
                continue
            idx = index_map.get_index(key)
            if idx >= 0:
                indices.append(idx)
                data.append(float(f["value"]))
        if intercept_idx >= 0:
            indices.append(intercept_idx)
            data.append(1.0)
        indptr.append(len(indices))

    n, d = len(labels), len(index_map)
    mat = sp.csr_matrix(
        (np.asarray(data), np.asarray(indices, np.int64),
         np.asarray(indptr, np.int64)), shape=(n, d))
    _reject_duplicate_features(mat, index_map, uids)
    return (mat, np.asarray(labels), np.asarray(offsets),
            np.asarray(weights), uids, index_map)


class _GameBatchBuilder:
    """Per-record GAME decode state — the ONE copy of the python-path
    record semantics (label/offset/weight/uid, metadataMap id extraction,
    per-shard feature + intercept append, duplicate-feature rejection at
    build), shared by ``read_game_dataset``'s fallback loop and
    ``iter_game_dataset_batches``."""

    def __init__(self, feature_shard_maps: Dict[str, IndexMap],
                 id_types: Sequence[str], add_intercept: bool):
        self._maps = feature_shard_maps
        self._id_types = id_types
        self._add_intercept = add_intercept
        self._builders = {s: {"data": [], "indices": [], "indptr": [0]}
                          for s in feature_shard_maps}
        self._labels: list = []
        self._offsets: list = []
        self._weights: list = []
        self._uids: list = []
        self._ids: Dict[str, list] = {t: [] for t in id_types}
        # Per-record work hoisted out of append(): one
        # (key->index dict .get, intercept index, column lists) tuple per
        # shard, so the hot loop is dict lookups + list appends on locals
        # — no method dispatch, no dict-of-dicts traversal per record.
        self._shard_ops = []
        for s, imap in feature_shard_maps.items():
            b = self._builders[s]
            self._shard_ops.append(
                (imap.key_to_index_dict().get,
                 imap.intercept_index if add_intercept else -1,
                 b["data"], b["indices"], b["indptr"]))
        self._id_ops = [(t, self._ids[t]) for t in id_types]

    def __len__(self) -> int:
        return len(self._labels)

    def append(self, rec: dict) -> None:
        self._labels.append(_record_label(rec))
        self._offsets.append(float(rec.get("offset") or 0.0))
        w = rec.get("weight")
        self._weights.append(1.0 if w is None else float(w))
        self._uids.append(rec.get("uid"))
        metadata = rec.get("metadataMap") or {}
        for t, lst in self._id_ops:
            v = metadata.get(t)
            if v is None:
                raise ValueError(
                    f"record is missing id type {t!r} in metadataMap")
            lst.append(str(v))
        # Feature keys are built ONCE per record, not once per shard.
        feats = [(feature_key(f["name"], f.get("term") or ""), f["value"])
                 for f in _record_features(rec)]
        for get_index, intercept_idx, data, indices, indptr in \
                self._shard_ops:
            for key, value in feats:
                idx = get_index(key, -1)
                if idx >= 0:
                    indices.append(idx)
                    data.append(float(value))
            if intercept_idx >= 0:
                indices.append(intercept_idx)
                data.append(1.0)
            indptr.append(len(indices))

    def build(self) -> GameDataset:
        n = len(self._labels)
        shards = {}
        for shard, imap in self._maps.items():
            b = self._builders[shard]
            m = sp.csr_matrix(
                (np.asarray(b["data"]),
                 np.asarray(b["indices"], np.int64),
                 np.asarray(b["indptr"], np.int64)), shape=(n, len(imap)))
            _reject_duplicate_features(m, imap, self._uids, shard)
            shards[shard] = m
        return GameDataset.build(
            responses=np.asarray(self._labels),
            feature_shards=shards,
            ids={t: np.asarray(v) for t, v in self._ids.items()},
            offsets=np.asarray(self._offsets),
            weights=np.asarray(self._weights),
            uids=np.asarray([u if u is not None else ""
                             for u in self._uids]),
        )


def iter_game_dataset_batches(
    path,
    id_types: Sequence[str],
    feature_shard_maps: Dict[str, IndexMap],
    batch_rows: int,
    add_intercept: bool = True,
    feeder: str = "auto",
    prefetch_depth: int = 0,
) -> Iterator[GameDataset]:
    """Streaming GAME ingest: yield GameDatasets of <= ``batch_rows`` rows.

    The bounded-memory feeder for the serving engine's scoring stream
    (cli/game_scoring_driver --stream): only O(batch_rows +
    prefetch_depth * batch_rows) rows are ever resident on the host, so
    arbitrarily large Avro inputs score in bounded memory. Decoding runs
    block-streamed through the native C decoder when available
    (data/block_stream.py — `shard_planner` block index + per-block
    `decode_training_block`), with a byte-identical pure-python fallback
    (the shared ``_GameBatchBuilder`` row loop — same duplicate-feature
    rejection, same metadataMap id extraction). Each batch's entity
    vocabularies are batch-local — consumers joining against a model
    vocabulary must map through entity NAMES, which is exactly what the
    serving engine does.

    ``feeder``: "auto" | "native" | "python"; ``prefetch_depth`` > 0
    decodes ahead on a background thread (see
    block_stream.BlockGameStream for the exact residency bound).
    """
    from photon_ml_tpu.data.block_stream import BlockGameStream

    yield from BlockGameStream(
        path, id_types=id_types, feature_shard_maps=feature_shard_maps,
        batch_rows=batch_rows, add_intercept=add_intercept,
        feeder=feeder, prefetch_depth=prefetch_depth)


def read_game_dataset(
    path,
    id_types: Sequence[str],
    feature_shard_maps: Optional[Dict[str, IndexMap]] = None,
    add_intercept: bool = True,
    default_shard: str = "global",
    ingest_workers=None,
) -> Tuple[GameDataset, Dict[str, IndexMap]]:
    """GAME ingest: one feature shard (default: all features) + entity id
    columns pulled from each record's metadataMap (falling back to uid).

    The reference's richer feature-bag/shard configuration
    (GameDriver.prepareFeatureMaps) maps onto ``feature_shard_maps``:
    shard id -> IndexMap restricted to that shard's features.

    ``ingest_workers``: see read_labeled_points — "auto"/None, or a worker
    count; parallel decode is byte-identical to single-process.
    """
    if feature_shard_maps is None:
        feature_shard_maps = {
            default_shard: build_index_map(path, add_intercept=add_intercept,
                                           ingest_workers=ingest_workers)}

    from photon_ml_tpu.data.parallel_ingest import resolve_ingest_workers

    if resolve_ingest_workers(ingest_workers) <= 1:
        # Single-process reads go through the C BLOCK decoder (the ~3x
        # faster path streamed scoring/training already use — ONE decode
        # implementation), byte-identical by the block-stream contract.
        # Multi-worker requests keep the parallel sharded pipeline.
        from photon_ml_tpu.data.block_stream import (
            read_game_dataset_via_blocks,
        )

        block_ds = read_game_dataset_via_blocks(
            path, id_types, feature_shard_maps, add_intercept)
        if block_ds is not None:
            return block_ds, feature_shard_maps

    from photon_ml_tpu.data.fast_ingest import fast_ingest

    fast = fast_ingest(
        _avro_paths(path), feature_shard_maps,
        {s: (m.intercept_index if add_intercept else -1)
         for s, m in feature_shard_maps.items()},
        id_types=id_types, workers=ingest_workers)
    if fast is not None:
        n = len(fast.labels)
        shards = {}
        for shard, imap in feature_shard_maps.items():
            data_, idx_, indptr_ = fast.shards[shard]
            m = sp.csr_matrix((data_, idx_, indptr_),
                              shape=(n, len(imap)))
            _reject_duplicate_features(m, imap, fast.uids, shard)
            shards[shard] = m
        data = GameDataset.build(
            responses=fast.labels,
            feature_shards=shards,
            ids=fast.ids,
            offsets=fast.offsets,
            weights=fast.weights,
            uids=np.asarray([u if u is not None else ""
                             for u in fast.uids]),
        )
        return data, feature_shard_maps

    batch = _GameBatchBuilder(feature_shard_maps, id_types, add_intercept)
    for rec in iter_records(path):
        batch.append(rec)
    return batch.build(), feature_shard_maps
