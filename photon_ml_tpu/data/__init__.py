"""Data structures and host-side ingest."""
