"""Data structures and host-side ingest.

Parallel ingest entry points (shard planning, the multi-process decoder
pool, and the chunked device feeder) live in shard_planner.py,
parallel_ingest.py and device_feed.py; `avro_reader.read_game_dataset` /
`read_labeled_points` thread an ``ingest_workers`` knob down to them.
"""
