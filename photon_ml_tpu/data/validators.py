"""Input sanity checks (reference: ml/data/DataValidators.scala:1-140).

VALIDATE_FULL checks every row; VALIDATE_SAMPLE checks a deterministic ~10%
subsample; VALIDATE_DISABLED skips. Raises ValueError listing every failed
check (the reference aggregates failures the same way before aborting).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.types import DataValidationType, TaskType


def validate_data(
    task: TaskType,
    features: sp.spmatrix | np.ndarray,
    labels: np.ndarray,
    offsets: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
    validation_type: DataValidationType = DataValidationType.VALIDATE_FULL,
) -> None:
    if validation_type == DataValidationType.VALIDATE_DISABLED:
        return
    n = len(labels)
    if validation_type == DataValidationType.VALIDATE_SAMPLE:
        rows = np.arange(0, n, 10)
    else:
        rows = np.arange(n)

    y = np.asarray(labels)[rows]
    errors: List[str] = []

    if not np.all(np.isfinite(y)):
        errors.append("labels contain non-finite values")
    if task == TaskType.LOGISTIC_REGRESSION or \
            task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        if not np.all(np.isin(y[np.isfinite(y)], (0.0, 1.0))):
            errors.append(f"{task.value} requires binary 0/1 labels")
    if task == TaskType.POISSON_REGRESSION:
        if np.any(y[np.isfinite(y)] < 0):
            errors.append("POISSON_REGRESSION requires non-negative labels")

    f = features[rows] if sp.issparse(features) else \
        np.asarray(features)[rows]
    fdata = f.data if sp.issparse(f) else f
    if not np.all(np.isfinite(fdata)):
        errors.append("features contain non-finite values")

    if offsets is not None and not np.all(
            np.isfinite(np.asarray(offsets)[rows])):
        errors.append("offsets contain non-finite values")
    if weights is not None:
        w = np.asarray(weights)[rows]
        if not np.all(np.isfinite(w)) or np.any(w < 0):
            errors.append("weights must be finite and non-negative")

    if errors:
        raise ValueError("input validation failed: " + "; ".join(errors))
