"""Samplers: per-entity reservoir caps (host, at ingest) and down-samplers
(device, inside the training loop).

Reference counterparts:
- reservoir cap with survivor reweighting:
  ml/data/RandomEffectDataSet.scala:254-317 + MinHeapWithFixedCapacity.scala
- DefaultDownSampler / BinaryClassificationDownSampler:
  ml/sampler/*.scala, applied in
  ml/optimization/DistributedOptimizationProblem.scala:112-121

On TPU the down-samplers do not drop rows (that would change array shapes):
they draw an on-device Bernoulli mask and fold it into the weight vector,
rescaling survivors by 1/rate so the objective stays unbiased — weight-0 rows
are provably inert in the fused objective (see ops/glm_objective.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def reservoir_sample(
    rng: np.random.Generator, n: int, cap: int
) -> tuple[np.ndarray, float]:
    """Pick `cap` of `n` rows uniformly; survivors' weights scale by n/cap.

    Returns (sorted selected indices, weight multiplier). Matches the
    reference's semantics (uniform subsample, aggregate weight preserved —
    RandomEffectDataSet.scala:299-310) without the streaming heap, which
    exists only because Spark combineByKey is a streaming fold.
    """
    if n <= cap:
        return np.arange(n), 1.0
    idx = rng.choice(n, size=cap, replace=False)
    idx.sort()
    return idx, n / cap


def default_down_sampler(
    key: Array, weights: Array, rate: float
) -> Array:
    """Keep each row with prob `rate`, rescale kept weights by 1/rate
    (ml/sampler/DefaultDownSampler.scala:27-45)."""
    mask = jax.random.bernoulli(key, rate, weights.shape)
    return jnp.where(mask, weights / rate, 0.0)


def binary_classification_down_sampler(
    key: Array, labels: Array, weights: Array, rate: float
) -> Array:
    """Down-sample negatives only, rescaling their weights
    (ml/sampler/BinaryClassificationDownSampler.scala:32-60)."""
    mask = jax.random.bernoulli(key, rate, weights.shape)
    is_neg = labels < 0.5
    neg_w = jnp.where(mask, weights / rate, 0.0)
    return jnp.where(is_neg, neg_w, weights)


def down_sample_weights(
    key: Array, labels: Array, weights: Array, rate: float,
    is_classification: bool,
) -> Array:
    """Dispatch matching DownSampler selection in the reference
    (ml/optimization/DistributedOptimizationProblem.scala:165-176)."""
    if rate >= 1.0:
        return weights
    if is_classification:
        return binary_classification_down_sampler(key, labels, weights, rate)
    return default_down_sampler(key, weights, rate)
