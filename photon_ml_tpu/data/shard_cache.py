"""Device-resident shard cache for out-of-core streaming TRAINING.

PRs 1 and 4 built a C-block-decoding, prefetched, chunked-H2D streaming
pipeline that only SCORING used; training still one-shot-materialized the
whole dataset on host and device (`read_game_dataset` ->
`fixed_effect_batch`), capping trainable dataset size at host RAM. This
module is the training-side consumer of that pipeline
(Snap ML's pipelined chunk streaming with a device-resident working set,
PAPERS.md): a `BlockGameStream` is consumed ONCE, batch by batch, and its
rows land on device in one of two regimes —

- **exact assembly** (`assemble_fixed_effect_batch`): each batch's CSR
  slice uploads as it decodes (host residency stays O(batch_rows)) and
  the device pieces concatenate into arrays BITWISE-identical to what
  `GameDataset.fixed_effect_batch` builds from a one-shot read (CSR cuts
  are row-contiguous, so values/col_ids/row_ids are literal slices of the
  one-shot arrays; casts are elementwise). The untouched fused
  `lax.while_loop` solvers then run on the assembled batch, so
  `--stream-train` writes a byte-identical model to the one-shot driver
  while never holding more than a batch of rows on host.

- **shard cache** (`DeviceShardCache`): each batch becomes a PADDED
  static-shape `CSRFeatures` block (rows and nnz quantized by the
  serving `BucketLadder`, so per-bucket jitted accumulate executables in
  ops/sharded_objective.py stay enumerable) kept in HBM, with row-space
  columns (labels/offsets/weights) ALWAYS resident and an explicit
  `hbm_budget_bytes` that spills FEATURE blocks to host column buffers
  (replay-aware furthest-next-use eviction, not plain LRU — see
  `DeviceShardCache`). Solver iterations after the first replay cached
  device blocks instead of re-decoding Avro; spilled blocks re-upload
  through `HostPrefetcher` + `chunked_device_put` so H2D of shard k+1
  overlaps the accumulate of shard k (the same three-stage pipeline
  shape as streamed scoring).

The spill tier itself has two knobs (Snap ML's hierarchical memory
tiers, PAPERS.md — compressed / recomputed lower tiers are what make
trainable size disk-bounded):

- ``spill_dtype`` — what the host spill buffers hold. ``"f32"``
  (default) keeps the PR-5 raw padded f32/i32/i32 triplet: re-uploads
  are literally the evicted bytes, so every bitwise replay guarantee
  holds unchanged. ``"bf16"`` spills values as bfloat16 and indices
  DELTA-ENCODED to u8/u16 (`encode_spill`: column ids re-based per row,
  row ids as non-negative diffs; either stream falls back to raw i32
  when a delta overflows or is negative), cutting spill bytes AND
  per-epoch H2D re-upload traffic to ~1/3-1/2 of f32. Restore
  (`restore_spilled_features`) decodes ON DEVICE — upload is the
  compact encoding; a per-bucket jitted kernel widens bf16 -> f32 and
  un-deltas the indices — so the `CSRFeatures` handed to the sharded
  objective is f32/i32 exactly as before: the accumulate kernels'
  dtype contract is untouched (index bits are EXACTLY the evicted
  ones; values round-trip through bf16 with documented parity bounds,
  docs/SCALE.md §Training memory envelope). Values are quantized ONCE
  AT INGEST — never-evicted blocks take the same bf16 round trip — so
  a bf16 replay is deterministic and residency-independent just like
  f32; only the value PRECISION differs from the f32-spill model.
- ``spill_source`` — where evicted blocks come back from.
  ``"buffer"`` (default) re-uploads host spill buffers (host RAM stays
  O(dataset) — f32 or ~1/3 of that for bf16). ``"redecode"`` keeps NO
  host copy: evicted blocks are dropped and a cache miss re-decodes
  the Avro container blocks that produced the batch through a
  `BlockRandomAccess` (data/block_stream.py) row-range fetch — host
  memory falls to O(budget + one block) and trainable dataset size is
  bounded only by disk. The re-decoded batch is byte-identical to the
  ingest-time batch (the block cut is deterministic), so the padded
  triplet — and every partial — is bit-for-bit the resident replay.
  Misses run inside the `blocks()` prefetch thread, so the re-decode
  of shard k+1 overlaps the accumulate of shard k.

With ``devices`` (a 1-D mesh's device list, ``--mesh-devices``), blocks
place ROUND-ROBIN over the devices — block i is committed to
``devices[i % D]``, spill re-uploads return to the same device, and
``hbm_budget_bytes`` becomes PER DEVICE (each device's resident feature
bytes stay within the budget; total residency scales to D x budget).
The block -> device assignment is a pure function of the block index,
so the fixed shard order — and with it the fold's numeric contract —
is untouched by placement (ops/sharded_objective.py combines partials
in shard order regardless of which device computed them). A single
device (or ``devices=None``) is EXACTLY the PR-5 single-pool cache,
bit for bit.

With ``col_blocks=C`` (> 1) the cache keys feature blocks by
(row-shard, column-block) for a 2-D ``(data, model)`` mesh
(``--mesh-shape RxC``): each streamed batch's CSR matrix is cut into C
contiguous column blocks of ``ceil(d / C)`` columns
(`parallel.distributed.split_csr_columns` — scipy's canonical column
slice, so each block's nnz stream is an order-preserving subsequence
of the full stream), each block padded to its OWN nnz bucket with
LOCAL column ids, spilled/restored through its OWN SpillBlock, and
placed on device slot ``(i % R) * C + c`` of the flat row-major
``devices`` list (R = len(devices) / C). Row-space columns live once
per shard on the row's HOME device ``grid[i % R][C-1]`` — the last
column block's device, where the 2-D objective's margin chain ends.
``hbm_budget_bytes`` still binds PER device slot and the Belady rule
is per-(row, col)-slot: a slot's resident column slices are an
index-arithmetic subsequence of the shard order (slices in slot s all
have index = s // C mod R), so the global cyclic distance ranks them
exactly as the slot's own replay cycle does — same argument as the
1-D round-robin. ``col_blocks=1`` is EXACTLY the 1-D cache, bit for
bit.

The reference's analog is treeAggregate over cached RDD partitions
(`ValueAndGradientAggregator.scala:243-274`): no node ever holds the whole
dataset, partials combine in a fixed deterministic order.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, Iterator, List, Optional

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.data.device_feed import HostPrefetcher, chunked_device_put
from photon_ml_tpu.telemetry import span
from photon_ml_tpu.ops.features import (
    CSRFeatures,
    DENSE_DENSITY_THRESHOLD,
    padded_csr_arrays,
)
from photon_ml_tpu.serving.buckets import BucketLadder, next_pow2

# Registry mirrors of the per-instance ``_stats`` (no-ops while
# telemetry is off); names are part of the metrics.json snapshot schema
# (docs/OBSERVABILITY.md).
_M_HITS = telemetry.counter("data.shard_cache.hits")
_M_MISSES = telemetry.counter("data.shard_cache.misses")
_M_EVICTIONS = telemetry.counter("data.shard_cache.evictions")
_M_REUPLOAD_BYTES = telemetry.counter("data.shard_cache.bytes_reuploaded")
_M_SPILL_WRITTEN = telemetry.counter("data.shard_cache.spill_bytes_written")
_M_REDECODE_BYTES = telemetry.counter("data.shard_cache.bytes_redecoded")
_M_EPOCHS = telemetry.counter("data.shard_cache.epochs")
_G_DEVICE_BYTES = telemetry.gauge("data.shard_cache.device_bytes")
_G_PEAK_BYTES = telemetry.gauge("data.shard_cache.peak_device_bytes")
# Host-side spill residency: the O(dataset) cost that device_bytes/peak
# never showed (metrics.json twin: stream_train.cache.spill_bytes_host).
_G_SPILL_HOST = telemetry.gauge("data.shard_cache.spill_bytes_host")

SPILL_DTYPES = ("f32", "bf16")
SPILL_SOURCES = ("buffer", "redecode")


def _row_ids_i32(indptr: np.ndarray, offset: int = 0) -> np.ndarray:
    n = len(indptr) - 1
    return (np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
            + offset).astype(np.int32)


# ---------------------------------------------------------------------------
# Spill codecs: compressed host buffers + on-device restore to f32/i32
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SpillBlock:
    """Host spill record of one evicted feature block.

    All three arrays are PADDED to ``nnz_bucket`` (pad entries are
    zeros), so restore H2D transfers keep the static bucket shape the
    jitted decode kernel compiles for. Encodings per ``dtype_tag``:

    - ``"f32"``: the raw PR-5 triplet — ``enc_values`` f32,
      ``enc_cols``/``enc_rows`` i32. Restore re-uploads them verbatim
      (bitwise the evicted bytes).
    - ``"bf16"``: ``enc_values`` bfloat16 (round-to-nearest-even of the
      f32 values); ``enc_cols`` u8/u16 per-row delta codes (absolute
      column at each row start, positive within-row diffs after — CSR
      canonicalization guarantees sorted, duplicate-free columns);
      ``enc_rows`` u8/u16 non-negative diffs of the non-decreasing row
      ids. Either index stream independently falls back to raw i32
      when a delta overflows its widest unsigned code (or a
      non-canonical input produces a negative delta).

    The ``enc_*`` fields are ONLY consumed by
    :func:`restore_spilled_features` — anywhere else they would leak
    bf16/delta-encoded data into device kernels (enforced by the
    jaxlint ``spill-dtype-leak`` rule, docs/ANALYSIS.md).
    """

    nnz: int  # true entries; [nnz, nnz_bucket) is padding
    enc_values: np.ndarray
    enc_cols: np.ndarray
    enc_rows: np.ndarray
    dtype_tag: str  # "f32" | "bf16"

    @property
    def nbytes(self) -> int:
        return (self.enc_values.nbytes + self.enc_cols.nbytes
                + self.enc_rows.nbytes)


def _shrink_deltas(deltas: np.ndarray, raw: np.ndarray,
                   pad_to: int) -> np.ndarray:
    """Pick the narrowest unsigned code that holds every delta; when a
    delta is negative or exceeds u16, fall back to the RAW i32 ids
    (decode then skips the cumulative reconstruction entirely)."""
    lo = int(deltas.min()) if len(deltas) else 0
    hi = int(deltas.max()) if len(deltas) else 0
    if lo < 0 or hi > np.iinfo(np.uint16).max:
        out = np.zeros(pad_to, np.int32)
        out[:len(raw)] = raw
        return out
    code = np.uint8 if hi <= np.iinfo(np.uint8).max else np.uint16
    out = np.zeros(pad_to, code)
    out[:len(deltas)] = deltas
    return out


def encode_spill(values: np.ndarray, cols: np.ndarray, rows: np.ndarray,
                 nnz: int, spill_dtype: str) -> SpillBlock:
    """Padded f32/i32/i32 triplet -> host spill record (see SpillBlock).

    ``values/cols/rows`` are the padded ingest arrays
    (`padded_csr_arrays`); ``nnz`` is the true entry count. The f32 tag
    stores them as-is (zero-copy — today's spill, bit for bit)."""
    if spill_dtype not in SPILL_DTYPES:
        raise ValueError(
            f"spill_dtype must be one of {SPILL_DTYPES}, got "
            f"{spill_dtype!r}")
    if spill_dtype == "f32":
        return SpillBlock(nnz=nnz, enc_values=values, enc_cols=cols,
                          enc_rows=rows, dtype_tag="f32")
    import ml_dtypes

    pad_to = len(values)
    ev = np.zeros(pad_to, ml_dtypes.bfloat16)
    ev[:nnz] = values[:nnz].astype(ml_dtypes.bfloat16)
    c = cols[:nnz].astype(np.int64)
    r = rows[:nnz].astype(np.int64)
    cd = c.copy()
    cd[1:] -= c[:-1]
    if nnz:
        # Absolute column at each row start (the first entry is one).
        starts = np.empty(nnz, bool)
        starts[0] = True
        starts[1:] = r[1:] != r[:-1]
        cd[starts] = c[starts]
    rd = r.copy()
    rd[1:] -= r[:-1]
    return SpillBlock(
        nnz=nnz, enc_values=ev,
        enc_cols=_shrink_deltas(cd, cols[:nnz], pad_to),
        enc_rows=_shrink_deltas(rd, rows[:nnz], pad_to),
        dtype_tag="bf16")


def _decode_spill_impl(values, col_enc, row_enc, nnz):
    """Device-side spill decode: widen values to f32, un-delta the
    index streams, zero the pad tail. Traced per (nnz_bucket, encoding
    dtypes); ``nnz`` is a TRACED i32 scalar, so varying true nnz never
    recompiles. Raw-i32 fallback streams skip reconstruction (the
    dtype is part of the trace signature, so the branch is static)."""
    import jax.numpy as jnp
    from jax import lax

    n = values.shape[0]
    pos = lax.iota(jnp.int32, n)
    live = pos < nnz
    vals = jnp.where(live, values.astype(jnp.float32),
                     jnp.zeros((), jnp.float32))
    if row_enc.dtype == jnp.int32:
        rows = row_enc
    else:
        rows = jnp.cumsum(row_enc.astype(jnp.int32))
    if col_enc.dtype == jnp.int32:
        cols = col_enc
    else:
        d = col_enc.astype(jnp.int32)
        cum = jnp.cumsum(d)
        start = jnp.concatenate(
            [jnp.ones((1,), bool), rows[1:] != rows[:-1]])
        base = cum - d  # prefix sum before each element
        # Bases at row starts are non-decreasing (deltas >= 0), so a
        # running max propagates each segment's re-base forward.
        corr = lax.cummax(jnp.where(start, base, 0))
        cols = cum - corr
    zero = jnp.zeros((), jnp.int32)
    return (vals, jnp.where(live, cols, zero).astype(jnp.int32),
            jnp.where(live, rows, zero).astype(jnp.int32))


@functools.lru_cache(maxsize=1)
def _decode_spill_jit():
    """One process-wide jitted decode (built on first spill restore so
    importing this module never imports jax); the jit cache keys on
    (nnz_bucket, encoding dtypes) — true nnz is a traced argument."""
    import jax

    return jax.jit(_decode_spill_impl)


def restore_spilled_features(spill: SpillBlock, rows_bucket: int,
                             n_features: int, device) -> CSRFeatures:
    """The ONE blessed spill -> device path: re-upload (compact bytes on
    the wire) and restore to the f32/i32 `CSRFeatures` the sharded
    objective's kernels were compiled for. f32 spill re-uploads the
    evicted bytes verbatim; bf16 spill uploads the encodings and
    decodes on device (`_decode_spill_impl`)."""
    import jax
    import jax.numpy as jnp

    def idx(x):
        return (jnp.asarray(x) if device is None
                else jax.device_put(x, device))

    if spill.dtype_tag == "f32":
        return CSRFeatures(
            chunked_device_put(spill.enc_values, device=device),
            idx(spill.enc_cols), idx(spill.enc_rows),
            rows_bucket, n_features)
    vals, cols, rows = _decode_spill_jit()(
        idx(spill.enc_values), idx(spill.enc_cols), idx(spill.enc_rows),
        idx(np.int32(spill.nnz)))
    return CSRFeatures(vals, cols, rows, rows_bucket, n_features)


# ---------------------------------------------------------------------------
# Exact assembly: streamed ingest -> the one-shot device batch, bit for bit
# ---------------------------------------------------------------------------


class StreamedFixedEffectData:
    """Duck-typed stand-in for the GameDataset a FixedEffectCoordinate
    consumes: the feature batch is already device-assembled from a
    stream, so `fixed_effect_batch` hands it back instead of re-uploading
    host CSR. Exposes exactly the surface the fixed-effect training path
    touches (`num_rows`, `feature_shards[...].shape`,
    `responses`/`offsets`/`weights` for the coordinate-descent objective
    rows, `fixed_effect_batch`)."""

    class _ShapeOnly:
        def __init__(self, shape):
            self.shape = shape

    def __init__(self, shard_id: str, batch, n_rows: int, d: int,
                 ingest_stats: dict):
        self._shard_id = shard_id
        self._batch = batch
        self._n_rows = int(n_rows)
        self.feature_shards = {shard_id: self._ShapeOnly((n_rows, d))}
        # Device f32 columns: jnp.asarray(col, dtype) in the consumer is a
        # no-op cast, value-identical to the one-shot host-f64 -> f32 cast.
        self.responses = batch.labels
        self.offsets = batch.offsets
        self.weights = batch.weights
        self.ingest_stats = dict(ingest_stats)

    @property
    def num_rows(self) -> int:
        return self._n_rows

    def fixed_effect_batch(self, shard_id: str, dtype=None,
                           extra_offsets=None):
        from photon_ml_tpu.ops.glm_objective import GLMBatch

        if shard_id != self._shard_id:
            raise KeyError(
                f"streamed ingest assembled shard {self._shard_id!r}, "
                f"coordinate asked for {shard_id!r}")
        if dtype is not None and np.dtype(dtype) != np.dtype(
                np.asarray(self._batch.labels).dtype):
            raise ValueError(
                f"streamed batch was assembled as "
                f"{np.asarray(self._batch.labels).dtype}, asked for {dtype}")
        if extra_offsets is None:
            return self._batch
        return GLMBatch(self._batch.features, self._batch.labels,
                        self._batch.offsets + extra_offsets,
                        self._batch.weights)


def assemble_fixed_effect_batch(
    stream, shard_id: str, dtype=np.float32,
    dense_threshold: float = DENSE_DENSITY_THRESHOLD,
) -> StreamedFixedEffectData:
    """Consume a BlockGameStream into ONE device GLMBatch, bitwise equal
    to `read_game_dataset(...)[0].fixed_effect_batch(shard_id, dtype)`.

    Host residency is O(batch_rows): each decoded batch's arrays upload
    (async) and are dropped before the next batch decodes. Device pieces
    are exact slices of the one-shot arrays (row-contiguous CSR cuts +
    the same elementwise f64->f32 / int->i32 casts), so the final
    device-side concatenation reconstructs the one-shot upload exactly —
    including the dense-vs-CSR layout decision, which is made from the
    GLOBAL density after the stream ends, exactly like
    `features_to_device` on the full matrix."""
    import jax.numpy as jnp

    from photon_ml_tpu.ops.glm_objective import GLMBatch

    vals_p, cols_p, rows_p = [], [], []
    lab_p, off_p, wgt_p = [], [], []
    n_rows = 0
    nnz = 0
    d = None
    for ds in stream:
        mat = ds.feature_shards[shard_id].tocsr()
        d = mat.shape[1]
        if ds.num_rows == 0:
            continue
        # Exact one-shot pieces: csr_from_scipy's COO row-stable sort is
        # the identity on a canonical CSR, so data/indices ARE the slices.
        vals_p.append(chunked_device_put(mat.data, dtype))
        cols_p.append(jnp.asarray(mat.indices.astype(np.int32)))
        rows_p.append(jnp.asarray(_row_ids_i32(mat.indptr, n_rows)))
        lab_p.append(chunked_device_put(ds.responses, dtype))
        off_p.append(chunked_device_put(ds.offsets, dtype))
        wgt_p.append(chunked_device_put(ds.weights, dtype))
        n_rows += ds.num_rows
        nnz += mat.nnz
    if n_rows == 0:
        raise ValueError("stream yielded no rows to assemble")

    def cat(parts):
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    values, col_ids, row_ids = cat(vals_p), cat(cols_p), cat(rows_p)
    feats = CSRFeatures(values, col_ids, row_ids, n_rows, int(d))
    density = nnz / max(1, n_rows * d)
    if density >= dense_threshold:
        # One-shot path densifies before upload; scattering the exact CSR
        # pieces into zeros reproduces the same array (no duplicates, and
        # the f64->f32 value cast already happened elementwise at upload).
        feats = feats.to_dense()
    batch = GLMBatch(features=feats, labels=cat(lab_p), offsets=cat(off_p),
                     weights=cat(wgt_p))
    stats = dict(stream.stats())
    stats.update({"assembled_rows": n_rows, "assembled_nnz": nnz,
                  "density": density,
                  "layout": type(feats).__name__})
    return StreamedFixedEffectData(shard_id, batch, n_rows, int(d), stats)


# ---------------------------------------------------------------------------
# The shard cache: padded device blocks, replay-aware spill, prefetch
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ColumnSlice:
    """One (row-shard, column-block) feature unit of a ``col_blocks > 1``
    cache: the shard's nnz entries whose columns fall in
    ``[c*block_size, (c+1)*block_size)``, padded to the slice's OWN nnz
    bucket, with LOCAL column ids (``CSRFeatures.n_features ==
    block_size``). The slice — not the shard — is the unit of placement
    (device ``grid[i % R][c]``), eviction, and spill."""

    c: int  # column-block index
    nnz: int  # true entries (<= nnz_bucket)
    nnz_bucket: int
    spill: Optional[SpillBlock]  # host spill record; None = no host copy
    feats: Optional[CSRFeatures] = None  # None = spilled
    device: object = None
    slot: int = 0  # (index % R) * C + c

    @property
    def feature_bytes(self) -> int:
        return 12 * self.nnz_bucket

    @property
    def spill_bytes(self) -> int:
        return 0 if self.spill is None else self.spill.nbytes


@dataclasses.dataclass
class CachedShard:
    """One streamed batch as a static-shape device block.

    Row-space columns (labels/offsets/weights, padded to ``rows_bucket``
    with weight-0 rows) are ALWAYS device-resident — they are the cheap
    4-bytes-per-row part, and keeping them resident is what makes the
    margin-cached line search feature-pass-free. The FEATURE triplet
    (``feats``) is the evictable part; ``spill`` is the host record it
    restores from (None in the ``redecode`` tier, where a miss re-decodes
    the source Avro rows instead).

    With ``col_blocks > 1`` the feature triplet is split into per-column
    ``ColumnSlice`` units (``cols``; ``feats``/``spill`` stay None and
    ``nnz_bucket`` is unused) and ``device``/``slot`` are the row's HOME
    placement — the LAST column block's device, where labels/offsets/
    weights and the 2-D objective's row-space state live."""

    index: int
    n_rows: int  # true rows (<= rows_bucket)
    nnz: int  # true nnz (<= nnz_bucket)
    rows_bucket: int
    nnz_bucket: int
    row_offset: int  # first global row id
    labels: object  # device f[rows_bucket]
    offsets: object
    weights: object
    spill: Optional[SpillBlock]  # host spill record; None = no host copy
    feats: Optional[CSRFeatures] = None  # None = spilled
    device: object = None  # mesh placement; None = default device
    slot: int = 0  # mesh slot (index % n_devices); 0 without a mesh
    cols: Optional[List[ColumnSlice]] = None  # col_blocks > 1 units

    @property
    def feature_bytes(self) -> int:
        # Device-resident cost: values f32 + col_ids i32 + row_ids i32,
        # at the padded shape (restore always widens back to f32/i32).
        if self.cols is not None:
            return sum(s.feature_bytes for s in self.cols)
        return 12 * self.nnz_bucket

    @property
    def spill_bytes(self) -> int:
        # Host-resident cost of the spill record (0 for redecode).
        if self.cols is not None:
            return sum(s.spill_bytes for s in self.cols)
        return 0 if self.spill is None else self.spill.nbytes


@dataclasses.dataclass(frozen=True)
class ResidentBlock:
    """A shard handed out by `DeviceShardCache.blocks()`: a SNAPSHOT
    holding its own strong reference to the device feature triplet, so a
    later eviction (which only drops the cache's reference) can never
    pull the arrays out from under an in-flight accumulate."""

    index: int
    n_rows: int
    feats: Optional[CSRFeatures]
    labels: object
    offsets: object
    weights: object
    slot: int = 0  # device slot the block (and its partials) live on
    # col_blocks > 1: per-column feature snapshots (feats is None); the
    # slot above is the HOME slot where row-space columns live.
    cols: tuple = ()


class DeviceShardCache:
    """Device cache of padded feature blocks over a streamed ingest.

    Built once from a `BlockGameStream` (`from_stream`); every solver
    iteration then replays `blocks()` in FIXED shard order — the
    accumulation order is part of the numeric contract, so resident,
    spilled, and re-uploaded replays produce bitwise-identical partials
    (re-uploaded bytes are the bytes that were evicted).

    ``hbm_budget_bytes`` bounds the feature bytes resident on device;
    `None` means unbounded (fully resident, spill buffers freed). The
    budget is enforced DURING ingest (evict-as-you-go, so ingest peak
    HBM is O(budget), not O(dataset)) and on every re-upload. Eviction
    is replay-aware rather than plain LRU: the replay order is the fixed
    shard order, so the victim is the resident block whose next use is
    FURTHEST in the cyclic order. Plain LRU degenerates to a 0% hit
    rate here — with n shards and budget n-1, the least-recently-used
    block is always exactly the next one needed (n misses/epoch). The
    distance rule pays ~(n - budget_blocks) misses per epoch plus a
    small wrap-around surcharge (the in-hand block must be cached, so
    the resident "hole" walks and costs one extra miss every n-1
    epochs: amortized 1 + 1/(n-1) misses/epoch at budget n-1 with
    equal blocks) —
    per-epoch re-uploads stay close to (dataset - budget) bytes instead
    of the whole dataset. The in-hand block is never evicted; one block
    can exceed a too-small budget (you cannot accumulate a block that
    is not there).
    """

    def __init__(self, entries: List[CachedShard], n_rows: int,
                 n_features: int, dtype,
                 hbm_budget_bytes: Optional[int] = None,
                 prefetch_depth: int = 2,
                 ingest_stats: Optional[dict] = None,
                 devices: Optional[List] = None,
                 spill_dtype: str = "f32",
                 spill_source: str = "buffer",
                 shard_id: Optional[str] = None,
                 redecode_fetch: Optional[Callable] = None,
                 col_blocks: int = 1):
        if spill_dtype not in SPILL_DTYPES:
            raise ValueError(
                f"spill_dtype must be one of {SPILL_DTYPES}, got "
                f"{spill_dtype!r}")
        if spill_source not in SPILL_SOURCES:
            raise ValueError(
                f"spill_source must be one of {SPILL_SOURCES}, got "
                f"{spill_source!r}")
        if spill_source == "redecode" and spill_dtype != "f32":
            raise ValueError(
                f"spill_dtype={spill_dtype!r} compresses host spill "
                "buffers, but spill_source='redecode' keeps none — the "
                "combination would silently train as f32 while "
                "reporting bf16; pick one")
        if spill_source == "redecode" and hbm_budget_bytes is not None \
                and redecode_fetch is None:
            raise ValueError(
                "spill_source='redecode' needs a redecode_fetch "
                "callable (BlockRandomAccess.fetch_rows) to re-decode "
                "evicted blocks from")
        self._entries = entries
        self.n_rows = int(n_rows)
        self.n_features = int(n_features)
        self.dtype = np.dtype(dtype)
        self.hbm_budget_bytes = hbm_budget_bytes
        self.prefetch_depth = max(0, int(prefetch_depth))
        self.ingest_stats = dict(ingest_stats or {})
        self.spill_dtype = spill_dtype
        self.spill_source = spill_source
        self._shard_id = shard_id
        self._redecode_fetch = redecode_fetch
        self._stats = {"hits": 0, "misses": 0, "evictions": 0,
                       "bytes_reuploaded": 0, "epochs": 0,
                       "spill_bytes_written": 0, "redecodes": 0,
                       "bytes_redecoded": 0}
        # A 1-device "mesh" is the single-pool cache: `devices` is only
        # recorded (and placement/budget split per device) for >= 2.
        self.devices = (list(devices)
                        if devices is not None and len(devices) > 1
                        else None)
        self.col_blocks = int(col_blocks)
        if self.col_blocks < 1:
            raise ValueError(f"col_blocks must be >= 1, got {col_blocks}")
        if self.col_blocks > 1:
            if self.devices is None:
                raise ValueError(
                    "col_blocks > 1 places column blocks on a (data, "
                    "model) device grid — pass devices="
                    "mesh_fold_devices(make_mesh_2d(R, C))")
            if len(self.devices) % self.col_blocks:
                raise ValueError(
                    f"{len(self.devices)} devices do not tile a grid "
                    f"with {self.col_blocks} column blocks — need a "
                    "multiple of col_blocks")
        self.n_slots = len(self.devices) if self.devices else 1
        # Uniform column-block width (the split_csr_columns rule); the
        # 2-D objective slices the coefficient vector by it.
        self.col_block_size = -(-self.n_features // self.col_blocks)
        self._slot_bytes = [0] * self.n_slots
        for _, unit in self._all_units():
            if unit.feats is not None:
                self._slot_bytes[unit.slot] += unit.feature_bytes
        self.peak_device_bytes = self.device_bytes
        if hbm_budget_bytes is None:
            for _, unit in self._all_units():
                unit.spill = None
        _G_SPILL_HOST.set(self.spill_bytes_host)

    def _all_units(self):
        """(entry, evictable feature unit) pairs in shard order — the
        CachedShard itself for col_blocks == 1, its ColumnSlices
        otherwise."""
        for e in self._entries:
            if e.cols is not None:
                for s in e.cols:
                    yield e, s
            else:
                yield e, e

    @property
    def spill_bytes_host(self) -> int:
        """Host bytes retained by spill records across all shards — the
        cost that is O(dataset) for ``buffer`` spill (f32, or ~1/3 for
        bf16) and 0 for ``redecode``. Constant after ingest: buffers
        are written once and retained regardless of residency."""
        return sum(e.spill_bytes for e in self._entries)

    @property
    def device_bytes(self) -> int:
        """Cache-accounted feature bytes resident across ALL devices
        (with a mesh the budget binds PER device — see stats())."""
        return sum(self._slot_bytes)

    # -- construction ------------------------------------------------------

    @classmethod
    def from_stream(cls, stream, shard_id: str, dtype=np.float32,
                    hbm_budget_bytes: Optional[int] = None,
                    min_rows_bucket: int = 16,
                    prefetch_depth: int = 2,
                    devices: Optional[List] = None,
                    spill_dtype: str = "f32",
                    spill_source: str = "buffer",
                    redecode_fetch: Optional[Callable] = None,
                    col_blocks: int = 1
                    ) -> "DeviceShardCache":
        """Ingest pass: decode (prefetched, via the stream) -> pad to the
        bucket ladder -> upload. Decode of batch k+1 overlaps the H2D of
        batch k (device_put is async; the stream's prefetch thread keeps
        decoding while uploads ride the wire). With an ``hbm_budget``
        the budget is enforced AS blocks upload — the most recently
        ingested block spills first (its next use, at the start of the
        first replay epoch, is the furthest away), so ingest-peak device
        bytes stay O(budget + one block) and the resident set ends as a
        stable PREFIX of the shard order. ``devices`` (>= 2) places
        block i on ``devices[i % D]`` and makes the budget (and the
        evict-as-you-go accounting) per device.

        ``spill_dtype``/``spill_source`` pick the spill tier (module
        docstring): compressed host buffers (``bf16``) and/or no host
        buffers at all (``redecode``, with ``redecode_fetch`` the
        row-range re-decode hook — `BlockRandomAccess.fetch_rows`)."""
        import jax
        import jax.numpy as jnp

        if spill_dtype not in SPILL_DTYPES:
            raise ValueError(
                f"spill_dtype must be one of {SPILL_DTYPES}, got "
                f"{spill_dtype!r}")
        if spill_source not in SPILL_SOURCES:
            raise ValueError(
                f"spill_source must be one of {SPILL_SOURCES}, got "
                f"{spill_source!r}")
        if spill_source == "redecode" and spill_dtype != "f32":
            # Fail BEFORE the ingest pass: compressed buffers and
            # no-buffers are mutually exclusive tiers (the combination
            # would silently train as f32 while reporting bf16).
            raise ValueError(
                f"spill_dtype={spill_dtype!r} compresses host spill "
                "buffers, but spill_source='redecode' keeps none — "
                "pick one")
        keep_buffers = (hbm_budget_bytes is not None
                        and spill_source == "buffer")
        devs = (list(devices)
                if devices is not None and len(devices) > 1 else None)
        n_slots = len(devs) if devs else 1
        col_blocks = int(col_blocks)
        if col_blocks > 1:
            if devs is None:
                raise ValueError(
                    "col_blocks > 1 places column blocks on a (data, "
                    "model) device grid — pass devices="
                    "mesh_fold_devices(make_mesh_2d(R, C))")
            if n_slots % col_blocks:
                raise ValueError(
                    f"{n_slots} devices do not tile a grid with "
                    f"{col_blocks} column blocks — need a multiple of "
                    "col_blocks")
        n_row_slots = n_slots // col_blocks
        entries: List[CachedShard] = []
        n_rows = 0
        d = None
        ladder = None
        slot_bytes = [0] * n_slots
        peak_bytes = 0
        evictions = 0
        spill_written = 0
        for ds in stream:
            if ds.num_rows == 0:
                continue
            mat = ds.feature_shards[shard_id].tocsr()
            d = mat.shape[1]
            if ladder is None:
                ladder = BucketLadder(
                    min_rows=min(min_rows_bucket, next_pow2(ds.num_rows)),
                    max_rows=next_pow2(ds.num_rows))
            rb = ladder.rows_bucket(ds.num_rows)
            nb = ladder.nnz_bucket(mat.nnz, rb)
            if col_blocks > 1:
                # Row-space columns live on the row's HOME device — the
                # LAST column block's slot, where the 2-D objective's
                # margin chain ends (ops/sharded_objective.py).
                slot = (len(entries) % n_row_slots) * col_blocks \
                    + (col_blocks - 1)
            else:
                slot = len(entries) % n_slots
            dev = devs[slot] if devs else None
            with span("shard_upload"):

                def col(x):
                    out = np.zeros(rb, dtype)
                    out[:ds.num_rows] = x
                    return (jnp.asarray(out) if dev is None
                            else jax.device_put(out, dev))

                def idx(x, d_=None):
                    d_ = dev if d_ is None else d_
                    return (jnp.asarray(x) if d_ is None
                            else jax.device_put(x, d_))

                def build_unit(sub, sub_nnz, nb_u, width, u_dev):
                    """Pad + spill-encode + upload one feature unit
                    (the whole shard, or one column slice)."""
                    nonlocal spill_written
                    values, cols_a, rows_a = padded_csr_arrays(
                        sub, rb, nb_u, value_dtype=dtype)
                    sp = None
                    if keep_buffers:
                        sp = encode_spill(values, cols_a, rows_a,
                                          sub_nnz, spill_dtype)
                        spill_written += sp.nbytes
                        _M_SPILL_WRITTEN.inc(sp.nbytes)
                    if sp is not None and sp.dtype_tag != "f32":
                        # Lossy spill encodings quantize AT INGEST:
                        # every block's device values take the same
                        # encode->restore round trip whether or not it
                        # ever spills, so bf16 replays stay
                        # deterministic AND residency-independent (a
                        # path-dependent precision profile — resident
                        # blocks f32, once-evicted blocks bf16 — would
                        # make model bits depend on eviction history).
                        f = restore_spilled_features(sp, rb, width,
                                                     u_dev)
                    else:
                        f = CSRFeatures(
                            chunked_device_put(values, device=u_dev),
                            idx(cols_a, u_dev), idx(rows_a, u_dev),
                            rb, width)
                    return sp, f

                if col_blocks > 1:
                    from photon_ml_tpu.parallel.distributed import (
                        split_csr_columns,
                    )

                    bs_cols, subs = split_csr_columns(mat, col_blocks)
                    r_slot = len(entries) % n_row_slots
                    slices = []
                    for c, sub in enumerate(subs):
                        c_slot = r_slot * col_blocks + c
                        c_dev = devs[c_slot]
                        nb_c = ladder.nnz_bucket(int(sub.nnz), rb)
                        sp, f = build_unit(sub, int(sub.nnz), nb_c,
                                           bs_cols, c_dev)
                        slices.append(ColumnSlice(
                            c=c, nnz=int(sub.nnz), nnz_bucket=nb_c,
                            spill=sp, feats=f, device=c_dev,
                            slot=c_slot))
                    spill, feats, cols_list = None, None, slices
                else:
                    spill, feats = build_unit(mat, int(mat.nnz), nb,
                                              int(d), dev)
                    cols_list = None
                e = CachedShard(
                    index=len(entries), n_rows=ds.num_rows,
                    nnz=int(mat.nnz), rows_bucket=rb, nnz_bucket=nb,
                    row_offset=n_rows,
                    labels=col(ds.responses), offsets=col(ds.offsets),
                    weights=col(ds.weights),
                    spill=spill,
                    feats=feats,
                    device=dev, slot=slot, cols=cols_list,
                )
            entries.append(e)
            n_rows += ds.num_rows
            new_units = e.cols if e.cols is not None else [e]
            for nu in new_units:
                slot_bytes[nu.slot] += nu.feature_bytes
            peak_bytes = max(peak_bytes, sum(slot_bytes))
            if hbm_budget_bytes is not None:
                # Evict-as-you-go on each new unit's OWN device slot:
                # the budget is per device, and eviction stays
                # most-recent-first (keep the prefix), never the block
                # just uploaded.
                for nu in new_units:
                    sl = nu.slot
                    for victim in reversed(entries[:-1]):
                        if slot_bytes[sl] <= hbm_budget_bytes:
                            break
                        vu = (victim.cols[sl % col_blocks]
                              if victim.cols is not None else victim)
                        if vu.slot == sl and vu.feats is not None:
                            vu.feats = None
                            slot_bytes[sl] -= vu.feature_bytes
                            evictions += 1
                            _M_EVICTIONS.inc()
        if not entries:
            raise ValueError("stream yielded no rows to cache")
        cache = cls(entries, n_rows, int(d), dtype,
                    hbm_budget_bytes=hbm_budget_bytes,
                    prefetch_depth=prefetch_depth,
                    ingest_stats=stream.stats(), devices=devs,
                    spill_dtype=spill_dtype, spill_source=spill_source,
                    shard_id=shard_id, redecode_fetch=redecode_fetch,
                    col_blocks=col_blocks)
        cache._stats["evictions"] += evictions
        cache._stats["spill_bytes_written"] += spill_written
        cache.peak_device_bytes = max(cache.peak_device_bytes, peak_bytes)
        if hbm_budget_bytes is not None:
            # The final block stayed pinned during ingest; settle to the
            # budget with the replay-aware policy (next use = shard 0).
            cache._enforce_budget(pinned=-1)
        # Mirror residency gauges even when nothing evicts (a fully
        # resident cache must not report 0 bytes in the registry).
        _G_DEVICE_BYTES.set(cache.device_bytes)
        _G_PEAK_BYTES.set(cache.peak_device_bytes)
        return cache

    # -- residency management ----------------------------------------------

    @property
    def n_shards(self) -> int:
        return len(self._entries)

    @property
    def entries(self) -> List[CachedShard]:
        return list(self._entries)

    def bucket_shapes(self) -> set:
        if self.col_blocks > 1:
            return {(e.rows_bucket, s.nnz_bucket)
                    for e in self._entries for s in e.cols}
        return {(e.rows_bucket, e.nnz_bucket) for e in self._entries}

    def _entry_resident(self, e: CachedShard) -> bool:
        if e.cols is not None:
            return all(s.feats is not None for s in e.cols)
        return e.feats is not None

    def _enforce_budget(self, pinned: int) -> None:
        """Evict until within budget — PER DEVICE slot under a mesh (the
        budget bounds each device's residency; a single-pool cache is
        the one-slot case). Victim = that slot's resident block whose
        next use is FURTHEST in the fixed cyclic replay order from the
        block in hand (`pinned`; -1 = before an epoch, i.e. next use
        starts at shard 0). Belady's rule for a known cyclic scan — see
        the class docstring for why plain LRU is pathological here.
        Round-robin slots are index-arithmetic subsequences of the shard
        order, so the GLOBAL cyclic distance ranks a slot's blocks
        exactly as the slot's own replay cycle does."""
        budget = self.hbm_budget_bytes
        if budget is None:
            return
        n = len(self._entries)
        cur = pinned if pinned >= 0 else 0
        for slot in range(self.n_slots):
            if self._slot_bytes[slot] <= budget:
                continue
            resident = [(e, u) for e, u in self._all_units()
                        if u.feats is not None and e.index != pinned
                        and u.slot == slot]
            # descending cyclic distance (j - cur) mod n: furthest-next-
            # use first; ties impossible (a slot holds at most one unit
            # per shard index).
            resident.sort(key=lambda p: -((p[0].index - cur) % n))
            while self._slot_bytes[slot] > budget and resident:
                _, victim = resident.pop(0)
                victim.feats = None
                self._slot_bytes[slot] -= victim.feature_bytes
                self._stats["evictions"] += 1
                _M_EVICTIONS.inc()
        _G_DEVICE_BYTES.set(self.device_bytes)

    def _redecode(self, e: CachedShard) -> CSRFeatures:
        """redecode-tier miss: re-decode the block's source rows through
        the random-access block fetch, re-pad, re-upload. The fetched
        batch is byte-identical to the ingest-time batch (deterministic
        block cut), so the padded triplet — hence every partial — is
        bit-for-bit the resident replay."""
        fetch = self._redecode_fetch
        before = getattr(fetch, "payload_bytes_read", None)
        with span("shard_redecode"):
            ds = fetch(e.row_offset, e.n_rows)
            mat = ds.feature_shards[self._shard_id].tocsr()
            if mat.shape[0] != e.n_rows or int(mat.nnz) != e.nnz:
                raise RuntimeError(
                    f"re-decoded shard {e.index} does not match the "
                    f"ingested block: got {mat.shape[0]} rows/{mat.nnz} "
                    f"nnz, cached {e.n_rows}/{e.nnz} — the input "
                    "changed under the cache")
            values, cols, rows = padded_csr_arrays(
                mat, e.rows_bucket, e.nnz_bucket, value_dtype=self.dtype)
        self._stats["redecodes"] += 1
        after = getattr(fetch, "payload_bytes_read", None)
        redecoded = (after - before if before is not None
                     and after is not None else e.feature_bytes)
        self._stats["bytes_redecoded"] += redecoded
        _M_REDECODE_BYTES.inc(redecoded)
        return restore_spilled_features(
            SpillBlock(nnz=e.nnz, enc_values=values, enc_cols=cols,
                       enc_rows=rows, dtype_tag="f32"),
            e.rows_bucket, self.n_features, e.device)

    def _redecode_2d(self, e: CachedShard, missing: List[ColumnSlice]
                     ) -> None:
        """redecode-tier miss for a col_blocks > 1 entry: ONE row-range
        fetch re-decodes the batch, the column cut re-slices it (the
        same deterministic `split_csr_columns` cut as ingest), and only
        the MISSING slices re-pad and re-upload — each to its own
        (row, col) device."""
        from photon_ml_tpu.parallel.distributed import split_csr_columns

        fetch = self._redecode_fetch
        before = getattr(fetch, "payload_bytes_read", None)
        with span("shard_redecode"):
            ds = fetch(e.row_offset, e.n_rows)
            mat = ds.feature_shards[self._shard_id].tocsr()
            if mat.shape[0] != e.n_rows or int(mat.nnz) != e.nnz:
                raise RuntimeError(
                    f"re-decoded shard {e.index} does not match the "
                    f"ingested block: got {mat.shape[0]} rows/{mat.nnz} "
                    f"nnz, cached {e.n_rows}/{e.nnz} — the input "
                    "changed under the cache")
            _, subs = split_csr_columns(mat, self.col_blocks)
            payloads = {}
            for s in missing:
                sub = subs[s.c]
                values, cols, rows = padded_csr_arrays(
                    sub, e.rows_bucket, s.nnz_bucket,
                    value_dtype=self.dtype)
                payloads[s.c] = (values, cols, rows, int(sub.nnz))
        self._stats["redecodes"] += 1
        after = getattr(fetch, "payload_bytes_read", None)
        redecoded = (after - before if before is not None
                     and after is not None
                     else sum(s.feature_bytes for s in missing))
        self._stats["bytes_redecoded"] += redecoded
        _M_REDECODE_BYTES.inc(redecoded)
        for s in missing:
            values, cols, rows, sub_nnz = payloads[s.c]
            s.feats = restore_spilled_features(
                SpillBlock(nnz=sub_nnz, enc_values=values, enc_cols=cols,
                           enc_rows=rows, dtype_tag="f32"),
                e.rows_bucket, self.col_block_size, s.device)

    def _ensure_2d(self, e: CachedShard) -> ResidentBlock:
        """col_blocks > 1 residency: a miss restores each evicted
        column slice to ITS OWN (row, col) device; the snapshot carries
        the per-column feature triplets in column order."""
        missing = [s for s in e.cols if s.feats is None]
        if missing:
            self._stats["misses"] += 1
            _M_MISSES.inc()
            reupload = 0
            for s in missing:
                if s.spill is not None:
                    reupload += (s.spill.nbytes
                                 if s.spill.dtype_tag != "f32"
                                 else s.feature_bytes)
                elif self._redecode_fetch is not None:
                    reupload += s.feature_bytes
                else:
                    raise RuntimeError(
                        f"shard {e.index} column block {s.c} was "
                        "evicted but has no spill buffers (cache built "
                        "without an hbm budget)")
            self._stats["bytes_reuploaded"] += reupload
            _M_REUPLOAD_BYTES.inc(reupload)
            for s in missing:
                self._slot_bytes[s.slot] += s.feature_bytes
            self.peak_device_bytes = max(self.peak_device_bytes,
                                         self.device_bytes)
            _G_PEAK_BYTES.set(self.peak_device_bytes)
            if missing[0].spill is not None:
                with span("shard_reupload"):
                    for s in missing:
                        s.feats = restore_spilled_features(
                            s.spill, e.rows_bucket, self.col_block_size,
                            s.device)
            else:
                self._redecode_2d(e, missing)
            self._enforce_budget(pinned=e.index)
        else:
            self._stats["hits"] += 1
            _M_HITS.inc()
        return ResidentBlock(index=e.index, n_rows=e.n_rows, feats=None,
                             labels=e.labels, offsets=e.offsets,
                             weights=e.weights, slot=e.slot,
                             cols=tuple(s.feats for s in e.cols))

    def ensure(self, index: int) -> ResidentBlock:
        """Return a resident snapshot of the block, restoring it on a
        miss (async put — the caller overlaps it with whatever it is
        accumulating): buffer spill re-uploads + decodes the host spill
        record (`restore_spilled_features`), the redecode tier
        re-decodes the source Avro rows (`_redecode`)."""
        e = self._entries[index]
        if e.cols is not None:
            return self._ensure_2d(e)
        if e.feats is None:
            self._stats["misses"] += 1
            _M_MISSES.inc()
            if e.spill is not None:
                reupload = (e.spill.nbytes if e.spill.dtype_tag != "f32"
                            else e.feature_bytes)
            elif self._redecode_fetch is not None:
                reupload = e.feature_bytes
            else:
                raise RuntimeError(
                    f"shard {index} was evicted but has no spill "
                    "buffers (cache built without an hbm budget)")
            self._stats["bytes_reuploaded"] += reupload
            _M_REUPLOAD_BYTES.inc(reupload)
            self._slot_bytes[e.slot] += e.feature_bytes
            self.peak_device_bytes = max(self.peak_device_bytes,
                                         self.device_bytes)
            _G_PEAK_BYTES.set(self.peak_device_bytes)
            if e.spill is not None:
                with span("shard_reupload"):
                    # Spilled blocks return to their ASSIGNED device —
                    # the round-robin placement is part of the replay
                    # contract.
                    e.feats = restore_spilled_features(
                        e.spill, e.rows_bucket, self.n_features,
                        e.device)
            else:
                e.feats = self._redecode(e)
            self._enforce_budget(pinned=index)
        else:
            self._stats["hits"] += 1
            _M_HITS.inc()
        return ResidentBlock(index=e.index, n_rows=e.n_rows, feats=e.feats,
                             labels=e.labels, offsets=e.offsets,
                             weights=e.weights, slot=e.slot)

    def blocks(self, prefetch_depth: Optional[int] = None
               ) -> Iterator[ResidentBlock]:
        """One replay epoch in fixed shard order. With a prefetch depth
        > 0 the spill re-uploads run on a background thread
        (`HostPrefetcher`), so H2D of shard k+1 overlaps the consumer's
        accumulate of shard k; resident epochs yield straight from HBM."""
        self._stats["epochs"] += 1
        _M_EPOCHS.inc()
        depth = (self.prefetch_depth if prefetch_depth is None
                 else max(0, int(prefetch_depth)))

        def gen():
            for i in range(len(self._entries)):
                yield self.ensure(i)

        if depth < 1 or self.hbm_budget_bytes is None:
            yield from gen()
            return
        yield from HostPrefetcher(gen(), depth)

    def stats(self) -> Dict:
        s = dict(self._stats)
        s.update({
            "shards": self.n_shards,
            "rows": self.n_rows,
            "bucket_shapes": sorted(self.bucket_shapes()),
            "hbm_budget_bytes": self.hbm_budget_bytes,
            "device_bytes": self.device_bytes,
            "peak_device_bytes": self.peak_device_bytes,
            # Host-side spill residency (the O(dataset) cost device
            # gauges never showed) + the tier that produced it.
            "spill_dtype": self.spill_dtype,
            "spill_source": self.spill_source,
            "spill_bytes_host": self.spill_bytes_host,
            "resident_shards": sum(1 for e in self._entries
                                   if self._entry_resident(e)),
            # Mesh placement: hbm_budget_bytes binds PER device, so the
            # per-device breakdown is the budget-compliance view. With
            # col_blocks > 1 the per-slot unit is a COLUMN SLICE, slots
            # are row-major over the (R, C) grid.
            "mesh_devices": len(self.devices) if self.devices else None,
            "col_blocks": self.col_blocks,
            "col_block_size": (self.col_block_size
                               if self.col_blocks > 1 else None),
            "per_device_bytes": list(self._slot_bytes),
            "per_device_resident_shards": [
                sum(1 for _, u in self._all_units()
                    if u.feats is not None and u.slot == slot)
                for slot in range(self.n_slots)],
        })
        return s
