"""Per-feature summary statistics (reference: ml/stat/BasicStatistics.scala:36,
BasicStatisticalSummary.scala:30-51 — which wrap Spark MLlib colStats)."""

from __future__ import annotations

import dataclasses

import numpy as np
import scipy.sparse as sp


class EmptyDatasetError(ValueError):
    """``BasicStatisticalSummary.compute`` was handed a matrix with no
    rows. Raised instead of silently emitting all-NaN mean/variance
    arrays (``s1 / 0``), which poisoned every downstream consumer with
    NaNs that only surfaced much later."""

    def __init__(self, shape):
        super().__init__(
            f"cannot summarize an empty matrix (shape {tuple(shape)}): "
            "statistics over 0 rows are undefined")
        self.shape = tuple(shape)


@dataclasses.dataclass(frozen=True)
class BasicStatisticalSummary:
    mean: np.ndarray
    variance: np.ndarray
    count: int
    num_nonzeros: np.ndarray
    max: np.ndarray
    min: np.ndarray
    norm_l1: np.ndarray
    norm_l2: np.ndarray
    mean_abs: np.ndarray

    @classmethod
    def compute(cls, mat) -> "BasicStatisticalSummary":
        """From a scipy sparse or dense [n, d] matrix. Sparse zeros
        participate in mean/var/min/max exactly as MLlib colStats does.
        Raises :class:`EmptyDatasetError` on an n=0 matrix (the
        division by ``n`` below is undefined; NaN arrays would
        propagate silently)."""
        n = mat.shape[0]
        if n == 0:
            raise EmptyDatasetError(mat.shape)
        if sp.issparse(mat):
            m = mat.tocsc()
            s1 = np.asarray(m.sum(axis=0)).ravel()
            s2 = np.asarray(m.multiply(m).sum(axis=0)).ravel()
            nnz = np.diff(m.indptr)
            mx = m.max(axis=0).toarray().ravel()
            mn = m.min(axis=0).toarray().ravel()
            # Columns with implicit zeros extend min/max to include 0.
            has_zero = nnz < n
            mx = np.where(has_zero, np.maximum(mx, 0.0), mx)
            mn = np.where(has_zero, np.minimum(mn, 0.0), mn)
            l1 = np.asarray(np.abs(m).sum(axis=0)).ravel()
        else:
            a = np.asarray(mat, np.float64)
            s1 = a.sum(axis=0)
            s2 = (a * a).sum(axis=0)
            nnz = (a != 0).sum(axis=0)
            mx = a.max(axis=0)
            mn = a.min(axis=0)
            l1 = np.abs(a).sum(axis=0)
        mean = s1 / n
        # Unbiased variance, matching MLlib colStats.
        var = (s2 - n * mean**2) / max(n - 1, 1)
        return cls(
            mean=mean, variance=np.maximum(var, 0.0), count=n,
            num_nonzeros=nnz.astype(np.int64), max=mx, min=mn,
            norm_l1=l1, norm_l2=np.sqrt(s2), mean_abs=l1 / n,
        )
