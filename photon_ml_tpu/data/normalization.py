"""Feature normalization as algebra, never materialized on the data.

The reference's key trick (ml/normalization/NormalizationContext.scala:38-83,
folded into the aggregators at ml/function/ValueAndGradientAggregator.scala:34-221):
train in the normalized feature space x' = (x - shift) .* factor WITHOUT
rewriting the data, by operating on effective coefficients
``eff = coef .* factor`` and a margin shift ``-eff . shift``. We keep exactly
that algebra — on TPU it additionally avoids materializing a second copy of
the batch in HBM and keeps CSR sparsity intact.

Model back-transform to the original space:
  w = w' .* factor,  b' -= w . shift  (intercept absorbs the shift).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class NormalizationContext:
    """factors/shifts over the feature axis; intercept excluded from both.

    factors: multiplicative scale per feature (None = all ones).
    shifts: additive shift per feature (None = all zeros).
    intercept_id: index of the intercept column, or -1 if none. The intercept
      column must have factor 1 and shift 0 (it is appended by ingest as a
      constant-1 feature).
    """

    factors: Optional[Array]
    shifts: Optional[Array]
    intercept_id: int = -1

    def effective_coefficients(self, coef: Array) -> Array:
        return coef * self.factors if self.factors is not None else coef

    def margin_shift(self, coef: Array) -> Array:
        if self.shifts is None:
            return jnp.zeros((), dtype=coef.dtype)
        eff = self.effective_coefficients(coef)
        return -(eff @ self.shifts)

    def model_to_original_space(self, coef: Array) -> Array:
        """Transform coefficients trained in normalized space back to raw space."""
        out = self.effective_coefficients(coef)
        if self.shifts is not None:
            if self.intercept_id < 0:
                raise ValueError(
                    "Normalization with shifts requires an intercept column"
                )
            out = out.at[self.intercept_id].add(-(out @ self.shifts))
        return out

    def model_to_normalized_space(self, coef: Array) -> Array:
        """Inverse of model_to_original_space (for warm starts across spaces)."""
        out = coef
        if self.shifts is not None:
            if self.intercept_id < 0:
                raise ValueError(
                    "Normalization with shifts requires an intercept column"
                )
            out = out.at[self.intercept_id].add(out @ self.shifts)
        if self.factors is not None:
            out = out / self.factors
        return out

    def tree_flatten(self):
        return (self.factors, self.shifts), (self.intercept_id,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0])


def no_normalization() -> NormalizationContext:
    return NormalizationContext(factors=None, shifts=None, intercept_id=-1)


# ---------------------------------------------------------------------------
# Gathered (per-entity local-space) normalization — the random-effect flavor.
#
# Random-effect blocks carry per-entity LOCAL feature columns (a gather of
# the global space through feat_idx, data/random_effect.py EntityBlock); the
# same normalization algebra applies with the factor/shift vectors gathered
# through the same map. Reference: RandomEffectOptimizationProblem.scala:105-125
# passes the broadcast NormalizationContext into every per-entity problem.
# ---------------------------------------------------------------------------


def gather_normalization(norm: NormalizationContext, feat_idx):
    """Gather (factors, shifts, intercept_mask) into a block's local
    feature space. feat_idx is i32[E, d_local] with -1 for padding columns;
    padding gets factor 1 / shift 0 so all-zero padding columns stay
    exactly zero through the x' = (x - shift) .* factor transform.
    Returns [E, d_local] float arrays (factors/shifts None when the
    context has none); intercept_mask is 1.0 at each entity's intercept
    column (needed by the shift-absorbing space transforms)."""
    safe = jnp.maximum(feat_idx, 0)
    pad = feat_idx < 0

    factors = None
    if norm.factors is not None:
        factors = jnp.where(pad, 1.0, norm.factors[safe])
    shifts = None
    if norm.shifts is not None:
        if norm.intercept_id < 0:
            raise ValueError(
                "Normalization with shifts requires an intercept column")
        # Every entity's local block must actually CONTAIN the intercept
        # column — an all-zero intercept_mask would silently drop the
        # shift-absorbing term from the space round-trip, producing
        # models whose margins are off by a per-entity constant.
        fi = np.asarray(feat_idx)
        # Sentinel padding entities (mesh sharding pads the entity axis
        # with all-padding rows, feat_idx == -1 everywhere) carry no data
        # and zero coefficients — exempt.
        present = (fi == norm.intercept_id).any(axis=-1) | (fi < 0).all(
            axis=-1)
        if not present.all():
            raise ValueError(
                "Normalization with shifts requires the intercept column "
                f"(global id {norm.intercept_id}) in every entity's local "
                f"feature block; {int((~present).sum())} entities lack it "
                "— build the random-effect dataset with intercept_col set")
        shifts = jnp.where(pad, 0.0, norm.shifts[safe])
    mask = (feat_idx == norm.intercept_id).astype(
        factors.dtype if factors is not None
        else shifts.dtype if shifts is not None else jnp.float32)
    return factors, shifts, mask


def gathered_to_normalized_space(coef, factors, shifts, intercept_mask):
    """model_to_normalized_space with gathered [E, d] arrays (coef [E, d],
    original space -> solve space). Same algebra as the context method:
    intercept absorbs the shift dot, then divide by factors."""
    out = coef
    if shifts is not None:
        dot = jnp.sum(out * shifts, axis=-1, keepdims=True)
        out = out + intercept_mask * dot
    if factors is not None:
        out = out / factors
    return out


def gathered_to_original_space(coef, factors, shifts, intercept_mask):
    """model_to_original_space with gathered [E, d] arrays (solve space ->
    original space): w = w' .* factor, intercept -= w . shift."""
    out = coef * factors if factors is not None else coef
    if shifts is not None:
        dot = jnp.sum(out * shifts, axis=-1, keepdims=True)
        out = out - intercept_mask * dot
    return out


# NOTE on box constraints + normalization: no bounds transform lives
# here ON PURPOSE. The reference clamps its optimizer ITERATE against
# the raw constraint values (projectCoefficientsToHypercube,
# LBFGS.scala:77), and that iterate is the NORMALIZED-space coefficient
# vector — the aggregators compute margins via effectiveCoefficients =
# coef :* factors (ValueAndGradientAggregator.scala:100-120), with the
# final model transformed to the original space afterwards. Matching
# semantics here means passing user bounds untransformed into the
# normalized-space solve (coordinates.py / model_training.py do exactly
# that).


def build_normalization_context(
    norm_type: str,
    summary,
    intercept_id: int = -1,
) -> NormalizationContext:
    """Build from a BasicStatisticalSummary.

    Reference: ml/normalization/NormalizationContext.scala factory — the four
    flavors of ml/normalization/NormalizationType.java:25-40.
    """
    from photon_ml_tpu.types import NormalizationType

    nt = NormalizationType(norm_type)
    if nt == NormalizationType.NONE:
        return NormalizationContext(None, None, intercept_id)

    std = np.asarray(summary.variance) ** 0.5
    safe_std = np.where(std > 0, std, 1.0)
    max_mag = np.maximum(np.abs(np.asarray(summary.max)),
                         np.abs(np.asarray(summary.min)))
    safe_mag = np.where(max_mag > 0, max_mag, 1.0)

    factors = None
    shifts = None
    if nt == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        factors = 1.0 / safe_std
    elif nt == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        factors = 1.0 / safe_mag
    elif nt == NormalizationType.STANDARDIZATION:
        factors = 1.0 / safe_std
        shifts = np.asarray(summary.mean).copy()
    if nt == NormalizationType.STANDARDIZATION and intercept_id < 0:
        raise ValueError("STANDARDIZATION requires an intercept column")

    # The intercept column stays untouched.
    if intercept_id >= 0:
        if factors is not None:
            factors = np.asarray(factors).copy()
            factors[intercept_id] = 1.0
        if shifts is not None:
            shifts[intercept_id] = 0.0

    to_arr = lambda a: None if a is None else jnp.asarray(a, dtype=jnp.float32)
    return NormalizationContext(to_arr(factors), to_arr(shifts), intercept_id)
