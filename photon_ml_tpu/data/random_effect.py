"""Random-effect datasets: per-entity problems as bucketed, padded, vmappable
dense blocks.

This module is the TPU re-design of the reference's entity-sharded layer
(ml/data/RandomEffectDataSet.scala:40-395, LocalDataSet.scala:34-304,
RandomEffectDataSetPartitioner.scala): instead of RDD[(entityId, LocalDataSet)]
with per-entity Breeze solves inside executor tasks, entities are

1. grouped by id (host, once, at ingest — replacing the groupByKey shuffle);
2. capped by reservoir sampling with survivor reweighting (sampling.py);
3. projected into their *observed* feature subspace — the union of nonzero
   columns (+ intercept), optionally Pearson-filtered — which is the
   reference's IndexMapProjector (ml/projector/IndexMapProjector.scala:42-106)
   realized as a column gather;
4. bucketed by padded (n_rows, n_features) size classes, each bucket one
   dense ``f[E, n_pad, d_pad]`` block solved by a single `vmap`-batched
   L-BFGS kernel and shardable over chips along the entity axis.

Rows beyond the active cap form "passive" blocks: scored with the entity's
model but not trained on (RandomEffectDataSet.scala:328-369).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.sampling import reservoir_sample

Array = jax.Array


# ---------------------------------------------------------------------------
# Configuration (reference: ml/data/RandomEffectDataConfiguration.scala:1-127)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfiguration:
    random_effect_type: str  # which id column groups the rows
    feature_shard_id: str
    num_active_data_points: Optional[int] = None  # reservoir cap
    num_passive_data_points_lower_bound: Optional[int] = None
    num_features_to_samples_ratio: Optional[float] = None  # Pearson cap
    projector_type: str = "INDEX_MAP"  # INDEX_MAP | IDENTITY | RANDOM=<d>

    @classmethod
    def parse(cls, s: str) -> "RandomEffectDataConfiguration":
        """Parse the reference's comma string:
        'reType,shardId,numPartitions,activeBound,passiveBound,ratio,projector'
        (numPartitions is Spark partitioning — meaningless on a mesh, accepted
        and ignored for CLI compatibility)."""
        p = [t.strip() for t in s.split(",")]
        if len(p) not in (6, 7):
            raise ValueError(
                "expected 'reType,shardId,numPartitions,activeBound,"
                f"passiveBound,ratio[,projector]', got {s!r}")
        maybe = lambda v, cast: (None if v.lower() in ("none", "-1", "")
                                 else cast(v))
        return cls(
            random_effect_type=p[0],
            feature_shard_id=p[1],
            num_active_data_points=maybe(p[3], int),
            num_passive_data_points_lower_bound=maybe(p[4], int),
            num_features_to_samples_ratio=maybe(p[5], float),
            projector_type=p[6].upper() if len(p) == 7 else "INDEX_MAP",
        )


# ---------------------------------------------------------------------------
# Device block
# ---------------------------------------------------------------------------


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class EntityBlock:
    """One bucket of entities with identical padded shapes.

    Padding contracts:
    - rows: weight 0, row_id == sentinel (the global n_rows slot);
    - local feature columns: all-zero x column, feat_idx == -1 (gathers from
      a zeros-extended global coefficient vector).
    """

    x: Array  # f[E, n_pad, d_pad]
    labels: Array  # f[E, n_pad]
    offsets: Array  # f[E, n_pad]
    weights: Array  # f[E, n_pad]
    row_ids: Array  # i32[E, n_pad], == n_rows for padding
    feat_idx: Array  # i32[E, d_pad], == -1 for padding

    @property
    def num_entities(self) -> int:
        return self.x.shape[0]

    @property
    def n_pad(self) -> int:
        return self.x.shape[1]

    @property
    def d_pad(self) -> int:
        return self.x.shape[2]

    def local_margins(self, coefs: Array) -> Array:
        """x @ coef per entity: [E, n_pad]."""
        return jnp.einsum("end,ed->en", self.x, coefs)

    def tree_flatten(self):
        return (self.x, self.labels, self.offsets, self.weights,
                self.row_ids, self.feat_idx), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@dataclasses.dataclass
class RandomEffectDataset:
    """All buckets for one random-effect coordinate.

    ``projection`` is set when the blocks live in a Gaussian-projected latent
    space (reference: RandomEffectDataSetInProjectedSpace.scala with
    ProjectionMatrixBroadcast); None means local spaces are column gathers of
    the global space (index-map / identity projector).
    """

    config: RandomEffectDataConfiguration
    blocks: List[EntityBlock]  # active data
    passive_blocks: List[Optional[EntityBlock]]  # aligned with blocks
    entity_codes: List[np.ndarray]  # [E] global entity code per block slot
    vocabulary: np.ndarray  # entity name per code
    n_rows: int  # global row count == scatter sentinel
    num_global_features: int
    projection: Optional[object] = None  # projector.ProjectionMatrix

    @property
    def num_entities(self) -> int:
        return sum(len(c) for c in self.entity_codes)

    def scatter_scores(self, per_block_margins: Sequence[Array],
                       passive_margins: Sequence[Optional[Array]]) -> Array:
        """Assemble a global dense score vector from per-entity local margins.

        The TPU replacement for the reference's score joins
        (RandomEffectCoordinate.scala:142-152, 179-200): every row belongs to
        exactly one entity, so a scatter-add into a sentinel-extended vector
        is exact.
        """
        scores = jnp.zeros((self.n_rows + 1,),
                           per_block_margins[0].dtype if per_block_margins
                           else jnp.float32)
        for block, m in zip(self.blocks, per_block_margins):
            scores = scores.at[block.row_ids.reshape(-1)].add(m.reshape(-1))
        for block, m in zip(self.passive_blocks, passive_margins):
            if block is not None and m is not None:
                scores = scores.at[block.row_ids.reshape(-1)].add(
                    m.reshape(-1))
        return scores[:-1]


# ---------------------------------------------------------------------------
# Pearson feature selection (reference: LocalDataSet.scala:116-140, 380-394)
# ---------------------------------------------------------------------------


def pearson_correlation_scores(
    x: sp.csr_matrix, y: np.ndarray, intercept_col: Optional[int]
) -> np.ndarray:
    """|Pearson corr(feature_j, label)| per column of a small CSR block.

    Constant columns get score 0; the intercept column (constant by
    construction) gets +inf so it always survives selection — mirroring
    LocalDataSet.filterFeaturesByPearsonCorrelationScore's special-casing.
    """
    n = x.shape[0]
    y = np.asarray(y, np.float64)
    y_c = y - y.mean()
    y_ss = float(y_c @ y_c)
    xs = np.asarray(x.sum(axis=0)).ravel()
    x_mean = xs / n
    x_sq = np.asarray(x.multiply(x).sum(axis=0)).ravel()
    x_var = x_sq - n * x_mean**2
    xy = np.asarray(x.T @ y).ravel()
    cov = xy - n * x_mean * y.mean()
    denom = np.sqrt(np.maximum(x_var, 0) * max(y_ss, 0))
    with np.errstate(invalid="ignore", divide="ignore"):
        corr = np.where(denom > 1e-12, np.abs(cov / np.maximum(denom, 1e-300)),
                        0.0)
    if intercept_col is not None and 0 <= intercept_col < x.shape[1]:
        corr[intercept_col] = np.inf
    return corr


def filter_features_by_support(
    x: sp.csr_matrix, min_num_support: int,
    intercept_col: Optional[int] = None,
) -> np.ndarray:
    """Column indices observed (nonzero) in at least ``min_num_support``
    rows — mirrors LocalDataSet.filterFeaturesBySupport
    (ml/data/LocalDataSet.scala:93-114; an API the reference exposes but
    never wires into its pipeline — same status here). The intercept column
    always survives."""
    support = np.diff(x.tocsc().indptr)
    keep = support >= min_num_support
    if intercept_col is not None and 0 <= intercept_col < x.shape[1]:
        keep[intercept_col] = True
    return np.flatnonzero(keep)


def _next_size(v: int, minimum: int) -> int:
    """Smallest power of two >= max(v, minimum) — the bucket size classes."""
    v = max(v, minimum)
    return 1 << (v - 1).bit_length()


@dataclasses.dataclass
class _EntityRows:
    code: int
    active: np.ndarray  # global row indices
    passive: np.ndarray
    weight_multiplier: float
    local_cols: np.ndarray  # selected global feature columns
    d_local: int = 0  # local block width (== len(local_cols) unless projected)


def build_random_effect_dataset(
    data: GameDataset,
    config: RandomEffectDataConfiguration,
    seed: int = 0,
    intercept_col: Optional[int] = None,
    dtype=jnp.float32,
    min_rows_pad: int = 4,
    min_cols_pad: int = 8,
) -> RandomEffectDataset:
    """Group → cap → select → bucket. Host-side, runs once at ingest
    (replacing the reference's per-iteration Spark shuffles).

    With ``projector_type=RANDOM=<k>`` the packed blocks live in the shared
    Gaussian latent space (reference: RandomEffectProjector.scala:54-66 +
    ProjectionMatrixBroadcast): Pearson selection still applies first (on
    global columns, mirroring RandomEffectDataSet.scala:380-394 running
    before projection), then each entity's rows are projected through the
    one replicated matrix.
    """
    from photon_ml_tpu.projector import build_random_effect_projector

    identity = config.projector_type == "IDENTITY"

    col = data.id_columns[config.random_effect_type]
    mat = data.feature_shards[config.feature_shard_id].tocsr()
    n_rows, d_global = mat.shape
    rng = np.random.default_rng(seed)
    projection = build_random_effect_projector(
        config.projector_type, d_global, intercept_col, seed=seed)

    from photon_ml_tpu.data.game_data import group_rows_by_code
    groups = group_rows_by_code(col.codes)

    entities: List[_EntityRows] = []
    for rows in groups:
        code = int(col.codes[rows[0]])
        cap = config.num_active_data_points
        if cap is not None and len(rows) > cap:
            sel, mult = reservoir_sample(rng, len(rows), cap)
            active = rows[sel]
            passive_mask = np.ones(len(rows), bool)
            passive_mask[sel] = False
            passive = rows[passive_mask]
            lb = config.num_passive_data_points_lower_bound
            if lb is not None and len(passive) < lb:
                passive = np.empty((0,), np.int64)
        else:
            active, passive, mult = rows, np.empty((0,), np.int64), 1.0

        sub = mat[active]
        if identity:
            observed = np.arange(d_global)
        else:
            observed = (np.unique(sub.indices) if sub.nnz
                        else np.empty((0,), np.int64))
            if intercept_col is not None and intercept_col not in observed:
                observed = np.append(observed, intercept_col)
        ratio = config.num_features_to_samples_ratio
        if ratio is not None and len(observed) > 0:
            keep = max(1, int(np.ceil(ratio * len(active))))
            if keep < len(observed):
                scores = pearson_correlation_scores(
                    sub[:, observed], data.responses[active],
                    int(np.flatnonzero(observed == intercept_col)[0])
                    if intercept_col is not None and
                    intercept_col in observed else None)
                top = np.argsort(-scores, kind="stable")[:keep]
                observed = observed[np.sort(top)]
        observed = np.sort(observed)
        d_local = (projection.projected_space_dimension
                   if projection is not None else len(observed))
        entities.append(
            _EntityRows(code, active, passive, mult, observed, d_local))

    # Bucket by padded size classes.
    buckets: Dict[Tuple[int, int, int], List[_EntityRows]] = {}
    for e in entities:
        n_pad = _next_size(len(e.active), min_rows_pad)
        d_pad = _next_size(max(e.d_local, 1), min_cols_pad)
        p_pad = _next_size(len(e.passive), 1) if len(e.passive) else 0
        buckets.setdefault((n_pad, d_pad, p_pad), []).append(e)

    blocks, passive_blocks, codes_per_block = [], [], []
    for (n_pad, d_pad, p_pad), members in sorted(buckets.items()):
        blocks.append(_pack_block(
            members, [m.active for m in members], n_pad, d_pad, data, mat,
            n_rows, dtype, weight_mult=True, projection=projection))
        if p_pad:
            passive_blocks.append(_pack_block(
                members, [m.passive for m in members], p_pad, d_pad, data,
                mat, n_rows, dtype, weight_mult=False, projection=projection))
        else:
            passive_blocks.append(None)
        codes_per_block.append(np.asarray([m.code for m in members],
                                          np.int32))

    return RandomEffectDataset(
        config=config, blocks=blocks, passive_blocks=passive_blocks,
        entity_codes=codes_per_block, vocabulary=col.vocabulary,
        n_rows=n_rows, num_global_features=d_global, projection=projection,
    )


def _pack_block(
    members: List[_EntityRows], row_sets: List[np.ndarray], n_pad: int,
    d_pad: int, data: GameDataset, mat: sp.csr_matrix, n_rows: int, dtype,
    weight_mult: bool, projection=None,
) -> EntityBlock:
    E = len(members)
    x = np.zeros((E, n_pad, d_pad), np.float32)
    labels = np.zeros((E, n_pad), np.float32)
    offsets = np.zeros((E, n_pad), np.float32)
    weights = np.zeros((E, n_pad), np.float32)
    row_ids = np.full((E, n_pad), n_rows, np.int32)
    feat_idx = np.full((E, d_pad), -1, np.int32)

    for i, (m, rows) in enumerate(zip(members, row_sets)):
        k = len(rows)
        if k == 0:
            continue
        cols = m.local_cols
        if projection is not None:
            # Latent-space block: restrict to the Pearson-kept columns on
            # both sides (equivalent to zeroing dropped columns, then
            # projecting the full global vector through P).
            k1 = projection.projected_space_dimension
            sub = np.asarray(
                mat[rows][:, cols] @ projection.matrix[:, cols].T)
            x[i, :k, :k1] = sub
            feat_idx[i, :k1] = np.arange(k1)
        else:
            sub = mat[rows][:, cols].toarray()
            x[i, :k, :len(cols)] = sub
            feat_idx[i, :len(cols)] = cols
        labels[i, :k] = data.responses[rows]
        offsets[i, :k] = data.offsets[rows]
        w = data.weights[rows]
        weights[i, :k] = w * (m.weight_multiplier if weight_mult else 1.0)
        row_ids[i, :k] = rows

    as_dev = lambda a: jnp.asarray(a, dtype) if a.dtype == np.float32 \
        else jnp.asarray(a)
    return EntityBlock(
        x=as_dev(x), labels=as_dev(labels), offsets=as_dev(offsets),
        weights=as_dev(weights), row_ids=jnp.asarray(row_ids),
        feat_idx=jnp.asarray(feat_idx),
    )
