"""Multi-process sharded Avro ingest: N workers run the C decoder over
block-range shards, feeding the parent through shared memory.

Single-host replacement for the reference's executor-parallel decode
(ml/data/AvroDataReader.scala:86-214): the shard planner
(data/shard_planner.py) splits the input files into block-aligned byte
ranges, a ``multiprocessing`` pool decodes each shard with
``native/_avro_native.c decode_training_block`` (zlib inflate + Avro decode
+ feature-dict lookups all happen in C, in parallel, GIL-free across
processes), and the parent assembles results in shard-sequence order — so
the output is byte-identical (values AND row order) to the single-process
path for any worker count.

Transport: each worker packs its shard's numeric columns (labels, offsets,
weights, per-shard-map CSR triplets) into ONE ``multiprocessing.shared_memory``
segment and sends only the segment name + layout over the result pipe; the
parent maps the segment zero-copy and the final ``np.concatenate`` is the
single copy into the result arrays. Non-numeric columns (uids, entity-id
strings, collected keys) ride the pickle pipe. Hosts without /dev/shm fall
back to pickled bytes transparently.

Workers are plain ``python -m photon_ml_tpu.data.parallel_ingest``
subprocesses fed over stdin/stdout pipes — NOT a multiprocessing pool:
fork would inherit an initialized XLA runtime (deadlock-prone), and
spawn/forkserver re-import the parent's ``__main__`` (broken for REPL/stdin
parents, and a failed worker makes Pool respawn forever). The explicit
protocol sidesteps all three, and workers import no jax.

Failure contract: a truncated or corrupt shard raises ``IngestShardError``
naming the shard; decode errors are caught IN the worker and returned as
values, and a worker that dies outright is detected by pipe EOF + exit
status — a bad file can never hang the pool.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

# Auto mode skips the pool below this much compressed payload: spawn-starting
# a worker costs ~0.5 s (python + numpy import), which only amortizes on
# inputs where decode itself is seconds.
MIN_PARALLEL_BYTES = 8 << 20

MAX_AUTO_WORKERS = 8


class IngestShardError(ValueError):
    """A shard failed to decode; the message names the shard."""


def resolve_ingest_workers(spec="auto") -> int:
    """CLI/env worker-count spec -> concrete count. "auto"/None resolves to
    the usable core count (capped at MAX_AUTO_WORKERS); explicit ints pass
    through (>= 1)."""
    if spec is None or spec == "auto" or spec == 0:
        try:
            cores = len(os.sched_getaffinity(0))
        except AttributeError:  # non-Linux
            cores = os.cpu_count() or 1
        return max(1, min(MAX_AUTO_WORKERS, cores))
    n = int(spec)
    if n < 1:
        raise ValueError(f"ingest workers must be >= 1, got {n}")
    return n


# ---------------------------------------------------------------------------
# Worker side. Runs in a `python -m photon_ml_tpu.data.parallel_ingest`
# subprocess: keep the import graph jax-free (only numpy, zlib, the native
# module, and the pure-python varint reader).
# ---------------------------------------------------------------------------

_W: dict = {}  # per-worker state, set by _init_worker


def _init_worker(file_specs, dicts_t, icepts_t, ids_t, delim, collect_keys):
    from photon_ml_tpu.native import load_avro_native

    _W["native"] = load_avro_native()
    _W["files"] = file_specs  # path -> (prog, layout, flags dict)
    _W["dicts"] = dicts_t
    _W["icepts"] = icepts_t
    _W["ids"] = ids_t
    _W["delim"] = delim
    _W["collect"] = collect_keys


def _pack_shared(arrays: Sequence[np.ndarray]):
    """Pack arrays into one shared-memory segment; return a transport
    descriptor. Falls back to pickled bytes when shared memory is
    unavailable."""
    total = sum(a.nbytes for a in arrays)
    try:
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(create=True, size=max(1, total))
    except Exception:  # noqa: BLE001 — no /dev/shm etc.
        return ("bytes", [(a.dtype.str, a.tobytes()) for a in arrays])
    try:
        # The PARENT owns the segment's lifetime (it unlinks after
        # assembly); detach this process's resource tracker so it doesn't
        # double-unlink at worker exit.
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:  # noqa: BLE001 — tracker internals are best-effort
        pass
    off = 0
    meta = []
    for a in arrays:
        # Write through a view — a.tobytes() would materialize a second
        # full host copy of every shard payload.
        np.frombuffer(shm.buf, a.dtype, len(a), off)[:] = a
        meta.append((a.dtype.str, len(a), off))
        off += a.nbytes
    name = shm.name
    shm.close()
    return ("shm", name, meta)


def _unpack_shared(transport):
    """Parent side: transport descriptor -> (arrays, closer). Arrays are
    VIEWS for the shm transport — copy before calling the closer."""
    if transport[0] == "bytes":
        return ([np.frombuffer(b, dtype) for dtype, b in transport[1]],
                lambda: None)
    from multiprocessing import shared_memory

    _, name, meta = transport
    shm = shared_memory.SharedMemory(name=name)
    arrays = [
        np.frombuffer(shm.buf, dtype, count=length,
                      offset=off)
        for dtype, length, off in meta]

    def closer():
        try:
            shm.close()
        except BufferError:
            # A caller kept a view alive; still unlink (it doesn't need
            # zero exports) so the segment can't outlive the process.
            pass
        try:
            shm.unlink()
        except FileNotFoundError:
            pass

    return arrays, closer


def _discard_transport(transport) -> None:
    """Release a result transport without consuming it (error paths):
    attach + unlink the shm segment so it doesn't outlive the ingest."""
    if not transport or transport[0] != "shm":
        return
    try:
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(name=transport[1])
        shm.close()
        shm.unlink()
    except Exception:  # noqa: BLE001 — cleanup is best-effort
        pass


def _decode_shard(shard) -> tuple:
    """Decode one shard's blocks; never raises (errors return as values)."""
    from photon_ml_tpu.data.shard_planner import read_block

    try:
        native = _W["native"]
        if native is None:
            raise RuntimeError("native decoder unavailable in worker")
        prog, layout, flags = _W["files"][shard.path]
        dicts_t, icepts_t, ids_t = _W["dicts"], _W["icepts"], _W["ids"]
        keys = set() if _W["collect"] else None

        label_chunks, off_chunks, w_chunks = [], [], []
        uids: list = []
        n_shards = len(dicts_t)
        vals_c: list = [[] for _ in range(n_shards)]
        cols_c: list = [[] for _ in range(n_shards)]
        rlen_c: list = [[] for _ in range(n_shards)]
        id_lists: list = [[] for _ in range(len(ids_t))]

        with open(shard.path, "rb") as f:
            f.seek(shard.offset)
            for _ in range(shard.num_blocks):
                count, payload = read_block(f, shard.codec, shard.sync,
                                            shard.path)
                (lb, ob, wb, us, shard_out, ids_out) = \
                    native.decode_training_block(
                        payload, count, prog, layout, dicts_t, icepts_t,
                        ids_t, _W["delim"], keys)
                label_chunks.append(np.frombuffer(lb, np.float64))
                # Mirror fast_ingest exactly: always one chunk per block so
                # mixed-layout files can't misalign rows.
                off_chunks.append(np.frombuffer(ob, np.float64)
                                  if flags["has_offset"]
                                  else np.zeros(count))
                w_chunks.append(np.frombuffer(wb, np.float64)
                                if flags["has_weight"]
                                else np.ones(count))
                if flags["has_uid"]:
                    uids.extend(us)
                else:
                    uids.extend([None] * count)
                for s, (vb, cb, rb) in enumerate(shard_out):
                    vals_c[s].append(np.frombuffer(vb, np.float64))
                    cols_c[s].append(np.frombuffer(cb, np.int64))
                    rlen_c[s].append(np.frombuffer(rb, np.int64))
                for t, lst in zip(range(len(ids_t)), ids_out):
                    id_lists[t].extend(lst)

        def cat(chunks, dtype):
            return (np.concatenate(chunks) if chunks
                    else np.zeros(0, dtype))

        arrays = [cat(label_chunks, np.float64),
                  cat(off_chunks, np.float64),
                  cat(w_chunks, np.float64)]
        for s in range(n_shards):
            arrays.append(cat(vals_c[s], np.float64))
            arrays.append(cat(cols_c[s], np.int64))
            arrays.append(cat(rlen_c[s], np.int64))
        transport = _pack_shared(arrays)
        return ("ok", shard.seq, transport, uids, id_lists, keys)
    except Exception as e:  # noqa: BLE001 — surfaces as IngestShardError
        return ("err", shard.seq, shard.label(),
                f"{type(e).__name__}: {e}")


def _worker_main() -> int:
    """Entry point of a worker subprocess (`python -m ...parallel_ingest`):
    read a tiny pickled task from stdin (shared-init file path + this
    worker's shards), load the init payload from the file (the feature
    dicts can be hundreds of MB at production index-map widths — pickled
    ONCE by the parent, read here through the shared page cache), stream
    one pickled result per shard to stdout."""
    import pickle
    import sys

    out = sys.stdout.buffer
    task = pickle.load(sys.stdin.buffer)
    with open(task["init_path"], "rb") as f:
        init = pickle.load(f)
    _init_worker(init["files"], init["dicts"], init["icepts"], init["ids"],
                 init["delim"], init["collect"])
    for shard in task["shards"]:
        pickle.dump(_decode_shard(shard), out,
                    protocol=pickle.HIGHEST_PROTOCOL)
        out.flush()
    return 0


# ---------------------------------------------------------------------------
# Parent side
# ---------------------------------------------------------------------------


def _run_workers(n_workers: int, shards, init: dict):
    """Launch worker subprocesses, interleave-assign shards, yield results
    AS THEY ARRIVE (completion order) — the parent assembles and feeds the
    device while other workers are still decoding.

    Shards are statically assigned round-robin (shard i -> worker
    i mod n): the planner's 2x oversplit keeps byte sizes even enough
    that static assignment balances within ~one shard. One reader thread
    per worker drains its stdout into a shared queue (results can exceed
    the pipe buffer); worker death surfaces as pipe EOF + exit status,
    never a hang.
    """
    import pickle
    import queue
    import subprocess
    import sys
    import tempfile
    import threading
    from pathlib import Path

    pkg_root = str(Path(__file__).resolve().parents[2])
    env = dict(os.environ)
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")

    procs = []
    err_files = []
    threads = []
    q: "queue.Queue[tuple]" = queue.Queue()
    counts = [0] * n_workers

    def reader(i, proc):
        try:
            while True:
                try:
                    q.put(("res", i, pickle.load(proc.stdout)))
                except EOFError:
                    q.put(("eof", i, None))
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised in parent
            q.put(("exc", i, e))

    def stderr_tail(i):
        err_files[i].seek(0)
        text = err_files[i].read().decode("utf-8", "replace")
        return " | ".join(text.strip().splitlines()[-3:])

    # The (possibly huge) init payload is pickled ONCE to a temp file all
    # workers read — not re-serialized down every stdin pipe.
    init_fd, init_path = tempfile.mkstemp(prefix="photon_ingest_init_")
    try:
        with os.fdopen(init_fd, "wb") as f:
            pickle.dump(init, f, protocol=pickle.HIGHEST_PROTOCOL)
        for i in range(n_workers):
            # stderr goes to a temp FILE, not a pipe: nobody drains a
            # stderr pipe while workers run, and a chatty worker filling
            # it would deadlock the whole ingest.
            ef = tempfile.TemporaryFile()
            err_files.append(ef)
            proc = subprocess.Popen(
                [sys.executable, "-m",
                 "photon_ml_tpu.data.parallel_ingest"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=ef, env=env)
            procs.append(proc)
            t = threading.Thread(target=reader, args=(i, proc),
                                 daemon=True)
            t.start()
            threads.append(t)
        for i, proc in enumerate(procs):
            task = {"init_path": init_path, "shards": shards[i::n_workers]}
            try:
                pickle.dump(task, proc.stdin,
                            protocol=pickle.HIGHEST_PROTOCOL)
                proc.stdin.flush()
                proc.stdin.close()
            except OSError:
                # Worker died before reading its task (bad interpreter,
                # import failure, ...) — the reader's EOF + exit status
                # below turns this into a clean IngestShardError with
                # the worker's stderr attached.
                pass

        done = 0
        while done < n_workers:
            kind, i, item = q.get()
            if kind == "res":
                counts[i] += 1
                yield item
            elif kind == "exc":
                raise IngestShardError(
                    f"ingest worker {i} result stream failed: "
                    f"{item}") from item
            else:  # eof
                threads[i].join()
                rc = procs[i].wait()
                expected = len(shards[i::n_workers])
                if rc != 0 or counts[i] != expected:
                    raise IngestShardError(
                        f"ingest worker {i} died (rc={rc}, "
                        f"{counts[i]}/{expected} shards done): "
                        + stderr_tail(i))
                done += 1
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
            for stream in (proc.stdin, proc.stdout):
                if stream:
                    try:
                        stream.close()
                    except OSError:
                        pass
        for ef in err_files:
            try:
                ef.close()
            except OSError:
                pass
        try:
            os.unlink(init_path)
        except OSError:
            pass
        # On an aborted run, queued-but-unyielded results still hold live
        # shm segments — release them (readers exit on the EOF their
        # worker's death produced).
        for t in threads:
            t.join(timeout=5)
        while not q.empty():
            kind, _, item = q.get_nowait()
            if kind == "res":
                _discard_transport(item[2])


def parallel_fast_ingest(
    paths: Sequence,
    shard_maps: Dict,
    intercepts: Dict[str, int],
    id_types: Sequence[str] = (),
    collect_keys: bool = False,
    restrict_keys: Optional[set] = None,
    workers: int = 2,
    auto: bool = False,
    column_consumer=None,
):
    """Multi-process variant of data/fast_ingest.fast_ingest.

    Returns a FastIngestResult byte-identical to the single-process fast
    path, or None when the parallel path doesn't apply (native decoder
    missing, schema not natively ingestible, too little data to amortize
    the pool in ``auto`` mode) — callers then take the single-process path.

    ``column_consumer``, when given, is called once per shard IN SEQUENCE
    ORDER with ``(seq, labels, offsets, weights)`` host arrays as soon as
    that shard's result is contiguous with everything already consumed —
    i.e. while later shards are still decoding. This is the overlap hook
    the chunked device_put feeder (data/device_feed.py) plugs into.

    Raises IngestShardError (naming the shard) on a truncated or corrupt
    shard; the pool is torn down, never hung.
    """
    from photon_ml_tpu.data.fast_ingest import (
        FastIngestResult,
        build_training_layout,
    )
    from photon_ml_tpu.data.index_map import DELIMITER
    from photon_ml_tpu.data.shard_planner import plan_shards, scan_paths
    from photon_ml_tpu.io.avro_codec import Schema
    from photon_ml_tpu.native import load_avro_native

    native = load_avro_native()
    if native is None or not hasattr(native, "decode_training_block"):
        return None
    if workers < 2:
        return None

    indexes = scan_paths(paths)
    total_bytes = sum(ix.num_bytes for ix in indexes)
    total_blocks = sum(len(ix.blocks) for ix in indexes)
    if total_blocks < 2:
        return None  # nothing to parallelize over
    if auto and total_bytes < MIN_PARALLEL_BYTES:
        return None

    # Compile each file's layout up front; any non-ingestible schema sends
    # the WHOLE read down the fallback path (same contract as fast_ingest).
    file_specs = {}
    for ix in indexes:
        if not ix.blocks:
            continue
        layout = build_training_layout(Schema(ix.schema_json).root)
        if layout is None:
            return None
        if id_types and not layout.has_metadata:
            return None
        file_specs[ix.path] = (
            layout.prog, layout.layout,
            dict(has_uid=layout.has_uid, has_weight=layout.has_weight,
                 has_offset=layout.has_offset,
                 has_metadata=layout.has_metadata))

    shard_names = list(shard_maps)
    dicts = []
    for s in shard_names:
        d = shard_maps[s].key_to_index_dict()
        if restrict_keys is not None:
            d = {k: v for k, v in d.items() if k in restrict_keys}
        dicts.append(d)
    dicts_t = tuple(dicts)
    icepts_t = tuple(int(intercepts.get(s, -1)) for s in shard_names)
    ids_t = tuple(id_types)

    shards = plan_shards(indexes, workers * 2)  # 2x oversplit: balance
    n_workers = min(workers, len(shards))

    # Incremental assembly: results arrive in COMPLETION order; each is
    # buffered until it is contiguous with everything already consumed,
    # then folded in (and handed to column_consumer) while later shards
    # are still decoding in the workers — decode, assembly, and H2D
    # genuinely overlap. Folding in seq order keeps the worker-count-
    # invariance contract: chunk concatenation in seq order reproduces
    # the single-process scan exactly.
    label_chunks, off_chunks, w_chunks = [], [], []
    uids: List[Optional[str]] = []
    shard_chunks = {s: ([], [], []) for s in shard_names}
    id_lists: Dict[str, list] = {t: [] for t in id_types}
    keys: Optional[set] = set() if collect_keys else None
    pending: Dict[int, tuple] = {}
    next_seq = 0
    closers = []

    def consume(res):
        _, seq, transport, s_uids, s_ids, s_keys = res
        arrays, closer = _unpack_shared(transport)
        closers.append(closer)
        labels_a, offs_a, ws_a = arrays[0], arrays[1], arrays[2]
        label_chunks.append(labels_a)
        off_chunks.append(offs_a)
        w_chunks.append(ws_a)
        for i, s in enumerate(shard_names):
            shard_chunks[s][0].append(arrays[3 + 3 * i])
            shard_chunks[s][1].append(arrays[3 + 3 * i + 1])
            shard_chunks[s][2].append(arrays[3 + 3 * i + 2])
        uids.extend(s_uids)
        for t, lst in zip(id_types, s_ids):
            id_lists[t].extend(lst)
        if keys is not None and s_keys is not None:
            keys.update(s_keys)
        if column_consumer is not None:
            column_consumer(seq, labels_a, offs_a, ws_a)

    try:
        for res in _run_workers(
                n_workers, shards,
                dict(files=file_specs, dicts=dicts_t, icepts=icepts_t,
                     ids=ids_t, delim=DELIMITER, collect=collect_keys)):
            if res[0] == "err":
                _, _, label, msg = res
                raise IngestShardError(
                    f"ingest shard {label} failed: {msg}")
            pending[res[1]] = res
            while next_seq in pending:
                consume(pending.pop(next_seq))
                next_seq += 1
        if next_seq != len(shards):
            raise IngestShardError(
                f"ingest lost shards: consumed {next_seq} of "
                f"{len(shards)}")

        labels = (np.concatenate(label_chunks) if label_chunks
                  else np.zeros(0))
        n = len(labels)
        offsets = (np.concatenate(off_chunks) if off_chunks
                   else np.zeros(n))
        weights = (np.concatenate(w_chunks) if w_chunks
                   else np.ones(n))
        shards_out = {}
        for s in shard_names:
            vals, cols, rlens = (
                np.concatenate(c) if c else np.zeros(0)
                for c in shard_chunks[s])
            indptr = np.zeros(n + 1, np.int64)
            np.cumsum(rlens.astype(np.int64), out=indptr[1:])
            shards_out[s] = (vals, cols.astype(np.int64), indptr)
        # Everything above COPIED out of the shared segments
        # (np.concatenate/astype allocate); drop the views now — a live
        # memoryview export makes shm.close() raise BufferError and the
        # segment would leak until interpreter shutdown.
        label_chunks.clear()
        off_chunks.clear()
        w_chunks.clear()
        shard_chunks.clear()
        return FastIngestResult(
            labels=labels, offsets=offsets, weights=weights, uids=uids,
            shards=shards_out,
            ids={t: np.asarray(v) for t, v in id_lists.items()},
            collected_keys=keys,
        )
    finally:
        # Error paths may leave shm views in the chunk lists (a live view
        # makes close() raise and the segment outlive us) and unconsumed
        # results in `pending` (segments nobody attached): drop the views
        # FIRST, then close the attached segments, then unlink the
        # orphans.
        label_chunks.clear()
        off_chunks.clear()
        w_chunks.clear()
        shard_chunks.clear()
        for closer in closers:
            try:
                closer()
            except Exception:  # noqa: BLE001 — cleanup is best-effort
                pass
        for res in pending.values():
            _discard_transport(res[2])
        pending.clear()


if __name__ == "__main__":
    raise SystemExit(_worker_main())
