"""LIBSVM text ingest (reference: ml/io/LibSVMInputDataFormat.scala:1-78).

Produces host-side CSR + labels; intercept appended as a trailing constant-1
column when requested (the reference's addIntercept, GLMSuite semantics).
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Tuple

import numpy as np
import scipy.sparse as sp


def read_libsvm(
    path: str | Path,
    num_features: Optional[int] = None,
    add_intercept: bool = True,
    zero_based: bool = False,
    map_negative_labels: bool = True,
) -> Tuple[sp.csr_matrix, np.ndarray]:
    """Returns (features CSR [n, d(+1)], labels f64[n]).

    With ``map_negative_labels`` (default), labels -1/+1 are mapped to 0/1 —
    the binary-classification convention of the reference's readers. Pass
    False for regression/Poisson tasks where -1 is a legitimate target.
    Malformed lines raise with the line number.
    """
    labels = []
    data, indices, indptr = [], [], [0]
    max_idx = -1
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            try:
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    idx_s, val_s = tok.split(":", 1)
                    idx = int(idx_s) - (0 if zero_based else 1)
                    if idx < 0:
                        raise ValueError(f"feature index {idx_s} out of range")
                    indices.append(idx)
                    data.append(float(val_s))
                    max_idx = max(max_idx, idx)
            except (ValueError, IndexError) as e:
                raise ValueError(f"{path}:{lineno}: malformed line: {e}") from e
            indptr.append(len(indices))

    n = len(labels)
    d = num_features if num_features is not None else max_idx + 1
    if max_idx >= d:
        raise ValueError(
            f"feature index {max_idx} >= declared num_features {d}")
    mat = sp.csr_matrix(
        (np.asarray(data), np.asarray(indices, np.int64),
         np.asarray(indptr, np.int64)),
        shape=(n, d))
    if add_intercept:
        mat = sp.hstack(
            [mat, np.ones((n, 1))], format="csr")
    y = np.asarray(labels, np.float64)
    if map_negative_labels:
        y[y == -1] = 0.0
    return mat, y
