"""Block-streaming GameDataset feeder: bounded-memory, C-decoded, prefetched.

The serving engine dispatches streamed scoring at ~10x the rate the
pure-python avro record loop can feed it (BENCH_full.json
`extra.serving.batch_curve` vs the ~13k rows/s record path), so `--stream`
scoring was feeder-bound. This module closes that gap with the same two
mechanisms the training ingest already uses, re-pointed at bounded batches
instead of whole files:

1. **Block-level native decode** — containers are indexed with
   `shard_planner.scan_container_blocks` (two varints read per block,
   payloads seeked over) and each block's payload is decoded straight to
   CSR triplets + label/id columns by the C decoder
   (`native/_avro_native.c decode_training_block`, the `fast_ingest`
   path). Decoded rows accumulate in a host-side column buffer and are cut
   into GameDatasets of EXACTLY ``batch_rows`` rows — block boundaries
   never leak into batch boundaries, so the output is byte-identical
   (values, row order, dtypes, entity vocabularies) to the pure-python
   record loop, which remains as the fallback when the extension is
   unbuilt or a schema doesn't fit the training layout.
2. **Prefetch** — a background thread (`device_feed.HostPrefetcher`) runs
   decode + featureize of batch k+1 while the consumer dispatches batch k;
   combined with the engine's `InFlightWindow` dispatch pipelining this
   yields the three-stage decode → H2D → dispatch pipeline
   (`StreamingGameScorer.score_container_stream`). Peak resident batches
   stay bounded by ``prefetch_depth + 2`` (queue + producer's hand +
   consumer's hand) — the bounded-memory contract is asserted in
   tests/test_block_stream.py.

This is the single-host analog of the reference's per-iteration scoring
flow over HDFS splits (`GameScoringDriver` / `AvroDataReader.scala`
executor-parallel decode), cf. the tf.data-style prefetch pipelines in
PAPERS.md: decode must overlap device execution, not serialize with it.
"""

from __future__ import annotations

import time
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.telemetry import span

from photon_ml_tpu.data.avro_reader import (
    _avro_paths,
    _GameBatchBuilder,
    _reject_duplicate_features,
    iter_records,
)
from photon_ml_tpu.data.device_feed import HostPrefetcher
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.data.index_map import DELIMITER, IndexMap
from photon_ml_tpu.data.shard_planner import (
    FileBlockIndex,
    read_block,
    scan_paths,
)

FEEDERS = ("auto", "native", "python")


def _native_layouts(indexes, id_types):
    """Compile one native decode layout per file index. Returns
    ``(layouts, None)`` on success or ``([], reason)`` when any file's
    schema cannot decode natively — shared by the sequential stream and
    the random-access fetch so both resolve the C path identically."""
    from photon_ml_tpu.data.fast_ingest import build_training_layout
    from photon_ml_tpu.io.avro_codec import Schema

    layouts = []
    for ix in indexes:
        layout = build_training_layout(Schema(ix.schema_json).root)
        if layout is None:
            return [], (f"{ix.path}: schema does not fit the native "
                        "training layout")
        if id_types and not layout.has_metadata:
            return [], f"{ix.path}: id types requested but no metadataMap"
        layouts.append(layout)
    return layouts, None


def _load_native():
    from photon_ml_tpu.native import load_avro_native

    native = load_avro_native()
    if native is None or not hasattr(native, "decode_training_block"):
        return None
    return native


class _ColumnBuffer:
    """Decoded-but-unbatched rows, as per-block column chunks.

    `put_block` appends one decoded block's columns; `take(n)` cuts the
    oldest ``n`` rows into a GameDataset (concatenating chunks only at cut
    time, so the steady-state cost is one O(batch) concatenate per batch
    and the remainder re-seeds as a single chunk)."""

    def __init__(self, shard_maps: Dict[str, IndexMap],
                 id_types: Sequence[str]):
        self._maps = shard_maps
        self._id_types = tuple(id_types)
        self.rows = 0
        self._labels: List[np.ndarray] = []
        self._offsets: List[np.ndarray] = []
        self._weights: List[np.ndarray] = []
        self._uids: List[Optional[str]] = []
        # shard -> (vals chunks, cols chunks, row-length chunks)
        self._shards = {s: ([], [], []) for s in shard_maps}
        self._ids: Dict[str, list] = {t: [] for t in self._id_types}

    def put_block(self, decoded, count: int, layout) -> None:
        lb, ob, wb, us, shard_out, ids_out = decoded
        self._labels.append(np.frombuffer(lb, np.float64))
        # Mirror fast_ingest exactly: one chunk per block regardless of
        # optional fields, so mixed-layout files cannot misalign rows.
        self._offsets.append(np.frombuffer(ob, np.float64)
                             if layout.has_offset else np.zeros(count))
        self._weights.append(np.frombuffer(wb, np.float64)
                             if layout.has_weight else np.ones(count))
        self._uids.extend(us if layout.has_uid else [None] * count)
        for s, (vb, cb, rb) in zip(self._shards, shard_out):
            vals_c, cols_c, rlen_c = self._shards[s]
            vals_c.append(np.frombuffer(vb, np.float64))
            cols_c.append(np.frombuffer(cb, np.int64))
            rlen_c.append(np.frombuffer(rb, np.int64))
        for t, lst in zip(self._id_types, ids_out):
            self._ids[t].extend(lst)
        self.rows += count

    @staticmethod
    def _cat(chunks: List[np.ndarray], dtype) -> np.ndarray:
        """Concatenate to ONE writable array (np.frombuffer chunks are
        read-only, but the CSR canonicalization in
        `_reject_duplicate_features` sorts indices in place)."""
        if not chunks:
            return np.zeros(0, dtype)
        if len(chunks) == 1:
            c = chunks[0]
            return c if c.flags.writeable else c.copy()
        return np.concatenate(chunks)

    def take(self, n: int) -> GameDataset:
        """Cut the oldest ``n`` rows (n <= self.rows) into a GameDataset
        byte-identical to what `_GameBatchBuilder` builds for the same
        records."""
        labels = self._cat(self._labels, np.float64)
        offsets = self._cat(self._offsets, np.float64)
        weights = self._cat(self._weights, np.float64)
        self._labels = [labels[n:]] if n < len(labels) else []
        self._offsets = [offsets[n:]] if n < len(offsets) else []
        self._weights = [weights[n:]] if n < len(weights) else []
        uids = self._uids[:n]
        self._uids = self._uids[n:]

        shards = {}
        for s, imap in self._maps.items():
            vals_c, cols_c, rlen_c = self._shards[s]
            vals = self._cat(vals_c, np.float64)
            cols = self._cat(cols_c, np.int64)
            rlens = self._cat(rlen_c, np.int64)
            nnz = int(rlens[:n].sum())
            indptr = np.zeros(n + 1, np.int64)
            np.cumsum(rlens[:n], out=indptr[1:])
            mat = sp.csr_matrix(
                (vals[:nnz], cols[:nnz], indptr), shape=(n, len(imap)))
            _reject_duplicate_features(mat, imap, uids, s)
            shards[s] = mat
            self._shards[s] = ([vals[nnz:]] if nnz < len(vals) else [],
                               [cols[nnz:]] if nnz < len(cols) else [],
                               [rlens[n:]] if n < len(rlens) else [])
        ids = {}
        for t in self._id_types:
            ids[t] = np.asarray(self._ids[t][:n])
            self._ids[t] = self._ids[t][n:]
        self.rows -= n
        return GameDataset.build(
            responses=labels[:n],
            feature_shards=shards,
            ids=ids,
            offsets=offsets[:n],
            weights=weights[:n],
            uids=np.asarray([u if u is not None else "" for u in uids]),
        )


class BlockGameStream:
    """Bounded-memory streaming GAME ingest: iterate GameDatasets of
    <= ``batch_rows`` rows (exactly ``batch_rows`` except the final
    partial batch) decoded through the native C block decoder when
    available, with a byte-identical pure-python fallback.

    ``feeder``: "auto" (C when the extension is built AND every file's
    schema fits the training layout, else python), "native" (require the
    C path; raises RuntimeError when unavailable), or "python" (force the
    record loop — parity tests, benchmarks).

    ``prefetch_depth``: > 0 decodes ahead on a background thread, holding
    at most that many finished batches (peak resident batches <=
    ``prefetch_depth + 2`` — see device_feed.HostPrefetcher); 0 decodes
    synchronously in the consumer's loop.

    Telemetry accumulates on the instance across iteration:
    ``decode_path`` ("native" | "python", resolved eagerly at
    construction), ``batches``, ``rows``, ``peak_resident_batches``.

    Each batch's entity vocabularies are batch-local — consumers joining
    against a model vocabulary must map through entity NAMES, which is
    exactly what the serving engine does.
    """

    def __init__(self, path, id_types: Sequence[str],
                 feature_shard_maps: Dict[str, IndexMap],
                 batch_rows: int, add_intercept: bool = True,
                 feeder: str = "auto", prefetch_depth: int = 2):
        if batch_rows < 1:
            raise ValueError(f"batch_rows must be >= 1, got {batch_rows}")
        if feeder not in FEEDERS:
            raise ValueError(f"feeder must be one of {FEEDERS}, "
                             f"got {feeder!r}")
        self._path = path
        self._id_types = tuple(id_types)
        self._maps = dict(feature_shard_maps)
        self._batch_rows = int(batch_rows)
        self._add_intercept = add_intercept
        self.prefetch_depth = max(0, int(prefetch_depth))
        self.batches = 0
        self.rows = 0
        self.peak_resident_batches = 0
        self.decode_seconds = 0.0

        self._indexes: List[FileBlockIndex] = []
        self._layouts: list = []
        self.decode_path = "python"
        native = None if feeder == "python" else _load_native()
        why = "native decoder unavailable"
        if native is not None:
            self._indexes = scan_paths(_avro_paths(path))
            why = self._compile_layouts()
            if why is None:
                self.decode_path = "native"
        if feeder == "native" and self.decode_path != "native":
            raise RuntimeError(
                f"feeder='native' requested but the C block path does not "
                f"apply: {why}")
        self._native = native if self.decode_path == "native" else None

    def _compile_layouts(self) -> Optional[str]:
        """Layout per file (aligned with self._indexes); returns a reason
        string when any file's schema can't decode natively, None on
        success."""
        self._layouts, why = _native_layouts(self._indexes,
                                             self._id_types)
        return why

    # -- iteration ---------------------------------------------------------

    def __iter__(self) -> Iterator[GameDataset]:
        src = self._timed(
            self._iter_native() if self.decode_path == "native"
            else self._iter_python())
        if self.prefetch_depth < 1:
            for ds in src:
                self.peak_resident_batches = max(
                    self.peak_resident_batches, 1)
                yield ds
            return
        prefetcher = HostPrefetcher(src, self.prefetch_depth)
        try:
            yield from prefetcher
        finally:
            self.peak_resident_batches = max(self.peak_resident_batches,
                                             prefetcher.peak_resident)

    def _count(self, ds: GameDataset) -> GameDataset:
        self.batches += 1
        self.rows += ds.num_rows
        return ds

    def _timed(self, src: Iterator[GameDataset]
               ) -> Iterator[GameDataset]:
        """Attribute the time spent producing each batch to the
        ``decode`` stage. With prefetch the producer thread runs this
        generator, so the spans land on that thread's trace track —
        overlap with the consumer's dispatch is visible, not averaged
        away; ``decode_seconds`` accumulates on the instance either
        way (stats())."""
        while True:
            t0 = time.perf_counter()
            with span("decode"):
                ds = next(src, None)
            self.decode_seconds += time.perf_counter() - t0
            if ds is None:
                return
            yield ds

    def _iter_python(self) -> Iterator[GameDataset]:
        """The record-at-a-time loop — ONE copy of the python-path batch
        semantics via `_GameBatchBuilder` (shared with
        `read_game_dataset`'s fallback)."""
        batch = _GameBatchBuilder(self._maps, self._id_types,
                                  self._add_intercept)
        for rec in iter_records(self._path):
            batch.append(rec)
            if len(batch) >= self._batch_rows:
                yield self._count(batch.build())
                batch = _GameBatchBuilder(self._maps, self._id_types,
                                          self._add_intercept)
        if len(batch):
            yield self._count(batch.build())

    def _iter_native(self) -> Iterator[GameDataset]:
        shard_names = list(self._maps)
        dicts_t = tuple(self._maps[s].key_to_index_dict()
                        for s in shard_names)
        icepts_t = tuple(
            int(self._maps[s].intercept_index if self._add_intercept
                else -1)
            for s in shard_names)
        buf = _ColumnBuffer(self._maps, self._id_types)
        for ix, layout in zip(self._indexes, self._layouts):
            if not ix.blocks:
                continue
            with open(ix.path, "rb") as f:
                f.seek(ix.blocks[0].offset)
                for b in ix.blocks:
                    _, payload = read_block(
                        f, ix.codec, ix.sync, ix.path,
                        expected=(b.count, b.payload_bytes, b.offset))
                    try:
                        decoded = self._native.decode_training_block(
                            payload, b.count, layout.prog, layout.layout,
                            dicts_t, icepts_t, self._id_types, DELIMITER,
                            None)
                    except ValueError as e:
                        raise ValueError(
                            f"{ix.path}: block at offset {b.offset} "
                            f"failed to decode: {e}") from e
                    buf.put_block(decoded, b.count, layout)
                    while buf.rows >= self._batch_rows:
                        yield self._count(buf.take(self._batch_rows))
        if buf.rows:
            yield self._count(buf.take(buf.rows))

    def stats(self) -> dict:
        return {
            "decode_path": self.decode_path,
            "prefetch_depth": self.prefetch_depth,
            "batches": self.batches,
            "rows": self.rows,
            "peak_resident_batches": self.peak_resident_batches,
            "decode_seconds": self.decode_seconds,
        }


class BlockRandomAccess:
    """Random-access re-decode of container rows by GLOBAL row range —
    the miss path of the shard cache's fully out-of-core ``redecode``
    spill tier (data/shard_cache.py): evicted feature blocks keep NO
    host copy, and a cache miss re-decodes exactly the Avro container
    blocks that cover the requested rows through the same block index
    the sequential stream uses (`shard_planner.scan_container_blocks`).

    ``fetch_rows(row_start, n_rows)`` returns a GameDataset
    byte-identical to the ``BlockGameStream`` batch that covered rows
    ``[row_start, row_start + n_rows)`` at ingest, for the same maps /
    id types / intercept settings: the native path feeds the covering
    blocks through the same `_ColumnBuffer` cut, the python path feeds
    the covering records through the same `_GameBatchBuilder` — the two
    batch-construction code paths whose byte-identity
    tests/test_block_stream.py already pins.

    Cost per fetch: the covering container blocks are re-read from disk
    and re-decoded (a batch spans ceil(batch_rows / block_rows) + 1
    blocks); nothing else is touched, so host residency is O(one
    fetch). Instances keep cumulative ``payload_bytes_read`` /
    ``blocks_decoded`` / ``rows_fetched`` — the shard cache reads the
    payload-byte deltas into its ``bytes_redecoded`` telemetry.
    Instances are callable (``fetch(row_start, n_rows)``) so the cache
    can hold them as a plain hook."""

    def __init__(self, path, id_types: Sequence[str],
                 feature_shard_maps: Dict[str, IndexMap],
                 add_intercept: bool = True, feeder: str = "auto"):
        if feeder not in FEEDERS:
            raise ValueError(f"feeder must be one of {FEEDERS}, "
                             f"got {feeder!r}")
        self._id_types = tuple(id_types)
        self._maps = dict(feature_shard_maps)
        self._add_intercept = add_intercept
        self._indexes = scan_paths(_avro_paths(path))
        self.decode_path = "python"
        native = None if feeder == "python" else _load_native()
        why = "native decoder unavailable"
        self._layouts: list = []
        if native is not None:
            self._layouts, why = _native_layouts(self._indexes,
                                                 self._id_types)
            if why is None:
                self.decode_path = "native"
        if feeder == "native" and self.decode_path != "native":
            raise RuntimeError(
                f"feeder='native' requested but the C block path does "
                f"not apply: {why}")
        self._native = native if self.decode_path == "native" else None
        self._schemas: dict = {}  # file idx -> parsed python schema root

        # Flattened (file idx, BlockSpan, global first row) table +
        # bisectable row starts: fetch maps a row range to the covering
        # block run in O(log blocks).
        self._blocks: list = []
        row = 0
        for fi, ix in enumerate(self._indexes):
            for b in ix.blocks:
                self._blocks.append((fi, b, row))
                row += b.count
        self.total_rows = row
        self._row_starts = [entry[2] for entry in self._blocks]
        self.payload_bytes_read = 0
        self.blocks_decoded = 0
        self.rows_fetched = 0

    def __call__(self, row_start: int, n_rows: int) -> GameDataset:
        return self.fetch_rows(row_start, n_rows)

    def _covering_blocks(self, row_start: int, n_rows: int):
        """Yield (file idx, BlockSpan) for the minimal block run
        covering the row range, reading each payload as it is needed."""
        import bisect

        first = bisect.bisect_right(self._row_starts, row_start) - 1
        need_until = row_start + n_rows
        i = first
        f = None
        cur_file = None
        try:
            while i < len(self._blocks) \
                    and self._blocks[i][2] < need_until:
                fi, b, _ = self._blocks[i]
                ix = self._indexes[fi]
                if fi != cur_file:
                    if f is not None:
                        f.close()
                    f = open(ix.path, "rb")
                    f.seek(b.offset)
                    cur_file = fi
                _, payload = read_block(
                    f, ix.codec, ix.sync, ix.path,
                    expected=(b.count, b.payload_bytes, b.offset))
                self.payload_bytes_read += b.payload_bytes
                self.blocks_decoded += 1
                yield fi, b, payload
                i += 1
        finally:
            if f is not None:
                f.close()

    def fetch_rows(self, row_start: int, n_rows: int) -> GameDataset:
        """Decode rows ``[row_start, row_start + n_rows)`` — see class
        docstring for the byte-identity contract."""
        if n_rows < 1:
            raise ValueError(f"n_rows must be >= 1, got {n_rows}")
        if row_start < 0 or row_start + n_rows > self.total_rows:
            raise ValueError(
                f"row range [{row_start}, {row_start + n_rows}) outside "
                f"the container ({self.total_rows} rows)")
        import bisect

        first = bisect.bisect_right(self._row_starts, row_start) - 1
        skip = row_start - self._blocks[first][2]
        self.rows_fetched += n_rows
        if self.decode_path == "native":
            return self._fetch_native(row_start, n_rows, skip)
        return self._fetch_python(row_start, n_rows, skip)

    def _fetch_native(self, row_start: int, n_rows: int,
                      skip: int) -> GameDataset:
        shard_names = list(self._maps)
        dicts_t = tuple(self._maps[s].key_to_index_dict()
                        for s in shard_names)
        icepts_t = tuple(
            int(self._maps[s].intercept_index if self._add_intercept
                else -1)
            for s in shard_names)
        buf = _ColumnBuffer(self._maps, self._id_types)
        for fi, b, payload in self._covering_blocks(row_start, n_rows):
            layout = self._layouts[fi]
            try:
                decoded = self._native.decode_training_block(
                    payload, b.count, layout.prog, layout.layout,
                    dicts_t, icepts_t, self._id_types, DELIMITER, None)
            except ValueError as e:
                raise ValueError(
                    f"{self._indexes[fi].path}: block at offset "
                    f"{b.offset} failed to decode: {e}") from e
            buf.put_block(decoded, b.count, layout)
        if skip:
            buf.take(skip)  # discard the head of the first block
        return buf.take(n_rows)

    def _fetch_python(self, row_start: int, n_rows: int,
                      skip: int) -> GameDataset:
        import io as _io

        from photon_ml_tpu.io.avro_codec import Schema, read_datum

        batch = _GameBatchBuilder(self._maps, self._id_types,
                                  self._add_intercept)
        pos = 0  # record position relative to the first covering block
        for fi, b, payload in self._covering_blocks(row_start, n_rows):
            root = self._schemas.get(fi)
            if root is None:
                root = Schema(self._indexes[fi].schema_json).root
                self._schemas[fi] = root
            src = _io.BytesIO(payload)
            for _ in range(b.count):
                rec = read_datum(src, root)
                if skip <= pos < skip + n_rows:
                    batch.append(rec)
                pos += 1
        return batch.build()


def read_game_dataset_via_blocks(
    path, id_types: Sequence[str],
    feature_shard_maps: Dict[str, IndexMap],
    add_intercept: bool = True,
) -> Optional[GameDataset]:
    """One-shot GAME read through the C BLOCK decoder: the whole container
    decoded as one `BlockGameStream` batch (byte-identical to the record
    paths — the same `_ColumnBuffer.take` contract the per-batch identity
    tests pin down). This is `read_game_dataset`'s single-process fast
    path: the block decode runs ~3x the generic C datum-decode record
    loop (BENCH_full.json `extra.stream_scoring`), and it makes the block
    path the ONE C decode implementation for both streamed and one-shot
    reads. Returns None when the native path does not apply (extension
    unbuilt, schema mismatch) — callers fall back as before."""
    stream = BlockGameStream(
        path, id_types=id_types, feature_shard_maps=feature_shard_maps,
        batch_rows=2 ** 62, add_intercept=add_intercept,
        feeder="auto", prefetch_depth=0)
    if stream.decode_path != "native":
        return None
    out = None
    for ds in stream:  # batch_rows spans the input: at most one batch
        out = ds
    return out
