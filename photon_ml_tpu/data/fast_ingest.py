"""Block-level native ingest of TrainingExampleAvro-shaped files.

The generic C decoder (native/_avro_native.c decode_block) still
materializes a python dict per record and per feature; this module goes one
level deeper for the training-data schema family: records decode STRAIGHT
to CSR triplets + label/offset/weight arrays in C
(decode_training_block), skipping all intermediate objects. Feature-name →
column lookups happen in C against the IndexMap's dict, so the whole ingest
is one C call per container block.

Schema flexibility: the file's actual field ORDER and optional-field
branch order are compiled into a layout descriptor per file (the reference
writes metadataMap before weight/offset; this codebase after — both work).
Anything that doesn't fit the expected shapes returns None and callers fall
back to the record-at-a-time path.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.data.index_map import DELIMITER, IndexMap
from photon_ml_tpu.io.avro_codec import (
    compile_schema_program,
    iter_raw_blocks,
)
from photon_ml_tpu.native import load_avro_native


@dataclasses.dataclass
class TrainingLayout:
    prog: bytes
    layout: bytes
    has_uid: bool
    has_weight: bool
    has_offset: bool
    has_metadata: bool


def _union_null_branch(prog, node, other_op: int) -> Optional[int]:
    """For union [null, X] (either order): the null branch index, or None
    if the node isn't exactly that union shape."""
    if prog[node] != 9 or prog[node + 1] != 2:
        return None
    b0, b1 = int(prog[node + 2]), int(prog[node + 3])
    if prog[b0] == 0 and prog[b1] == other_op:
        return 0
    if prog[b1] == 0 and prog[b0] == other_op:
        return 1
    return None


def build_training_layout(schema_root) -> Optional[TrainingLayout]:
    sp = compile_schema_program(schema_root)
    if sp is None:
        return None
    prog = np.frombuffer(sp.prog, np.int64)
    root = sp.root
    if prog[root] != 12:
        return None
    nf = int(prog[root + 1])
    fields = [(sp.strings[int(prog[root + 2 + 2 * i])],
               int(prog[root + 2 + 2 * i + 1])) for i in range(nf)]

    outer: List[Tuple[int, int]] = []
    inner: Optional[List[Tuple[int, int]]] = None
    flags = dict(has_uid=False, has_weight=False, has_offset=False,
                 has_metadata=False)
    for name, child in fields:
        if name == "uid":
            nb = _union_null_branch(prog, child, 6)
            if nb is None:
                return None
            outer.append((1, nb))
            flags["has_uid"] = True
        elif name == "label":
            if prog[child] != 4:
                return None
            outer.append((2, 0))
        elif name == "weight" or name == "offset":
            nb = _union_null_branch(prog, child, 4)
            if nb is None:
                return None
            outer.append((3 if name == "weight" else 4, nb))
            flags["has_weight" if name == "weight" else "has_offset"] = True
        elif name == "features":
            if prog[child] != 10:  # array
                return None
            rec = int(prog[child + 1])
            if prog[rec] != 12:
                return None
            inner = []
            n_in = int(prog[rec + 1])
            seen = set()
            for i in range(n_in):
                fname = sp.strings[int(prog[rec + 2 + 2 * i])]
                fchild = int(prog[rec + 2 + 2 * i + 1])
                if fname == "name":
                    if prog[fchild] != 6:
                        return None
                    inner.append((10, 0))
                elif fname == "term":
                    if prog[fchild] == 6:
                        inner.append((11, -1))  # plain string
                    else:
                        nb = _union_null_branch(prog, fchild, 6)
                        if nb is None:
                            return None
                        inner.append((11, nb))
                elif fname == "value":
                    if prog[fchild] != 4:
                        return None
                    inner.append((12, 0))
                else:
                    inner.append((0, fchild))
                seen.add(fname)
            if not {"name", "value"} <= seen:
                return None
            outer.append((5, 0))
        elif name == "metadataMap":
            # union [null, map<string>]
            if prog[child] != 9 or prog[child + 1] != 2:
                return None
            b0, b1 = int(prog[child + 2]), int(prog[child + 3])

            def _is_str_map(b):
                return prog[b] == 11 and prog[int(prog[b + 1])] == 6

            if prog[b0] == 0 and _is_str_map(b1):
                nb = 0
            elif prog[b1] == 0 and _is_str_map(b0):
                nb = 1
            else:
                return None
            outer.append((6, nb))
            flags["has_metadata"] = True
        else:
            outer.append((0, child))
    if not any(k == 2 for k, _ in outer) or inner is None:
        return None

    from array import array

    lay = array("q")
    lay.append(len(outer))
    for k, a in outer:
        lay.extend([k, a])
    lay.append(len(inner))
    for k, a in inner:
        lay.extend([k, a])
    return TrainingLayout(prog=sp.prog, layout=lay.tobytes(), **flags)


@dataclasses.dataclass
class FastIngestResult:
    labels: np.ndarray
    offsets: np.ndarray
    weights: np.ndarray
    uids: List[Optional[str]]
    # shard name -> (data, indices, indptr) CSR pieces
    shards: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]]
    ids: Dict[str, np.ndarray]
    collected_keys: Optional[set]


def fast_ingest(
    paths: Sequence,
    shard_maps: Dict[str, IndexMap],
    intercepts: Dict[str, int],
    id_types: Sequence[str] = (),
    collect_keys: bool = False,
    restrict_keys: Optional[set] = None,
    workers=None,
) -> Optional[FastIngestResult]:
    """Native whole-file ingest. Returns None when the native module is
    missing or any file's schema doesn't fit the training layout — callers
    fall back to the record-at-a-time path.

    ``restrict_keys``: selected-features whitelist (lookups happen against
    the restricted dict).

    ``workers``: "auto"/None resolves to the usable core count; an int >= 2
    decodes block-range shards in a process pool (data/parallel_ingest.py)
    with byte-identical output (values and row order); 1 forces this
    single-process path. The parallel path declines (returns None
    internally) on inputs too small to amortize the pool in auto mode, and
    this in-process path then runs as before.
    """
    native = load_avro_native()
    if native is None or not hasattr(native, "decode_training_block"):
        return None

    from photon_ml_tpu.data.parallel_ingest import (
        parallel_fast_ingest,
        resolve_ingest_workers,
    )

    auto = workers in (None, "auto", 0)
    n_workers = resolve_ingest_workers(workers)
    if n_workers > 1:
        result = parallel_fast_ingest(
            paths, shard_maps, intercepts, id_types=id_types,
            collect_keys=collect_keys, restrict_keys=restrict_keys,
            workers=n_workers, auto=auto)
        if result is not None:
            return result

    shard_names = list(shard_maps)
    dicts = []
    for s in shard_names:
        d = shard_maps[s].key_to_index_dict()
        if restrict_keys is not None:
            d = {k: v for k, v in d.items() if k in restrict_keys}
        dicts.append(d)
    dicts_t = tuple(dicts)
    icepts_t = tuple(int(intercepts.get(s, -1)) for s in shard_names)
    ids_t = tuple(id_types)
    keys: Optional[set] = set() if collect_keys else None

    label_chunks, off_chunks, w_chunks = [], [], []
    uids: List[Optional[str]] = []
    shard_chunks = {s: ([], [], []) for s in shard_names}  # vals, cols, rlen
    id_lists: Dict[str, list] = {t: [] for t in id_types}

    for path in paths:
        blocks = iter_raw_blocks(path)
        layout: Optional[TrainingLayout] = None
        for schema, payload, count in blocks:
            if layout is None:
                layout = build_training_layout(schema.root)
                if layout is None:
                    return None  # schema not ingestible natively
                if id_types and not layout.has_metadata:
                    return None  # ids requested but absent from schema
            (lb, ob, wb, us, shard_out, ids_out) = \
                native.decode_training_block(
                    payload, count, layout.prog, layout.layout,
                    dicts_t, icepts_t, ids_t, DELIMITER, keys)
            label_chunks.append(np.frombuffer(lb, np.float64))
            # Always append a chunk per block so files with and without
            # optional fields can be mixed without misaligning rows.
            off_chunks.append(np.frombuffer(ob, np.float64)
                              if layout.has_offset
                              else np.zeros(count))
            w_chunks.append(np.frombuffer(wb, np.float64)
                            if layout.has_weight
                            else np.ones(count))
            if layout.has_uid:
                uids.extend(us)
            else:
                uids.extend([None] * count)
            for s, (vb, cb, rb) in zip(shard_names, shard_out):
                shard_chunks[s][0].append(np.frombuffer(vb, np.float64))
                shard_chunks[s][1].append(np.frombuffer(cb, np.int64))
                shard_chunks[s][2].append(np.frombuffer(rb, np.int64))
            for t, lst in zip(ids_t, ids_out):
                id_lists[t].extend(lst)

    labels = (np.concatenate(label_chunks) if label_chunks
              else np.zeros(0))
    n = len(labels)
    offsets = (np.concatenate(off_chunks) if off_chunks
               else np.zeros(n))
    weights = (np.concatenate(w_chunks) if w_chunks
               else np.ones(n))
    shards = {}
    for s in shard_names:
        vals, cols, rlens = (
            np.concatenate(c) if c else np.zeros(0)
            for c in shard_chunks[s])
        indptr = np.zeros(n + 1, np.int64)
        np.cumsum(rlens.astype(np.int64), out=indptr[1:])
        shards[s] = (vals, cols.astype(np.int64), indptr)
    return FastIngestResult(
        labels=labels, offsets=offsets, weights=weights, uids=uids,
        shards=shards,
        ids={t: np.asarray(v) for t, v in id_lists.items()},
        collected_keys=keys,
    )
