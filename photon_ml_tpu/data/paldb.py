"""Parser AND writer for PalDB 1.1 stores — reference index-map interop.

The reference builds its feature-index stores with LinkedIn PalDB
(`com.linkedin.paldb:paldb:1.1.0`, photon-ml/build.gradle:52) through
FeatureIndexingJob (ml/FeatureIndexingJob.scala:145-174) and reads them with
PalDBIndexMap (ml/util/PalDBIndexMap.scala:43-220). Its GAME integ fixtures
ship pre-built stores (GameIntegTest/input/feature-indexes/,
test-with-uid-feature-indexes/) — the artifact a migrating user actually
has. This module parses the PALDB_V1 container directly (no JVM), so those
stores load as ordinary IndexMaps.

Store semantics (PalDBIndexMapBuilder.scala:45-49): every partition holds
BOTH directions in one store — (name: str) -> (index: int) and
(index: int) -> (name: str); feature names are `name + "\\u0001" + term`
(GLMSuite key convention). Partitioning follows Spark's HashPartitioner
over Java String.hashCode (PalDBIndexMap.scala:138-140), and partition i's
internal indices are offset by the cumulative size of partitions < i
(PalDBIndexMap.load, :71-100).

PALDB_V1 container layout (reverse-engineered from the fixtures and the
public PalDB 1.1 format):

    writeUTF("PALDB_V1") | timestamp i64 | keyCount i32 |
    keyLengthCount i32 | maxKeyLength i32 |
    per key-length class: {serializedKeyLen i32, keyCount i32, slots i32,
        slotSize i32, indexOffset i32, dataOffset i64} |
    serializerCount i32 (0) | indexStart i32 | dataStart i64 |
    index slots (open-addressed hash, slot = serialized key +
        MSB-first 7-bit varint data offset, 0 = empty) |
    data entries (varint byte length + serialized value)

Value/key serialization (observed subset of PalDB's StorageSerialization;
varints are LSB-first 7-bit groups with the high bit as continuation,
protobuf-style):
    0x05+k          -> int k, k in 0..8
    0x0e + u8       -> int 9..255
    0x10 + varint   -> int >= 256 (packed)
    0x67 ('g') + varint charCount + per-char varint -> str
Unknown type bytes raise with the offending byte, so stores written with
serializations outside this subset fail loudly instead of mis-decoding.
"""

from __future__ import annotations

import re
import struct
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple, Union

from photon_ml_tpu.data.index_map import IndexMap

_MAGIC = "PALDB_V1"
_STORE_RE = re.compile(r"paldb-partition-(?P<ns>.+)-(?P<part>\d+)\.dat$")


def _unpack_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    """LSB-first 7-bit varint (PalDB LongPacker, protobuf byte order):
    high bit = continuation."""
    ret = 0
    shift = 0
    while True:
        v = buf[pos]
        pos += 1
        ret |= (v & 0x7F) << shift
        shift += 7
        if not (v & 0x80):
            return ret, pos


def _decode_value(buf: bytes, pos: int, end: int) -> Union[int, str]:
    """Decode one serialized PalDB object in buf[pos:end], enforcing that
    the decode consumes EXACTLY the declared bytes — a truncated or
    corrupt entry fails loudly instead of mis-decoding into its
    neighbor's bytes."""
    start = pos
    t = buf[pos]
    pos += 1
    if 0x05 <= t <= 0x0D:  # small ints 0..8, immediate
        value: Union[int, str] = t - 0x05
    elif t == 0x0E:  # unsigned byte
        value = buf[pos]
        pos += 1
    elif t == 0x10:  # packed varint
        value, pos = _unpack_varint(buf, pos)
    elif t == 0x67:  # string: char count + per-char varints
        n, pos = _unpack_varint(buf, pos)
        chars = []
        for _ in range(n):
            c, pos = _unpack_varint(buf, pos)
            chars.append(chr(c))
        value = "".join(chars)
    else:
        raise ValueError(
            f"unsupported PalDB serialization type byte 0x{t:02x} at "
            f"{pos - 1} (only the int/str encodings produced by "
            "PalDBIndexMapBuilder are supported)")
    if pos != end:
        raise ValueError(
            f"corrupt PalDB entry at {start}: decoded {pos - start} bytes, "
            f"declared {end - start}")
    return value


def read_paldb_store(path) -> Iterator[Tuple[Union[int, str],
                                             Union[int, str]]]:
    """Yield (key, value) pairs from one PALDB_V1 store file."""
    raw = Path(path).read_bytes()
    n_magic = struct.unpack_from(">H", raw, 0)[0]
    magic = raw[2:2 + n_magic].decode()
    if magic != _MAGIC:
        raise ValueError(f"{path}: not a {_MAGIC} store (got {magic!r})")
    o = 2 + n_magic + 8  # skip timestamp
    key_count, key_len_count, _max_key_len = struct.unpack_from(">iii", raw, o)
    o += 12
    sections = []
    for _ in range(key_len_count):
        klen, kcnt, slots, ssize, ioff = struct.unpack_from(">iiiii", raw, o)
        o += 20
        doff = struct.unpack_from(">q", raw, o)[0]
        o += 8
        sections.append((klen, kcnt, slots, ssize, ioff, doff))
    n_serializers = struct.unpack_from(">i", raw, o)[0]
    o += 4
    if n_serializers:
        raise ValueError(
            f"{path}: custom PalDB serializers are not supported")
    index_start = struct.unpack_from(">i", raw, o)[0]
    o += 4
    data_start = struct.unpack_from(">q", raw, o)[0]

    seen = 0
    for klen, kcnt, slots, ssize, ioff, doff in sections:
        base = index_start + ioff
        for s in range(slots):
            slot = raw[base + s * ssize: base + (s + 1) * ssize]
            off, _ = _unpack_varint(slot, klen)
            if off == 0:  # empty slot
                continue
            key = _decode_value(slot, 0, klen)
            vpos = data_start + doff + off
            vlen, vpos = _unpack_varint(raw, vpos)
            value = _decode_value(raw, vpos, vpos + vlen)
            seen += 1
            yield key, value
    if seen != key_count:
        raise ValueError(
            f"{path}: decoded {seen} entries, header declares {key_count}")


def _java_string_hash(s: str) -> int:
    """Java String.hashCode (32-bit overflow semantics)."""
    h = 0
    for c in s:
        h = (31 * h + ord(c)) & 0xFFFFFFFF
    return h - 0x100000000 if h >= 0x80000000 else h


def java_hash_partition(key: str, num_partitions: int) -> int:
    """Spark HashPartitioner.getPartition: nonNegativeMod(hashCode, p)."""
    m = _java_string_hash(key) % num_partitions
    return m + num_partitions if m < 0 else m


def discover_namespaces(directory) -> Dict[str, int]:
    """namespace -> partition count, from paldb-partition-<ns>-<i>.dat
    filenames in `directory`."""
    found: Dict[str, List[int]] = {}
    for p in Path(directory).iterdir():
        m = _STORE_RE.match(p.name)
        if m:
            found.setdefault(m.group("ns"), []).append(int(m.group("part")))
    out = {}
    for ns, parts in found.items():
        expected = list(range(len(parts)))
        if sorted(parts) != expected:
            raise ValueError(
                f"{directory}: namespace {ns!r} has partitions "
                f"{sorted(parts)}, expected contiguous 0..{len(parts) - 1}")
        out[ns] = len(parts)
    if not out:
        raise FileNotFoundError(
            f"no paldb-partition-*.dat stores under {directory}")
    return out


def load_paldb_index_map(directory, namespace: str,
                         num_partitions: Optional[int] = None) -> IndexMap:
    """Load one namespace's partitioned PalDB stores as an IndexMap.

    Exactly mirrors PalDBIndexMap.load (ml/util/PalDBIndexMap.scala:71-100):
    partition i's indices are offset by the cumulative feature count of
    partitions < i, and lookups hash with Spark's HashPartitioner — the
    offsets are validated here by re-partitioning every key.
    """
    directory = Path(directory)
    if num_partitions is None:
        num_partitions = discover_namespaces(directory)[namespace]

    key_to_index: Dict[str, int] = {}
    offset = 0
    for i in range(num_partitions):
        path = directory / f"paldb-partition-{namespace}-{i}.dat"
        part_pairs = [(k, v) for k, v in read_paldb_store(path)
                      if isinstance(k, str)]
        for name, idx in part_pairs:
            if not isinstance(idx, int):
                raise ValueError(
                    f"{path}: string key {name!r} maps to non-int {idx!r}")
            expected = java_hash_partition(name, num_partitions)
            if expected != i:
                raise ValueError(
                    f"{path}: key {name!r} hashes to partition {expected}, "
                    f"found in partition {i} — wrong num_partitions?")
            key_to_index[name] = idx + offset
        offset += len(part_pairs)

    n = len(key_to_index)
    if sorted(key_to_index.values()) != list(range(n)):
        raise ValueError(
            f"{directory}/{namespace}: indices are not a permutation of "
            f"0..{n - 1} — corrupt store or partition mismatch")
    return IndexMap(key_to_index)


def load_paldb_index_maps(directory) -> Dict[str, IndexMap]:
    """Load EVERY namespace under `directory` (shard id -> IndexMap)."""
    return {ns: load_paldb_index_map(directory, ns, parts)
            for ns, parts in discover_namespaces(directory).items()}


def discover_store_namespaces(directory) -> Dict[str, int]:
    """namespace -> partition count for EITHER store format: the
    reference's partitioned PalDB stores (count >= 1) or this package's
    <ns>.json stores (count 0 marks the JSON format). The single place
    that knows the on-disk naming conventions."""
    directory = Path(directory)
    if any(_STORE_RE.match(p.name) for p in directory.iterdir()):
        return discover_namespaces(directory)
    out = {p.stem: 0 for p in sorted(directory.glob("*.json"))}
    if not out:
        raise FileNotFoundError(
            f"no paldb-partition-*.dat or *.json index stores in {directory}")
    return out


def load_store_namespace(directory, namespace: str,
                         num_partitions: int) -> IndexMap:
    """Load ONE namespace in either format (num_partitions from
    :func:`discover_store_namespaces`; 0 = JSON)."""
    if num_partitions:
        return load_paldb_index_map(directory, namespace, num_partitions)
    return IndexMap.load(Path(directory) / f"{namespace}.json")


# ---------------------------------------------------------------------------
# Writer — the other half of PalDBIndexMapBuilder interop
# (ml/FeatureIndexingJob.scala:145-174 produces these stores; a migrated
# pipeline that feeds index stores to other Photon-adjacent tooling needs
# us to produce them too). Layout constants verified against the
# reference's checked-in fixtures (PalDBIndexMapTest/, GameIntegTest/
# feature-indexes/): slots = Math.round(count / 0.75), slot = serialized
# key + LSB-first varint data offset zero-padded to slotSize, sections
# ascending by serialized key length, each section's data prefixed with
# one 0x00 byte (offset 0 = empty slot sentinel), and slot placement by
# murmur3-32(seed 42, masked positive) with linear probing — the hash was
# determined empirically from the fixtures (11/14 keys sit at their exact
# hash slot, the rest at linear-probe distance 1).
# ---------------------------------------------------------------------------

_LOAD_FACTOR = 0.75
_MURMUR_SEED = 42


def _pack_varint(v: int) -> bytes:
    """LSB-first 7-bit varint (inverse of _unpack_varint)."""
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _encode_value(v: Union[int, str]) -> bytes:
    """Serialize one int/str in the PalDB StorageSerialization subset
    (inverse of _decode_value)."""
    if isinstance(v, bool) or not isinstance(v, (int, str)):
        raise TypeError(f"PalDB writer supports int/str, got {type(v)}")
    if isinstance(v, int):
        if v < 0:
            raise ValueError(f"negative ints are not supported: {v}")
        if v <= 8:
            return bytes([0x05 + v])
        if v <= 255:
            return bytes([0x0E, v])
        return bytes([0x10]) + _pack_varint(v)
    out = bytearray([0x67])
    out += _pack_varint(len(v))
    for c in v:
        out += _pack_varint(ord(c))
    return bytes(out)


def _murmur3_32(data: bytes, seed: int = _MURMUR_SEED) -> int:
    """MurmurHash3 x86 32-bit — PalDB's HashUtils slot hash."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    n = len(data)
    for i in range(n // 4):
        k = struct.unpack_from("<I", data, i * 4)[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = data[(n // 4) * 4:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if tail:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h & 0x7FFFFFFF


def write_paldb_store(path, pairs, timestamp: int = 0) -> None:
    """Write one PALDB_V1 store file from (key, value) pairs (int/str
    each). Round-trips through read_paldb_store and follows the layout of
    stores the reference's PalDBIndexMapBuilder produces."""
    by_len: Dict[int, List[Tuple[bytes, bytes]]] = {}
    seen_keys = set()
    n_pairs = 0
    for k, v in pairs:
        kb = _encode_value(k)
        if kb in seen_keys:
            raise ValueError(f"duplicate PalDB key {k!r}")
        seen_keys.add(kb)
        by_len.setdefault(len(kb), []).append((kb, _encode_value(v)))
        n_pairs += 1
    # n_pairs == 0 is legal: a hash partition can be empty (Spark's
    # HashPartitioner tolerates it, and the store must still exist for
    # PalDBIndexMap.load's 0..N-1 filename scan).

    sections = []  # (klen, cnt, slots, ssize, index_blob, data_blob)
    for klen in sorted(by_len):
        entries = by_len[klen]
        data = bytearray(b"\x00")  # offset 0 marks an empty index slot
        offsets = []
        for _, vb in entries:
            offsets.append(len(data))
            data += _pack_varint(len(vb)) + vb
        cnt = len(entries)
        slots = max(1, int(cnt / _LOAD_FACTOR + 0.5))  # Math.round
        ssize = klen + len(_pack_varint(max(offsets)))
        index = bytearray(slots * ssize)
        for (kb, _), off in zip(entries, offsets):
            s = _murmur3_32(kb) % slots
            for _probe in range(slots):
                base = s * ssize
                if _unpack_varint(index, base + klen)[0] == 0:
                    rec = kb + _pack_varint(off)
                    index[base:base + len(rec)] = rec
                    break
                s = (s + 1) % slots
            else:
                raise AssertionError("open-addressed index overflow")
        sections.append((klen, cnt, slots, ssize, bytes(index),
                         bytes(data)))

    magic = _MAGIC.encode()
    header = bytearray()
    header += struct.pack(">H", len(magic)) + magic
    header += struct.pack(">q", timestamp)
    header += struct.pack(">iii", n_pairs, len(sections),
                          max(by_len) if by_len else 0)
    ioff = 0
    doff = 0
    for klen, cnt, slots, ssize, index, data in sections:
        header += struct.pack(">iiiii", klen, cnt, slots, ssize, ioff)
        header += struct.pack(">q", doff)
        ioff += len(index)
        doff += len(data)
    header += struct.pack(">i", 0)  # serializer count
    index_start = len(header) + 4 + 8
    header += struct.pack(">i", index_start)
    header += struct.pack(">q", index_start + ioff)  # data start

    with open(path, "wb") as f:
        f.write(bytes(header))
        for *_, index, _data in sections:
            f.write(index)
        for *_, data in sections:
            f.write(data)


def build_paldb_index_stores(directory, namespace: str,
                             names, num_partitions: int = 1) -> IndexMap:
    """Write a partitioned PalDB feature-index store the way
    FeatureIndexingJob does (ml/FeatureIndexingJob.scala:145-174 via
    PalDBIndexMapBuilder.put, which stores BOTH directions): names are
    partitioned with Spark's HashPartitioner, each partition assigns
    per-partition local indices (sorted order — deterministic), and the
    global index of partition i's features is local + the cumulative
    count of partitions < i, exactly the contract PalDBIndexMap.load
    (and load_paldb_index_map here) reconstructs. Returns the resulting
    global IndexMap."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    names = list(names)
    if len(set(names)) != len(names):
        raise ValueError("duplicate feature names")
    parts: List[List[str]] = [[] for _ in range(num_partitions)]
    for name in names:
        parts[java_hash_partition(name, num_partitions)].append(name)

    key_to_index: Dict[str, int] = {}
    offset = 0
    for i, members in enumerate(parts):
        members = sorted(members)
        pairs: List[Tuple[Union[int, str], Union[int, str]]] = []
        for local, name in enumerate(members):
            pairs.append((name, local))
            pairs.append((local, name))
            key_to_index[name] = local + offset
        write_paldb_store(
            directory / f"paldb-partition-{namespace}-{i}.dat", pairs)
        offset += len(members)
    return IndexMap(key_to_index)


def load_feature_index_maps(directory) -> Dict[str, IndexMap]:
    """shard id -> IndexMap from a feature-index directory of EITHER
    format: the reference's partitioned PalDB stores
    (paldb-partition-<shard>-<i>.dat) or this package's JSON stores
    (<shard>.json, written by the training driver / feature-indexing CLI)."""
    return {ns: load_store_namespace(directory, ns, parts)
            for ns, parts in discover_store_namespaces(directory).items()}
