"""Name-and-term feature-set extraction and persistence (reference:
ml/avro/data/NameAndTerm.scala and
ml/avro/data/NameAndTermFeatureSetContainer.scala — per-feature-section
distinct (name, term) sets, persisted as text files, merged into a feature
index map with optional intercept; the GAME driver's "Avro scan" feature-map
path, ml/cli/game/GAMEDriver.prepareFeatureMaps:43-100)."""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, Sequence, Set, Tuple

from photon_ml_tpu.data.avro_reader import iter_records
from photon_ml_tpu.data.index_map import (
    DELIMITER,
    INTERCEPT_KEY,
    IndexMap,
    feature_key,
)

NameAndTerm = Tuple[str, str]


@dataclasses.dataclass
class NameAndTermFeatureSetContainer:
    """section key -> set of (name, term) pairs."""

    feature_sets: Dict[str, Set[NameAndTerm]]

    def get_feature_name_and_term_to_index_map(
        self, section_keys: Sequence[str], add_intercept: bool = False,
    ) -> IndexMap:
        """Union the selected sections into one contiguous IndexMap
        (NameAndTermFeatureSetContainer.getFeatureNameAndTermToIndexMap).
        Sorted for determinism (the reference's set-fold order is JVM-hash
        dependent; stable order makes models reproducible)."""
        merged: Set[NameAndTerm] = set()
        for key in section_keys:
            merged |= self.feature_sets.get(key, set())
        k2i = {feature_key(n, t): i
               for i, (n, t) in enumerate(sorted(merged))}
        if add_intercept:
            k2i[INTERCEPT_KEY] = len(k2i)
        return IndexMap(k2i)

    def save_as_text_files(self, output_dir) -> None:
        """One `<section>.txt` per section, one `name<0x01>term` line per
        feature (saveAsTextFiles)."""
        out = Path(output_dir)
        out.mkdir(parents=True, exist_ok=True)
        for section, features in self.feature_sets.items():
            lines = [f"{n}{DELIMITER}{t}" for n, t in sorted(features)]
            (out / f"{section}.txt").write_text(
                "\n".join(lines) + ("\n" if lines else ""))

    @classmethod
    def load_from_text_files(
        cls, input_dir, section_keys: Sequence[str],
    ) -> "NameAndTermFeatureSetContainer":
        """(readNameAndTermFeatureSetContainerFromTextFiles)."""
        feature_sets: Dict[str, Set[NameAndTerm]] = {}
        for section in section_keys:
            path = Path(input_dir) / f"{section}.txt"
            features: Set[NameAndTerm] = set()
            for line in path.read_text().splitlines():
                if line:
                    name, _, term = line.partition(DELIMITER)
                    features.add((name, term))
            feature_sets[section] = features
        return cls(feature_sets)

    @classmethod
    def from_avro(
        cls, path, section_keys: Sequence[str] = ("features",),
    ) -> "NameAndTermFeatureSetContainer":
        """Scan Avro training records and collect distinct (name, term) per
        feature-bag field (AvroUtils.readNameAndTermFeatureSetContainer...
        FromGenericRecords — each section key is a record field holding a
        list of {name, term, value} records)."""
        feature_sets: Dict[str, Set[NameAndTerm]] = {
            key: set() for key in section_keys}
        for rec in iter_records(path):
            for key in section_keys:
                for f in rec.get(key) or ():
                    feature_sets[key].add(
                        (f["name"], f.get("term") or ""))
        return cls(feature_sets)
