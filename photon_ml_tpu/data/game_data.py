"""GameDataset: the host-side columnar container for GAME training data.

Replaces the reference's RDD[(uid, GameDatum)] (ml/data/GameDatum.scala:33-59)
with struct-of-arrays: row order is frozen at construction, so every score
vector is a dense f32[n_rows] indexed by row position and the reference's
KeyValueScore join algebra (ml/data/KeyValueScore.scala:62-82) becomes
elementwise +/- on device.

Feature shards: named sparse matrices over disjoint (or overlapping) feature
spaces (the reference's featureShardContainer). Entity id columns: one
integer-coded column per random-effect type (user ids, item ids, ...), with
the string->code vocabulary kept host-side.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.ops.features import (
    DENSE_DENSITY_THRESHOLD,
    features_to_device,
)
from photon_ml_tpu.ops.glm_objective import GLMBatch


@dataclasses.dataclass
class EntityIdColumn:
    """Integer-coded entity ids for one random-effect type."""

    codes: np.ndarray  # i32[n_rows], code per row
    vocabulary: np.ndarray  # entity name per code (unicode array)

    @property
    def num_entities(self) -> int:
        return len(self.vocabulary)


def group_rows_by_code(codes: np.ndarray) -> list[np.ndarray]:
    """Row indices grouped by code value (stable order within groups).

    The single host-side replacement for every groupByKey shuffle in the
    reference (entity grouping, sharded evaluators).
    """
    order = np.argsort(codes, kind="stable")
    bounds = np.flatnonzero(np.diff(codes[order])) + 1
    return np.split(order, bounds)


@dataclasses.dataclass
class GameDataset:
    """Columnar GAME data, one row per example (host RAM, numpy/scipy)."""

    responses: np.ndarray  # f[n]
    offsets: np.ndarray  # f[n]
    weights: np.ndarray  # f[n]
    feature_shards: Dict[str, sp.csr_matrix]
    id_columns: Dict[str, EntityIdColumn]
    uids: Optional[np.ndarray] = None  # opaque row ids for score output

    def __post_init__(self):
        n = len(self.responses)
        for name, mat in self.feature_shards.items():
            if mat.shape[0] != n:
                raise ValueError(
                    f"feature shard {name!r} has {mat.shape[0]} rows, "
                    f"expected {n}")
        for name, col in self.id_columns.items():
            if len(col.codes) != n:
                raise ValueError(
                    f"id column {name!r} has {len(col.codes)} rows, "
                    f"expected {n}")

    @property
    def num_rows(self) -> int:
        return len(self.responses)

    @classmethod
    def build(
        cls,
        responses,
        feature_shards: Dict[str, sp.spmatrix],
        ids: Optional[Dict[str, np.ndarray]] = None,
        offsets=None,
        weights=None,
        uids=None,
    ) -> "GameDataset":
        """Build from raw columns; string entity ids are integer-coded here
        (the analog of GameConverters.getGameDataSetFromDataFrame,
        ml/data/GameConverters.scala:27-172)."""
        responses = np.asarray(responses, np.float64)
        n = len(responses)
        offsets = (np.zeros(n) if offsets is None
                   else np.asarray(offsets, np.float64))
        weights = (np.ones(n) if weights is None
                   else np.asarray(weights, np.float64))
        id_columns = {}
        for name, raw in (ids or {}).items():
            vocab, codes = np.unique(np.asarray(raw), return_inverse=True)
            id_columns[name] = EntityIdColumn(codes.astype(np.int32), vocab)
        return cls(
            responses=responses, offsets=offsets, weights=weights,
            feature_shards={k: sp.csr_matrix(v) for k, v in
                            feature_shards.items()},
            id_columns=id_columns, uids=uids,
        )

    # -- device views ------------------------------------------------------

    def fixed_effect_batch(
        self, shard_id: str, dtype=jnp.float32,
        extra_offsets: Optional[np.ndarray] = None,
        dense_threshold: float = DENSE_DENSITY_THRESHOLD,
        sparse_layout: str = "csr",
    ) -> GLMBatch:
        """Materialize one feature shard as a device GLMBatch
        (the analog of FixedEffectDataSet, ml/data/FixedEffectDataSet.scala:29-103).
        ``sparse_layout`` picks the below-threshold layout ("csr" |
        "bucketed_ell" | "sort_permute_ell" — see features_to_device)."""
        from photon_ml_tpu.data.device_feed import chunked_device_put

        mat = self.feature_shards[shard_id]
        feats = features_to_device(mat, dtype, dense_threshold,
                                   sparse_layout=sparse_layout)
        off = self.offsets if extra_offsets is None else \
            self.offsets + extra_offsets
        # Column vectors ride the same chunked uploader as the features:
        # a single put below the chunk threshold, bounded overlapped
        # transfers above it (billions-of-rows datasets).
        return GLMBatch(
            features=feats,
            labels=chunked_device_put(self.responses, dtype),
            offsets=chunked_device_put(off, dtype),
            weights=chunked_device_put(self.weights, dtype),
        )

    def subset(self, rows: np.ndarray) -> "GameDataset":
        """Row-sliced view (used by validation splits and tests)."""
        return GameDataset(
            responses=self.responses[rows],
            offsets=self.offsets[rows],
            weights=self.weights[rows],
            feature_shards={k: m[rows] for k, m in self.feature_shards.items()},
            id_columns={
                k: EntityIdColumn(c.codes[rows], c.vocabulary)
                for k, c in self.id_columns.items()},
            uids=None if self.uids is None else self.uids[rows],
        )
