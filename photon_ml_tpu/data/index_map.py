"""Feature index maps: name⊕term feature keys -> contiguous column indices.

The reference needs an off-heap PalDB store for this (ml/util/PalDBIndexMap.scala:43-220)
only to keep JVM heaps small; on the TPU stack a plain host-side dict plus a
frozen numpy view is sufficient (SURVEY §2.9). Key construction matches
GLMSuite: key = name + "\\u0001" + term (ml/io/GLMSuite.scala:370 — the
delimiter is the 0x01 control byte, NOT an empty string), intercept key is
"(INTERCEPT)" with empty term.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, Iterable, Iterator, Optional, Tuple

DELIMITER = ""
INTERCEPT_NAME = "(INTERCEPT)"
INTERCEPT_KEY = INTERCEPT_NAME + DELIMITER


def feature_key(name: str, term: str = "") -> str:
    return f"{name}{DELIMITER}{term}"


def split_key(key: str) -> Tuple[str, str]:
    name, _, term = key.partition(DELIMITER)
    return name, term


class IndexMap:
    """Bidirectional feature-key <-> index map (ml/util/IndexMap.scala:1-54)."""

    def __init__(self, key_to_index: Dict[str, int]):
        self._k2i = dict(key_to_index)
        self._i2k: Dict[int, str] = {i: k for k, i in self._k2i.items()}
        if len(self._i2k) != len(self._k2i):
            raise ValueError("index map has duplicate indices")

    # -- core interface ---------------------------------------------------

    def get_index(self, key: str) -> int:
        """-1 when absent (the reference's NULL_KEY contract)."""
        return self._k2i.get(key, -1)

    def get_feature_name(self, index: int) -> Optional[str]:
        return self._i2k.get(index)

    def __len__(self) -> int:
        return len(self._k2i)

    def __contains__(self, key: str) -> bool:
        return key in self._k2i

    @property
    def num_features(self) -> int:
        return len(self._k2i)

    def items(self) -> Iterator[Tuple[Tuple[str, str], int]]:
        """Yields ((name, term), index) — used for wildcard constraint
        expansion (ml/io/GLMSuite.scala:207-260)."""
        for key, idx in self._k2i.items():
            yield split_key(key), idx

    def key_items(self) -> Iterator[Tuple[str, int]]:
        return iter(self._k2i.items())

    def key_to_index_dict(self) -> Dict[str, int]:
        """The underlying key->index dict (NOT a copy) — handed to the
        native ingest so feature lookups happen in C. Treat as read-only."""
        return self._k2i

    @property
    def intercept_index(self) -> int:
        idx = self.get_index(INTERCEPT_KEY)
        if idx < 0:
            # Tolerate an intercept registered without the delimiter.
            idx = self.get_index(INTERCEPT_NAME)
        return idx

    # -- construction -----------------------------------------------------

    @classmethod
    def from_keys(cls, keys: Iterable[str], add_intercept: bool = False
                  ) -> "IndexMap":
        """Deterministic map: sorted unique keys, intercept appended last.

        (The reference's DefaultIndexMap sorts for determinism as well.)
        """
        uniq = sorted(set(keys) - {INTERCEPT_KEY})
        if add_intercept:
            uniq.append(INTERCEPT_KEY)
        return cls({k: i for i, k in enumerate(uniq)})

    @classmethod
    def from_name_terms(cls, pairs: Iterable[Tuple[str, str]],
                        add_intercept: bool = False) -> "IndexMap":
        return cls.from_keys(
            (feature_key(n, t) for n, t in pairs), add_intercept)

    # -- persistence (replaces PalDB stores) ------------------------------

    def save(self, path: str | Path) -> None:
        Path(path).write_text(json.dumps(self._k2i))

    @classmethod
    def load(cls, path: str | Path) -> "IndexMap":
        return cls(json.loads(Path(path).read_text()))


class IdentityIndexMap(IndexMap):
    """index i <-> key str(i), for pre-indexed (e.g. LIBSVM) data
    (reference: ml/util/IdentityIndexMapLoader.scala)."""

    def __init__(self, num_features: int, intercept_last: bool = False):
        n = num_features - (1 if intercept_last else 0)
        mapping = {feature_key(str(i)): i for i in range(n)}
        if intercept_last:
            mapping[INTERCEPT_KEY] = n
        super().__init__(mapping)
