"""Full GLM validation metric map (reference: ml/Evaluation.scala:31-194 —
the Spark-MLlib-backed metric bundle the GLM driver logs per λ)."""

from __future__ import annotations

from typing import Dict

import numpy as np

from photon_ml_tpu.constants import POSITIVE_RESPONSE_THRESHOLD
from photon_ml_tpu.evaluation.evaluators import (
    area_under_precision_recall,
    area_under_roc_curve,
    peak_f1_score,
)
from photon_ml_tpu.types import TaskType


def _sigmoid(z):
    return 1 / (1 + np.exp(-np.clip(z, -500, 500)))


def evaluate_glm(task: TaskType, scores, labels, offsets=None, weights=None,
                 num_coefficients: int | None = None) -> Dict[str, float]:
    """Metric map for one model's validation scores (margins, no offset)."""
    scores = np.asarray(scores, np.float64)
    labels = np.asarray(labels, np.float64)
    n = len(scores)
    offsets = np.zeros(n) if offsets is None else np.asarray(offsets)
    weights = np.ones(n) if weights is None else np.asarray(weights)
    z = scores + offsets
    out: Dict[str, float] = {}

    if task == TaskType.LOGISTIC_REGRESSION:
        p = _sigmoid(z)
        eps = 1e-15
        log_lik = float(np.sum(
            weights * (labels * np.log(np.maximum(p, eps))
                       + (1 - labels) * np.log(np.maximum(1 - p, eps)))))
        pred = (p >= POSITIVE_RESPONSE_THRESHOLD).astype(float)
        tp = float(weights[(pred == 1) & (labels == 1)].sum())
        fp = float(weights[(pred == 1) & (labels == 0)].sum())
        fn = float(weights[(pred == 0) & (labels == 1)].sum())
        precision = tp / (tp + fp) if tp + fp > 0 else 0.0
        recall = tp / (tp + fn) if tp + fn > 0 else 0.0
        out.update({
            "AUC": area_under_roc_curve(z, labels, weights),
            "PR_AUC": area_under_precision_recall(z, labels, weights),
            "PEAK_F1": peak_f1_score(z, labels, weights),
            "ACCURACY": float(np.average(pred == labels, weights=weights)),
            "PRECISION": precision,
            "RECALL": recall,
            "F1": (2 * precision * recall / (precision + recall)
                   if precision + recall > 0 else 0.0),
            "LOG_LIKELIHOOD": log_lik,
        })
    elif task == TaskType.LINEAR_REGRESSION:
        resid = z - labels
        mse = float(np.average(resid**2, weights=weights))
        var = float(np.average(
            (labels - np.average(labels, weights=weights))**2,
            weights=weights))
        # Gaussian log-likelihood at sigma^2 = mse.
        log_lik = float(-0.5 * weights.sum()
                        * (np.log(2 * np.pi * max(mse, 1e-300)) + 1))
        out.update({
            "RMSE": float(np.sqrt(mse)),
            "MSE": mse,
            "MAE": float(np.average(np.abs(resid), weights=weights)),
            "R2": 1.0 - mse / var if var > 0 else float("nan"),
            "LOG_LIKELIHOOD": log_lik,
        })
    elif task == TaskType.POISSON_REGRESSION:
        from scipy.special import gammaln

        mu = np.exp(np.clip(z, -500, 30))
        log_lik = float(np.sum(
            weights * (labels * z - mu - gammaln(labels + 1))))
        out.update({
            "POISSON_LOSS": float(np.sum(weights * (mu - labels * z))),
            "RMSE": float(np.sqrt(np.average((mu - labels)**2,
                                             weights=weights))),
            "LOG_LIKELIHOOD": log_lik,
        })
    else:  # smoothed hinge SVM
        t = (2 * labels - 1) * z
        loss = np.where(t <= 0, 0.5 - t,
                        np.where(t < 1, 0.5 * (1 - t)**2, 0.0))
        pred = (z >= 0).astype(float)
        out.update({
            "AUC": area_under_roc_curve(z, labels, weights),
            "PR_AUC": area_under_precision_recall(z, labels, weights),
            "PEAK_F1": peak_f1_score(z, labels, weights),
            "ACCURACY": float(np.average(pred == labels, weights=weights)),
            "SMOOTHED_HINGE_LOSS": float(np.sum(weights * loss)),
        })

    if "LOG_LIKELIHOOD" in out and num_coefficients is not None:
        # AIC = 2k - 2 ln L (ml/Evaluation.scala AIC computation).
        out["AIC"] = 2.0 * num_coefficients - 2.0 * out["LOG_LIKELIHOOD"]
    return out
