"""Full GLM validation metric map (reference: ml/Evaluation.scala:31-194 —
the Spark-MLlib-backed metric bundle the GLM driver logs per λ)."""

from __future__ import annotations

from typing import Dict

import numpy as np

from photon_ml_tpu.constants import POSITIVE_RESPONSE_THRESHOLD
from photon_ml_tpu.evaluation.evaluators import (
    area_under_precision_recall,
    area_under_roc_curve,
    peak_f1_score,
)
from photon_ml_tpu.types import TaskType


def _sigmoid(z):
    return 1 / (1 + np.exp(-np.clip(z, -500, 500)))


def evaluate_glm(task: TaskType, scores, labels, offsets=None, weights=None,
                 num_coefficients: int | None = None) -> Dict[str, float]:
    """Metric map for one model's validation scores (margins, no offset)."""
    scores = np.asarray(scores, np.float64)
    labels = np.asarray(labels, np.float64)
    n = len(scores)
    offsets = np.zeros(n) if offsets is None else np.asarray(offsets)
    weights = np.ones(n) if weights is None else np.asarray(weights)
    z = scores + offsets
    out: Dict[str, float] = {}

    if task == TaskType.LOGISTIC_REGRESSION:
        p = _sigmoid(z)
        eps = 1e-15
        log_lik = float(np.sum(
            weights * (labels * np.log(np.maximum(p, eps))
                       + (1 - labels) * np.log(np.maximum(1 - p, eps)))))
        pred = (p >= POSITIVE_RESPONSE_THRESHOLD).astype(float)
        tp = float(weights[(pred == 1) & (labels == 1)].sum())
        fp = float(weights[(pred == 1) & (labels == 0)].sum())
        fn = float(weights[(pred == 0) & (labels == 1)].sum())
        precision = tp / (tp + fp) if tp + fp > 0 else 0.0
        recall = tp / (tp + fn) if tp + fn > 0 else 0.0
        out.update({
            "AUC": area_under_roc_curve(z, labels, weights),
            "PR_AUC": area_under_precision_recall(z, labels, weights),
            "PEAK_F1": peak_f1_score(z, labels, weights),
            "ACCURACY": float(np.average(pred == labels, weights=weights)),
            "PRECISION": precision,
            "RECALL": recall,
            "F1": (2 * precision * recall / (precision + recall)
                   if precision + recall > 0 else 0.0),
            "LOG_LIKELIHOOD": log_lik,
        })
    elif task == TaskType.LINEAR_REGRESSION:
        resid = z - labels
        mse = float(np.average(resid**2, weights=weights))
        var = float(np.average(
            (labels - np.average(labels, weights=weights))**2,
            weights=weights))
        # Gaussian log-likelihood at sigma^2 = mse.
        log_lik = float(-0.5 * weights.sum()
                        * (np.log(2 * np.pi * max(mse, 1e-300)) + 1))
        out.update({
            "RMSE": float(np.sqrt(mse)),
            "MSE": mse,
            "MAE": float(np.average(np.abs(resid), weights=weights)),
            "R2": 1.0 - mse / var if var > 0 else float("nan"),
            "LOG_LIKELIHOOD": log_lik,
        })
    elif task == TaskType.POISSON_REGRESSION:
        from scipy.special import gammaln

        mu = np.exp(np.clip(z, -500, 30))
        log_lik = float(np.sum(
            weights * (labels * z - mu - gammaln(labels + 1))))
        out.update({
            "POISSON_LOSS": float(np.sum(weights * (mu - labels * z))),
            "RMSE": float(np.sqrt(np.average((mu - labels)**2,
                                             weights=weights))),
            "LOG_LIKELIHOOD": log_lik,
        })
    else:  # smoothed hinge SVM
        t = (2 * labels - 1) * z
        loss = np.where(t <= 0, 0.5 - t,
                        np.where(t < 1, 0.5 * (1 - t)**2, 0.0))
        pred = (z >= 0).astype(float)
        out.update({
            "AUC": area_under_roc_curve(z, labels, weights),
            "PR_AUC": area_under_precision_recall(z, labels, weights),
            "PEAK_F1": peak_f1_score(z, labels, weights),
            "ACCURACY": float(np.average(pred == labels, weights=weights)),
            "SMOOTHED_HINGE_LOSS": float(np.sum(weights * loss)),
        })

    if "LOG_LIKELIHOOD" in out and num_coefficients is not None:
        # AIC = 2k - 2 ln L (ml/Evaluation.scala AIC computation).
        out["AIC"] = 2.0 * num_coefficients - 2.0 * out["LOG_LIKELIHOOD"]
    return out


class StreamedEvalAccumulator:
    """Bounded-memory evaluation over a streamed scoring pipeline: per
    scored batch, retain ONLY the evaluation columns (scores, labels,
    offsets, weights, and the entity-id names the requested id types
    need) — never features — then evaluate once at the end. Shared by
    `game_scoring_driver --stream` and `game_training_driver
    --stream-train` validation, so the streamed-evaluation semantics
    cannot diverge between the two drivers."""

    def __init__(self, id_types=()):
        self.id_types = tuple(id_types)
        self._scores: list = []
        self._responses: list = []
        self._offsets: list = []
        self._weights: list = []
        self._ids = {t: [] for t in self.id_types}
        self.rows = 0

    def add(self, dataset, scores) -> None:
        self._scores.append(np.asarray(scores))
        self._responses.append(dataset.responses)
        self._offsets.append(dataset.offsets)
        self._weights.append(dataset.weights)
        for t in self.id_types:
            col = dataset.id_columns[t]
            self._ids[t].append(col.vocabulary[col.codes])
        self.rows += dataset.num_rows

    def metrics(self, evaluators) -> Dict[str, float]:
        """Metric map from the accumulated columns; {} when the stream
        yielded no rows (an empty validation input must degrade to empty
        metrics, not crash after a long training run)."""
        if not evaluators or not self._responses:
            return {}
        from photon_ml_tpu.data.game_data import GameDataset

        eval_data = GameDataset.build(
            responses=np.concatenate(self._responses),
            feature_shards={},
            ids={t: np.concatenate(v) for t, v in self._ids.items()},
            offsets=np.concatenate(self._offsets),
            weights=np.concatenate(self._weights))
        scores_all = np.concatenate(self._scores)
        return {ev.name: ev.evaluate_dataset(scores_all, eval_data)
                for ev in evaluators}
