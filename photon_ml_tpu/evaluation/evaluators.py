"""Evaluators: one scalar metric over (scores, labels, offsets, weights).

Reference: ml/evaluation/Evaluator.scala:24-78 and the concrete evaluators in
ml/evaluation/. Scores arrive as dense vectors aligned with the dataset's row
order (no joins). ``better_than`` encodes per-metric ordering exactly as the
reference does (higher-is-better for AUC/precision, lower for losses).

Sharded evaluators group rows by an id column and average the local metric
over groups (ml/evaluation/ShardedAreaUnderROCCurveEvaluator.scala,
ShardedPrecisionAtKEvaluator.scala).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

import numpy as np


def _as_np(a):
    return np.asarray(a, np.float64)


@dataclasses.dataclass(frozen=True)
class MetricMetadata:
    """Metadata about a metric (reference: ml/metric/MetricMetadata.scala).

    ``higher_is_better`` plays the role of the reference's
    worstToBestOrdering; ``value_range`` its rangeOption.
    """

    name: str
    description: str
    higher_is_better: bool
    value_range: Optional[tuple] = None  # (min, max)

    def to_dict(self) -> dict:
        return {"description": self.description,
                "higherIsBetter": self.higher_is_better,
                "range": self.value_range}


# Registry covering every metric emitted by evaluate_glm and the evaluator
# family. Drivers attach these to their metric reports (the reference binds
# MetricMetadata to each logged metric in ml/Evaluation.scala).
METRIC_METADATA = {
    m.name: m for m in [
        MetricMetadata("AUC", "area under the ROC curve", True, (0.0, 1.0)),
        MetricMetadata("ACCURACY", "weighted classification accuracy", True,
                       (0.0, 1.0)),
        MetricMetadata("PRECISION", "precision at the response threshold",
                       True, (0.0, 1.0)),
        MetricMetadata("RECALL", "recall at the response threshold", True,
                       (0.0, 1.0)),
        MetricMetadata("F1", "harmonic mean of precision and recall", True,
                       (0.0, 1.0)),
        MetricMetadata("PR_AUC", "area under the precision/recall curve",
                       True, (0.0, 1.0)),
        MetricMetadata("PEAK_F1", "max F1 over score thresholds", True,
                       (0.0, 1.0)),
        MetricMetadata("LOG_LIKELIHOOD", "data log-likelihood", True),
        MetricMetadata("AIC", "Akaike information criterion", False),
        MetricMetadata("RMSE", "root mean squared error", False),
        MetricMetadata("MSE", "mean squared error", False),
        MetricMetadata("MAE", "mean absolute error", False),
        MetricMetadata("R2", "coefficient of determination", True),
        MetricMetadata("POISSON_LOSS", "Poisson negative log-likelihood",
                       False),
        MetricMetadata("LOGISTIC_LOSS", "logistic loss", False),
        MetricMetadata("SQUARED_LOSS", "squared loss", False),
        MetricMetadata("SMOOTHED_HINGE_LOSS", "Rennie smoothed hinge loss",
                       False),
    ]
}


def metadata_for(evaluator: "Evaluator") -> MetricMetadata:
    """MetricMetadata for an evaluator (sharded evaluators inherit the base
    metric's metadata; PRECISION@k is synthesized)."""
    base = evaluator.name.split(":")[0].upper()
    if base in METRIC_METADATA:
        meta = METRIC_METADATA[base]
        return dataclasses.replace(meta, name=evaluator.name)
    if base.startswith("PRECISION@"):
        return MetricMetadata(evaluator.name, "precision in the top k",
                              True, (0.0, 1.0))
    return MetricMetadata(
        name=evaluator.name,
        description=evaluator.name,
        higher_is_better=evaluator.higher_is_better,
    )


@dataclasses.dataclass(frozen=True)
class Evaluator:
    name: str

    def evaluate(self, scores, labels, offsets=None, weights=None,
                 data=None) -> float:
        scores = _as_np(scores)
        n = len(scores)
        labels = _as_np(labels)
        offsets = np.zeros(n) if offsets is None else _as_np(offsets)
        weights = np.ones(n) if weights is None else _as_np(weights)
        return self._evaluate(scores + offsets, labels, weights, data)

    def evaluate_dataset(self, scores, data) -> float:
        return self.evaluate(scores, data.responses, data.offsets,
                             data.weights, data=data)

    def _evaluate(self, pred, labels, weights, data) -> float:
        raise NotImplementedError

    def better_than(self, a: float, b: Optional[float]) -> bool:
        if b is None:
            return True
        return a > b if self.higher_is_better else a < b

    @property
    def higher_is_better(self) -> bool:
        return False


def area_under_roc_curve(scores, labels, weights=None) -> float:
    """Weighted AUC via the Mann-Whitney statistic with midrank ties
    (equivalent to MLlib BinaryClassificationMetrics' trapezoidal ROC)."""
    scores = _as_np(scores)
    labels = _as_np(labels)
    w = np.ones(len(scores)) if weights is None else _as_np(weights)
    pos = labels >= 0.5
    w_pos = w[pos].sum()
    w_neg = w[~pos].sum()
    if w_pos == 0 or w_neg == 0:
        return float("nan")
    order = np.argsort(scores, kind="mergesort")
    s = scores[order]
    ww = w[order]
    # Weighted midranks with ties, vectorized: rank = cum-weight strictly
    # below the tie block + half the block's weight (reduceat over tie-block
    # starts replaces the per-block python loop).
    cw_excl = np.cumsum(ww) - ww
    new_block = np.r_[True, s[1:] != s[:-1]]
    bstart = np.flatnonzero(new_block)
    bid = np.cumsum(new_block) - 1
    bw = np.add.reduceat(ww, bstart)
    ranks = cw_excl[bstart][bid] + bw[bid] / 2.0
    r = np.empty(len(s))
    r[order] = ranks
    u = (w[pos] * r[pos]).sum() - w_pos * w_pos / 2.0
    return float(u / (w_pos * w_neg))


def _pr_curve(scores, labels, weights=None):
    """Weighted precision/recall points at each distinct-score threshold,
    ordered by increasing recall (MLlib BinaryClassificationMetrics
    convention: the curve is prepended with (0, p_first))."""
    scores = _as_np(scores)
    labels = _as_np(labels)
    w = np.ones(len(scores)) if weights is None else _as_np(weights)
    pos = (labels >= 0.5).astype(np.float64)
    total_pos = (w * pos).sum()
    if total_pos == 0:
        return None
    order = np.argsort(-scores, kind="mergesort")
    s = scores[order]
    tp = np.cumsum(w[order] * pos[order])
    pred = np.cumsum(w[order])
    # Collapse tie blocks: keep the LAST index of each distinct score.
    last = np.r_[s[1:] != s[:-1], True]
    tp, pred = tp[last], pred[last]
    precision = tp / pred
    recall = tp / total_pos
    return precision, recall


def area_under_precision_recall(scores, labels, weights=None) -> float:
    """Weighted PR-AUC (trapezoidal; curve starts at (0, p_first) like
    MLlib areaUnderPR — reference metric AREA_UNDER_PRECISION_RECALL,
    ml/Evaluation.scala:81)."""
    curve = _pr_curve(scores, labels, weights)
    if curve is None:
        return float("nan")
    precision, recall = curve
    p = np.r_[precision[0], precision]
    r = np.r_[0.0, recall]
    # np.trapezoid is NumPy >= 2.0; np.trapz is its pre-2.0 name.
    trapezoid = getattr(np, "trapezoid", None) or np.trapz
    return float(trapezoid(p, r))


def peak_f1_score(scores, labels, weights=None) -> float:
    """Max F1 over score thresholds (reference PEAK_F1_SCORE,
    ml/Evaluation.scala:83)."""
    curve = _pr_curve(scores, labels, weights)
    if curve is None:
        return float("nan")
    precision, recall = curve
    denom = precision + recall
    f1 = np.where(denom > 0, 2 * precision * recall / np.maximum(denom, 1e-300), 0.0)
    return float(f1.max())


@dataclasses.dataclass(frozen=True)
class AreaUnderROCCurveEvaluator(Evaluator):
    name: str = "AUC"

    @property
    def higher_is_better(self) -> bool:
        return True

    def _evaluate(self, pred, labels, weights, data) -> float:
        return area_under_roc_curve(pred, labels, weights)


@dataclasses.dataclass(frozen=True)
class RMSEEvaluator(Evaluator):
    name: str = "RMSE"

    def _evaluate(self, pred, labels, weights, data) -> float:
        return float(np.sqrt(
            np.sum(weights * (pred - labels) ** 2) / np.sum(weights)))


def _logistic_loss_np(z, y):
    return np.log1p(np.exp(-np.abs(z))) + np.maximum(z, 0) - y * z


@dataclasses.dataclass(frozen=True)
class LogisticLossEvaluator(Evaluator):
    name: str = "LOGISTIC_LOSS"

    def _evaluate(self, pred, labels, weights, data) -> float:
        return float(np.sum(weights * _logistic_loss_np(pred, labels)))


@dataclasses.dataclass(frozen=True)
class PoissonLossEvaluator(Evaluator):
    name: str = "POISSON_LOSS"

    def _evaluate(self, pred, labels, weights, data) -> float:
        return float(np.sum(weights * (np.exp(pred) - labels * pred)))


@dataclasses.dataclass(frozen=True)
class SquaredLossEvaluator(Evaluator):
    name: str = "SQUARED_LOSS"

    def _evaluate(self, pred, labels, weights, data) -> float:
        return float(np.sum(weights * 0.5 * (pred - labels) ** 2))


@dataclasses.dataclass(frozen=True)
class SmoothedHingeLossEvaluator(Evaluator):
    name: str = "SMOOTHED_HINGE_LOSS"

    def _evaluate(self, pred, labels, weights, data) -> float:
        t = (2 * labels - 1) * pred
        loss = np.where(t <= 0, 0.5 - t,
                        np.where(t < 1, 0.5 * (1 - t) ** 2, 0.0))
        return float(np.sum(weights * loss))


class _ShardedEvaluator(Evaluator):
    """Group rows by an id column; average the local metric over groups.

    Both sharded metrics are computed SORT-ONCE + segmented (np.lexsort +
    reduceat over group/tie-block starts) — one pass for any number of
    groups, replacing per-group python loops that dominated validation
    wallclock at 5k-1M groups (reference per-group path:
    ml/evaluation/ShardedAreaUnderROCCurveEvaluator.scala +
    AreaUnderROCCurveLocalEvaluator.scala)."""

    id_type: str

    def _codes(self, data) -> np.ndarray:
        return data.id_columns[self.id_type].codes


def sharded_auc(pred, labels, weights, codes) -> float:
    """Mean of per-group weighted AUCs (midrank ties), vectorized.

    Groups with a single class are skipped, matching the per-group NaN
    filter of the reference's sharded evaluator."""
    order = np.lexsort((pred, codes))
    g = np.asarray(codes)[order]
    s = np.asarray(pred)[order]
    w = np.asarray(weights, np.float64)[order]
    pos = np.asarray(labels)[order] >= 0.5
    if len(g) == 0:
        return float("nan")

    new_group = np.r_[True, g[1:] != g[:-1]]
    gstart = np.flatnonzero(new_group)
    gid = np.cumsum(new_group) - 1
    # Within-group cum weight strictly below each row.
    cw = np.cumsum(w)
    cw_excl = cw - w
    rel_excl = cw_excl - cw_excl[gstart][gid]
    # Tie blocks: same group AND same score.
    new_block = np.r_[True, (g[1:] != g[:-1]) | (s[1:] != s[:-1])]
    bstart = np.flatnonzero(new_block)
    bid = np.cumsum(new_block) - 1
    bw = np.add.reduceat(w, bstart)
    rank = rel_excl[bstart][bid] + bw[bid] / 2.0

    w_pos = np.add.reduceat(np.where(pos, w, 0.0), gstart)
    w_neg = np.add.reduceat(np.where(pos, 0.0, w), gstart)
    u = np.add.reduceat(np.where(pos, w * rank, 0.0), gstart) \
        - w_pos * w_pos / 2.0
    valid = (w_pos > 0) & (w_neg > 0)
    if not valid.any():
        return float("nan")
    return float(np.mean(u[valid] / (w_pos[valid] * w_neg[valid])))


def sharded_precision_at_k(pred, labels, codes, k: int) -> float:
    """Mean of per-group precision@k (stable descending score order),
    vectorized: one lexsort + positional mask + segmented sums."""
    pred = np.asarray(pred)
    codes = np.asarray(codes)
    order = np.lexsort((-pred, codes))
    g = codes[order]
    hit = (np.asarray(labels)[order] >= 0.5).astype(np.float64)
    n = len(g)
    if n == 0:
        return float("nan")
    new_group = np.r_[True, g[1:] != g[:-1]]
    gstart = np.flatnonzero(new_group)
    gid = np.cumsum(new_group) - 1
    in_top = (np.arange(n) - gstart[gid]) < k
    hits = np.add.reduceat(np.where(in_top, hit, 0.0), gstart)
    sizes = np.diff(np.r_[gstart, n])
    return float(np.mean(hits / np.minimum(k, sizes)))


@dataclasses.dataclass(frozen=True)
class ShardedAreaUnderROCCurveEvaluator(_ShardedEvaluator):
    id_type: str = ""
    name: str = ""

    def __post_init__(self):
        if not self.name:
            object.__setattr__(self, "name", f"AUC:{self.id_type}")

    @property
    def higher_is_better(self) -> bool:
        return True

    def _evaluate(self, pred, labels, weights, data) -> float:
        if data is None:
            raise ValueError("sharded evaluators need the dataset (id columns)")
        return sharded_auc(pred, labels, weights, self._codes(data))


@dataclasses.dataclass(frozen=True)
class ShardedPrecisionAtKEvaluator(_ShardedEvaluator):
    k: int = 1
    id_type: str = ""
    name: str = ""

    def __post_init__(self):
        if not self.name:
            object.__setattr__(self, "name", f"PRECISION@{self.k}:{self.id_type}")

    @property
    def higher_is_better(self) -> bool:
        return True

    def _evaluate(self, pred, labels, weights, data) -> float:
        if data is None:
            raise ValueError("sharded evaluators need the dataset (id columns)")
        return sharded_precision_at_k(pred, labels, self._codes(data), self.k)


_PLAIN = {
    "AUC": AreaUnderROCCurveEvaluator,
    "RMSE": RMSEEvaluator,
    "LOGISTIC_LOSS": LogisticLossEvaluator,
    "POISSON_LOSS": PoissonLossEvaluator,
    "SQUARED_LOSS": SquaredLossEvaluator,
    "SMOOTHED_HINGE_LOSS": SmoothedHingeLossEvaluator,
}


def build_evaluator(spec: str) -> Evaluator:
    """Parse an evaluator spec (reference: Evaluator.buildEvaluator +
    EvaluatorType/ShardedEvaluatorType parsing):
      'AUC' | 'RMSE' | '<LOSS>' | 'AUC:idType' | 'PRECISION@k:idType'
    """
    s = spec.strip()
    up = s.upper()
    if up in _PLAIN:
        return _PLAIN[up]()
    m = re.fullmatch(r"AUC:(\w+)", s, re.IGNORECASE)
    if m:
        return ShardedAreaUnderROCCurveEvaluator(id_type=m.group(1))
    m = re.fullmatch(r"PRECISION@(\d+):(\w+)", s, re.IGNORECASE)
    if m:
        return ShardedPrecisionAtKEvaluator(k=int(m.group(1)),
                                            id_type=m.group(2))
    raise ValueError(f"unknown evaluator spec {spec!r}")
