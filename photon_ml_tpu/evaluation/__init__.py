"""Evaluators for validation metrics."""

from photon_ml_tpu.evaluation.evaluators import (
    Evaluator,
    METRIC_METADATA,
    MetricMetadata,
    metadata_for,
    AreaUnderROCCurveEvaluator,
    RMSEEvaluator,
    LogisticLossEvaluator,
    PoissonLossEvaluator,
    SquaredLossEvaluator,
    SmoothedHingeLossEvaluator,
    ShardedAreaUnderROCCurveEvaluator,
    ShardedPrecisionAtKEvaluator,
    build_evaluator,
)

__all__ = [
    "Evaluator",
    "METRIC_METADATA",
    "MetricMetadata",
    "metadata_for",
    "AreaUnderROCCurveEvaluator",
    "RMSEEvaluator",
    "LogisticLossEvaluator",
    "PoissonLossEvaluator",
    "SquaredLossEvaluator",
    "SmoothedHingeLossEvaluator",
    "ShardedAreaUnderROCCurveEvaluator",
    "ShardedPrecisionAtKEvaluator",
    "build_evaluator",
]
