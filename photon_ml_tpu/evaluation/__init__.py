"""Evaluators for validation metrics."""

from photon_ml_tpu.evaluation.evaluators import (
    Evaluator,
    AreaUnderROCCurveEvaluator,
    RMSEEvaluator,
    LogisticLossEvaluator,
    PoissonLossEvaluator,
    SquaredLossEvaluator,
    SmoothedHingeLossEvaluator,
    ShardedAreaUnderROCCurveEvaluator,
    ShardedPrecisionAtKEvaluator,
    build_evaluator,
)

__all__ = [
    "Evaluator",
    "AreaUnderROCCurveEvaluator",
    "RMSEEvaluator",
    "LogisticLossEvaluator",
    "PoissonLossEvaluator",
    "SquaredLossEvaluator",
    "SmoothedHingeLossEvaluator",
    "ShardedAreaUnderROCCurveEvaluator",
    "ShardedPrecisionAtKEvaluator",
    "build_evaluator",
]
