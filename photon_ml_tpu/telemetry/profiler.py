"""Compile/device-time profiler for the serving executable population —
the per-kernel cost-accounting discipline of the TPU distributed
linear-algebra literature (PAPERS.md), applied to the bucket ladder.

The :class:`~photon_ml_tpu.serving.engine.ExecutableCache` already knows
every executable the process ever built; what it could not answer is
"where did the compile seconds go" and "what does one dispatch of bucket
r4096 cost on the device". This profiler records, per cache key:

- **lower wall time + static cost analysis** at build: one
  ``fn.lower(*args)`` pass (tracing only — it does NOT compile, does not
  touch the jit dispatch cache, and therefore changes no TracingGuard
  count) whose ``Lowered.cost_analysis()`` yields FLOPs / bytes-accessed
  estimates where the backend provides them;
- **first-call wall time**: the first invocation of a jitted executable
  runs trace + XLA compile synchronously before enqueueing, so timing it
  at the dispatch site is an honest compile-wall proxy with NO added
  synchronization (everything after the first call is enqueue-only);
- **per-bucket dispatch wall**: dispatch-to-settle seconds observed at
  the EXISTING ``block_until_ready`` boundary (the ``InFlightWindow``
  settle — never a new sync), per rows-bucket, mirrored into registry
  histograms ``serving.bucket.r<rows>.dispatch_seconds`` and kept in
  always-live local accumulators (like the engines' ``_stats``). With
  pipeline depth > 1 the settle may lag the device finishing, so the
  number is an upper bound on device time — the same caveat as the
  ``device_wait`` span, documented in docs/OBSERVABILITY.md.

``table()`` renders the roofline-style per-bucket view served on
``/statusz`` and written into metrics.json: per key, compile economics
(lower/first-call seconds, FLOPs, bytes) next to steady-state dispatch
statistics (count, mean/min/max seconds, est. FLOP/s from the static
FLOP count over the mean dispatch wall).
"""

from __future__ import annotations

import importlib
import threading
import time
from typing import Dict, Optional

_reg = importlib.import_module("photon_ml_tpu.telemetry.registry")


def _cost_numbers(lowered) -> Dict[str, float]:
    """FLOPs / bytes-accessed from a ``jax.stages.Lowered``, where the
    backend provides them (CPU and TPU do; the estimate is
    pre-optimization HLO). Absent/failed analysis degrades to {}."""
    try:
        cost = lowered.cost_analysis()
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return {}
    out = {}
    if "flops" in cost:
        out["flops"] = float(cost["flops"])
    if "bytes accessed" in cost:
        out["bytes_accessed"] = float(cost["bytes accessed"])
    return out


class ExecutableProfiler:
    """Per-key build economics + per-bucket dispatch timing for one
    :class:`ExecutableCache` population (shared across every engine on
    that cache, so a tenancy's whole executable population lands in one
    table). All local state is plain dicts under one lock — live even
    while telemetry is disabled, like the engines' ``_stats``; only the
    registry histogram mirrors go quiet."""

    def __init__(self):
        self._lock = threading.Lock()
        self._builds: Dict[str, dict] = {}
        self._dispatch: Dict[int, dict] = {}
        self._hists: Dict[int, object] = {}

    # -- build-time profiling ----------------------------------------------

    def profile_build(self, key, fn, args,
                      rows_bucket: Optional[int] = None) -> None:
        """Record one cache build: time ``fn.lower(*args)`` and harvest
        its cost analysis. Tracing-only (no XLA compile happens here; the
        first real call still compiles exactly once), so the per-key cost
        is one extra trace — small against the compile it annotates.
        ``rows_bucket`` is the key's rows component, passed structurally
        by the caller (who holds the real key tuple) so ``table()`` can
        join builds onto dispatch rows without parsing key reprs."""
        entry = {"lower_s": None, "first_call_s": None,
                 "rows_bucket": (int(rows_bucket)
                                 if rows_bucket is not None else None)}
        t0 = time.perf_counter()
        try:
            lowered = fn.lower(*args)
            entry["lower_s"] = time.perf_counter() - t0
            entry.update(_cost_numbers(lowered))
        except Exception:  # noqa: BLE001 — profiling must not fail a build
            pass
        with self._lock:
            self._builds[repr(key)] = entry

    def record_first_call(self, key, seconds: float) -> None:
        """First-invocation wall time (trace + XLA compile + enqueue) —
        the compile-wall proxy, timed at the dispatch site with no added
        sync."""
        with self._lock:
            entry = self._builds.setdefault(
                repr(key), {"lower_s": None, "first_call_s": None,
                            "rows_bucket": None})
            entry["first_call_s"] = float(seconds)

    # -- dispatch-time profiling -------------------------------------------

    def record_dispatch(self, rows_bucket: int, seconds: float,
                        rows: int) -> None:
        """One dispatch-to-settle observation for ``rows_bucket``,
        measured at the existing ``InFlightWindow`` settle boundary."""
        rb = int(rows_bucket)
        s = float(seconds)
        with self._lock:
            d = self._dispatch.get(rb)
            if d is None:
                d = self._dispatch[rb] = {
                    "count": 0, "sum_s": 0.0, "min_s": s, "max_s": s,
                    "rows": 0}
                # Lazy per-bucket registry histogram (bounded by ladder
                # size; dynamic name — fragments stay lint-legal).
                self._hists[rb] = _reg.registry().histogram(
                    f"serving.bucket.r{rb}.dispatch_seconds")
            d["count"] += 1
            d["sum_s"] += s
            d["min_s"] = min(d["min_s"], s)
            d["max_s"] = max(d["max_s"], s)
            d["rows"] += int(rows)
            hist = self._hists[rb]
        hist.observe(s)

    # -- reporting ---------------------------------------------------------

    def table(self) -> dict:
        """The /statusz + metrics.json per-bucket compile/device-time
        table: ``builds`` (per cache key) and ``dispatch`` (per rows
        bucket, with est_flops_per_sec where a build on that key
        reported FLOPs — roofline-style: static FLOPs over mean
        dispatch-to-settle wall, an UPPER-bound denominator and so a
        LOWER-bound rate)."""
        with self._lock:
            builds = {k: dict(v) for k, v in self._builds.items()}
            dispatch = {k: dict(v) for k, v in self._dispatch.items()}
        # FLOPs per rows-bucket (recorded structurally at build time);
        # several nnz buckets share a rows bucket — take the max (the
        # widest executable bounds the rate).
        flops_by_rb: Dict[int, float] = {}
        for b in builds.values():
            fl = b.get("flops")
            rb = b.get("rows_bucket")
            if fl is None or rb is None:
                continue
            flops_by_rb[rb] = max(flops_by_rb.get(rb, 0.0), fl)
        out_dispatch = {}
        for rb, d in sorted(dispatch.items()):
            mean_s = d["sum_s"] / d["count"] if d["count"] else None
            row = {
                "rows_bucket": rb,
                "dispatches": d["count"],
                "rows": d["rows"],
                "mean_s": mean_s,
                "min_s": d["min_s"],
                "max_s": d["max_s"],
            }
            fl = flops_by_rb.get(rb)
            if fl is not None and mean_s:
                row["est_flops_per_sec"] = fl / mean_s
            out_dispatch[f"r{rb}"] = row
        return {"builds": builds, "dispatch": out_dispatch}

    def reset(self) -> None:
        with self._lock:
            self._builds.clear()
            self._dispatch.clear()
            self._hists.clear()
