"""Fleet observability federation: one pane of glass over N processes.

The live plane (exposition.py, PR 9/11/13) is strictly per-process, but
the system is multi-process everywhere it scales: forced-N mesh training
children, per-mode bench subprocesses, and the ROADMAP item-3 target of
N serving replicas behind a router. This module merges those planes:

- :func:`registry_snapshot` serializes one process's registry into the
  canonical ``photon.obs.snapshot.v1`` schema served on ``/snapshotz``:
  counters, gauges (value + call count), FULL raw histogram bucket
  states with exemplars (:meth:`Histogram.state`), sketch states, SLO
  spec strings, tail-sampled traces, stage attribution, and process
  metadata (pid / role / start_unix / labels).
- :func:`merge_snapshots` folds any number of snapshots into a
  :class:`FleetView` with deterministic semantics: counters SUM;
  histograms add bucket-wise — EXACT, never a re-bin, because every
  process shares the fixed ladder (registry.py); gauges merge by the
  declared per-family policy (:data:`GAUGE_MERGE_POLICIES`, lint-backed
  by dev_scripts/metric_names.py); sketches merge via their existing
  deterministic merges (sketches.py) in sorted-peer order, so the
  result is independent of scrape arrival order; trace tails union with
  per-process attribution; SLOs are re-evaluated STATELESSLY against
  the merged registry (slo.evaluate_specs) — because counters sum and
  buckets add exactly, the fleet burn rate is the true whole-fleet
  number, not an average of per-process burns.
- :class:`FleetAggregator` discovers peers from explicit URLs and/or
  ``obs_port`` descriptor files (see :func:`read_obs_descriptor`),
  pulls ``/snapshotz`` on an interval, tracks staleness (a dead child
  is marked stale, its LAST snapshot is retained, and the fleet plane
  degrades rather than crashes), and serves merged ``/metrics``,
  ``/statusz``, ``/tracez``, ``/distz`` — plus its own ``/snapshotz``
  in the same schema, so aggregators compose hierarchically (Snap
  ML-style roll-up, PAPERS.md).

The ``fleet.`` metric prefix is RESERVED for this module (peers may not
emit it — lint rule ``fleet-prefix-reserved``). The aggregator's own
``fleet.*`` series come from plain internal state synthesized into a
pseudo-peer snapshot, never from the process-global registry: the
aggregator can ride inside a bench or driver process without polluting
that process's plane or depending on the telemetry enable flag.
"""

from __future__ import annotations

import dataclasses
import importlib
import json
import os
import re
import socket
import threading
import time
import urllib.request
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_reg = importlib.import_module("photon_ml_tpu.telemetry.registry")
_spans = importlib.import_module("photon_ml_tpu.telemetry.spans")
_tracectx = importlib.import_module("photon_ml_tpu.telemetry.tracectx")
_sketches = importlib.import_module("photon_ml_tpu.telemetry.sketches")
_slo = importlib.import_module("photon_ml_tpu.telemetry.slo")
_expo = importlib.import_module("photon_ml_tpu.telemetry.exposition")

SNAPSHOT_SCHEMA = "photon.obs.snapshot.v1"

#: How many traces each merged tail ring retains (newest first): the
#: fleet view is a debugging aid, not an archive.
MERGED_TRACE_RING = 128

# ---------------------------------------------------------------------------
# Gauge merge policies
# ---------------------------------------------------------------------------

#: Per-family gauge merge policy. Counters and histograms have ONE
#: correct merge (sum / bucket-wise add); gauges do not — "bytes held"
#: sums across processes, "uptime" does not. Keys are exact dotted
#: names, ``prefix.`` entries (trailing dot, matched by startswith) or
#: ``.suffix`` entries (leading dot, matched by endswith); resolution
#: is exact > longest suffix > longest prefix > default ``last``.
#: dev_scripts/metric_names.py (rule ``gauge-merge-policy``) requires
#: every registered gauge family to resolve to a declared entry, so a
#: new gauge cannot silently pick up ``last`` semantics.
#:
#: ``last`` = the value from the peer with the newest snapshot_unix
#: among peers that ever set the gauge (tie → greatest peer id) —
#: deterministic, not arrival-order "last write wins".
GAUGE_MERGE_POLICIES: Dict[str, str] = {
    # Process lifetime gauges: fleet uptime is the OLDEST process.
    "process.uptime_seconds": "max",
    "process.heartbeat_unix_time": "max",
    # Training-data distribution headline gauges (data/distmon.py):
    # volumes sum, statistical headlines (means/percentiles) keep the
    # newest writer — cross-process means need the sketches, which the
    # fleet merges exactly on /distz.
    "data.dist.rows": "sum",
    "data.dist.batches": "sum",
    "data.dist.": "last",
    # Cache/residency byte counts are per-process holdings: sum.
    "data.factor_cache.": "sum",
    "data.shard_cache.": "sum",
    # Aggregator-reserved namespace (pseudo-peer snapshots only).
    "fleet.": "last",
    # SLO burn + drift scores: the fleet is as burnt as its worst
    # member (alerts must not average away a bad replica).
    ".burn_rate": "max",
    ".score_drift_psi": "max",
    ".score_drift_ks": "max",
    ".score_dist_rows": "sum",
    # Batched λ-grid: in-flight grid points sum across processes (the
    # fleet-wide count of λ points still iterating).
    "training.grid.active_points": "sum",
    # 2-D mesh extents (ops/sharded_objective.py): each process trains
    # on its own mesh; the fleet view keeps the newest writer rather
    # than summing axis extents into a meaningless total. (The
    # training.mesh.*_transfer_bytes series are counters and sum.)
    "training.mesh.": "last",
    # Network front door (serving/netserver.py): connections held open
    # are per-process holdings — the fleet has the sum. (Everything
    # else under serving.net.* is a counter; lint rule counter-family.)
    "serving.net.open_connections": "sum",
    # SLO-adaptive admission controller state (serving/adaptive.py):
    # each replica steers its own knobs; the merged view keeps the
    # newest writer (burn_rate maxes via the .burn_rate entry above —
    # the fleet is as burnt as its worst member).
    "serving.adaptive.": "last",
}

_VALID_POLICIES = ("sum", "max", "last")


def gauge_merge_policy(name: str) -> str:
    """Resolve the merge policy for gauge family ``name`` (docstring of
    :data:`GAUGE_MERGE_POLICIES` for precedence)."""
    hit = GAUGE_MERGE_POLICIES.get(name)
    if hit is not None:
        return hit
    best = None
    for key, pol in GAUGE_MERGE_POLICIES.items():
        if key.startswith(".") and name.endswith(key):
            if best is None or len(key) > len(best[0]):
                best = (key, pol)
    if best is not None:
        return best[1]
    for key, pol in GAUGE_MERGE_POLICIES.items():
        if key.endswith(".") and name.startswith(key):
            if best is None or len(key) > len(best[0]):
                best = (key, pol)
    return best[1] if best is not None else "last"


# ---------------------------------------------------------------------------
# Snapshot serialization
# ---------------------------------------------------------------------------

def registry_snapshot(role: str = "process",
                      labels: Optional[Dict[str, str]] = None,
                      slo_specs: Optional[Sequence[str]] = None,
                      sketch_providers: Optional[
                          Dict[str, Callable[[], dict]]] = None,
                      start_unix: Optional[float] = None,
                      registry=None) -> dict:
    """Serialize the registry (default: the process-global one) into
    the canonical snapshot schema. Histograms export their RAW
    per-bucket counts (:meth:`Histogram.state`) so the fleet merge is
    bucket-wise addition, exact by construction. Sketch providers
    (``{key: state_dict}`` callables) contribute under ``sketches``; a
    provider that raises reports its error inline — a snapshot must
    never fail because one sketch source is mid-teardown."""
    reg = registry if registry is not None else _reg.registry()
    counters, gauges, histograms = reg.metrics()
    sketches: Dict[str, dict] = {}
    sketch_errors: Dict[str, str] = {}
    for pname, fn in sorted((sketch_providers or {}).items()):
        try:
            sketches[pname] = {str(k): v for k, v in fn().items()}
        except Exception as e:  # noqa: BLE001 — report, don't fail
            sketch_errors[pname] = f"{type(e).__name__}: {e}"
    snap = {
        "schema": SNAPSHOT_SCHEMA,
        "process": {
            "pid": os.getpid(),
            "role": role,
            "host": socket.gethostname(),
            "start_unix": start_unix,
            "snapshot_unix": time.time(),
            "labels": dict(labels or {}),
        },
        "counters": {n: c.value for n, c in sorted(counters.items())},
        "gauges": {n: {"value": g.value, "calls": g.calls}
                   for n, g in sorted(gauges.items())},
        "histograms": {n: h.state()
                       for n, h in sorted(histograms.items())},
        "sketches": sketches,
        "slo_specs": [str(s) for s in (slo_specs or [])],
        "traces": _tracectx.trace_tail().snapshot(),
        "stages": _spans.stage_attribution(),
    }
    if sketch_errors:
        snap["sketch_errors"] = sketch_errors
    return snap


# ---------------------------------------------------------------------------
# Merged registry (duck-typed read-only twins)
# ---------------------------------------------------------------------------

class _MergedCounter:
    """Read-only counter twin: quacks like registry.Counter for the
    exposition renderer and SLO math."""

    __slots__ = ("name", "value", "calls")

    def __init__(self, name: str, value=0):
        self.name = name
        self.value = value
        self.calls = 0


class _MergedGauge:
    __slots__ = ("name", "value", "calls", "policy")

    def __init__(self, name: str, value=0.0, calls=0, policy="last"):
        self.name = name
        self.value = value
        self.calls = calls
        self.policy = policy


class _MergedHistogram:
    """Read-only histogram twin rebuilt from merged raw-bucket state;
    implements the read surface consumers use (exposition_state,
    exemplars, quantile, snapshot, state)."""

    def __init__(self, name: str, state: dict):
        self.name = name
        self._bounds = tuple(float(b) for b in state["bounds"])
        self._counts = [int(c) for c in state["counts"]]
        self._count = int(state["count"])
        self._sum = float(state["sum"])
        self._min = state["min"]
        self._max = state["max"]
        self._ex = {int(i): tuple(e)
                    for i, e in (state.get("exemplars") or {}).items()}

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def exposition_state(self):
        cum, c = [], 0
        for v in self._counts[:-1]:
            c += v
            cum.append(c)
        return self._bounds, cum, self._count, self._sum

    def exemplars(self) -> dict:
        out = {}
        for i, e in self._ex.items():
            key = (self._bounds[i] if i < len(self._bounds) else "+inf")
            out[key] = e
        return out

    def quantile(self, q: float):
        # Same interpolation as registry.Histogram.quantile, over the
        # merged raw buckets and the fleet-wide min/max.
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        if self._count == 0:
            return None
        target = q * self._count
        cum = 0
        for i, c in enumerate(self._counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = self._bounds[i - 1] if i > 0 else self._min
                hi = (self._bounds[i] if i < len(self._bounds)
                      else self._max)
                frac = (target - cum) / c
                val = lo + frac * (hi - lo)
                return min(max(val, self._min), self._max)
            cum += c
        return self._max

    def percentiles(self):
        return {"p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}

    def snapshot(self) -> dict:
        out = {"count": self._count, "sum": self._sum,
               "mean": (self._sum / self._count if self._count
                        else None),
               "min": self._min, "max": self._max}
        out.update(self.percentiles())
        ex = self.exemplars()
        if ex:
            out["exemplars"] = {
                str(b): {"trace_id": t, "value": v, "unix_ts": ts}
                for b, (t, v, ts) in ex.items()}
        return out

    def state(self) -> dict:
        return {"bounds": list(self._bounds),
                "counts": list(self._counts),
                "count": self._count, "sum": self._sum,
                "min": self._min, "max": self._max,
                "exemplars": {str(i): list(e)
                              for i, e in sorted(self._ex.items())}}


class MergedRegistry:
    """Read-only registry twin over merged metric maps: the exposition
    renderer (``render_prometheus(registry=...)``), the stateless SLO
    evaluator and /statusz all consume it through the same duck-typed
    surface as the live registry. Lookups of names no peer reported
    return zero-valued twins (get-or-observe-nothing), mirroring the
    live registry's get-or-create so SLO specs over quiet metrics judge
    "no traffic" instead of raising."""

    def __init__(self, counters: Dict[str, _MergedCounter],
                 gauges: Dict[str, _MergedGauge],
                 histograms: Dict[str, _MergedHistogram]):
        self._counters = counters
        self._gauges = gauges
        self._histograms = histograms

    def counter(self, name: str) -> _MergedCounter:
        return self._counters.get(name) or _MergedCounter(name)

    def gauge(self, name: str) -> _MergedGauge:
        return self._gauges.get(name) or _MergedGauge(name)

    def histogram(self, name: str, buckets=None, exemplars=False):
        h = self._histograms.get(name)
        if h is None:
            h = _MergedHistogram(name, {
                "bounds": list(_reg.DEFAULT_LATENCY_BUCKETS),
                "counts": [0] * (len(_reg.DEFAULT_LATENCY_BUCKETS) + 1),
                "count": 0, "sum": 0.0, "min": None, "max": None})
        return h

    def metrics(self):
        return (dict(self._counters), dict(self._gauges),
                dict(self._histograms))

    def snapshot(self) -> dict:
        return {
            "counters": {k: v.value
                         for k, v in sorted(self._counters.items())},
            "gauges": {k: v.value
                       for k, v in sorted(self._gauges.items())},
            "histograms": {k: v.snapshot()
                           for k, v in sorted(self._histograms.items())},
        }


# ---------------------------------------------------------------------------
# Merge
# ---------------------------------------------------------------------------

def _merge_exemplars(ex_a: Dict[int, tuple],
                     ex_b: Dict[int, tuple]) -> Dict[int, tuple]:
    """Per-bucket: keep the NEWEST exemplar (greatest unix ts); ties
    break toward the smallest trace_id so merge order cannot leak in."""
    out = dict(ex_a)
    for i, e in ex_b.items():
        prev = out.get(i)
        if prev is None or (e[2], prev[0]) > (prev[2], e[0]):
            out[i] = tuple(e)
    return out


def _merge_histogram_states(a: dict, b: dict,
                            name: str, notes: List[str]) -> dict:
    """Bucket-wise addition of two raw histogram states. Exact because
    both sides share the fixed ladder; a ladder mismatch (custom-bucket
    drift between versions) keeps the first state and records a note —
    re-binning would silently fabricate counts."""
    if list(a["bounds"]) != list(b["bounds"]):
        notes.append(f"histogram {name!r}: bucket ladder mismatch, "
                     f"kept first peer's state")
        return a
    mins = [m for m in (a["min"], b["min"]) if m is not None]
    maxs = [m for m in (a["max"], b["max"]) if m is not None]
    ex = _merge_exemplars(
        {int(i): tuple(e) for i, e in (a.get("exemplars") or {}).items()},
        {int(i): tuple(e) for i, e in (b.get("exemplars") or {}).items()})
    return {
        "bounds": list(a["bounds"]),
        "counts": [int(x) + int(y)
                   for x, y in zip(a["counts"], b["counts"])],
        "count": int(a["count"]) + int(b["count"]),
        "sum": float(a["sum"]) + float(b["sum"]),
        "min": min(mins) if mins else None,
        "max": max(maxs) if maxs else None,
        "exemplars": {str(i): list(e) for i, e in sorted(ex.items())},
    }


def _merge_traces(snaps: List[Tuple[str, dict]]) -> dict:
    """Union the peers' tail-sampled trace rings, tagging every trace
    with its peer id (the per-process attribution /tracez promises).
    Rings are sorted newest-first by (start_unix, trace_id) — a total
    order, so the merged tail is peer-order independent — and capped at
    :data:`MERGED_TRACE_RING`."""
    out = {"sampling_enabled": False, "seen": 0, "kept": {},
           "peers": {}, "traces": {}}
    rings: Dict[str, list] = {}
    for peer_id, tr in snaps:
        if not isinstance(tr, dict):
            continue
        out["sampling_enabled"] = (out["sampling_enabled"]
                                   or bool(tr.get("sampling_enabled")))
        out["seen"] += int(tr.get("seen", 0))
        for ring, n in (tr.get("kept") or {}).items():
            out["kept"][ring] = out["kept"].get(ring, 0) + int(n)
        out["peers"][peer_id] = {"seen": tr.get("seen", 0),
                                 "kept": tr.get("kept", {})}
        for ring, traces in (tr.get("traces") or {}).items():
            for t in traces:
                tagged = dict(t)
                tagged["peer"] = peer_id
                rings.setdefault(ring, []).append(tagged)
    for ring, traces in rings.items():
        traces.sort(key=lambda t: (-float(t.get("start_unix") or 0.0),
                                   str(t.get("trace_id"))))
        out["traces"][ring] = traces[:MERGED_TRACE_RING]
    return out


def _merge_sketch_maps(snaps: List[Tuple[str, dict]],
                       notes: List[str]) -> dict:
    """Merge ``{provider: {key: state}}`` maps across peers via the
    sketches' own deterministic merges, folding in SORTED peer order:
    quantile/moments merges are fully associative+commutative (bitwise
    order-independent), and the weighted Misra-Gries TopK — whose
    combine is order-dependent by nature — becomes deterministic under
    the fixed fold order."""
    merged: Dict[str, Dict[str, object]] = {}
    for peer_id, sketches in snaps:  # caller passes sorted peers
        for provider, states in (sketches or {}).items():
            slot = merged.setdefault(provider, {})
            for key, state in states.items():
                try:
                    sk = _sketches.sketch_from_state(state)
                    if key in slot:
                        slot[key].merge(sk)
                    else:
                        slot[key] = sk
                except Exception as e:  # noqa: BLE001 — keep merging
                    notes.append(f"sketch {provider}/{key} from "
                                 f"{peer_id}: {type(e).__name__}: {e}")
    return {provider: {key: sk.state()
                       for key, sk in sorted(slot.items())}
            for provider, slot in sorted(merged.items())}


@dataclasses.dataclass
class FleetView:
    """One merged, self-consistent view of the fleet at merge time."""

    registry: MergedRegistry
    sketches: dict
    traces: dict
    slo_specs: List[str]
    slo: dict
    peers: Dict[str, dict]
    notes: List[str]

    def snapshot(self, role: str = "aggregator",
                 labels: Optional[Dict[str, str]] = None,
                 start_unix: Optional[float] = None) -> dict:
        """The merged view re-serialized in the SAME v1 schema — the
        merge is closed under serialization, so aggregators stack."""
        counters, gauges, histograms = self.registry.metrics()
        return {
            "schema": SNAPSHOT_SCHEMA,
            "process": {
                "pid": os.getpid(),
                "role": role,
                "host": socket.gethostname(),
                "start_unix": start_unix,
                "snapshot_unix": time.time(),
                "labels": dict(labels or {}),
                "merged_peers": sorted(self.peers),
            },
            "counters": {n: c.value
                         for n, c in sorted(counters.items())},
            "gauges": {n: {"value": g.value, "calls": g.calls}
                       for n, g in sorted(gauges.items())},
            "histograms": {n: h.state()
                           for n, h in sorted(histograms.items())},
            "sketches": self.sketches,
            "slo_specs": list(self.slo_specs),
            "traces": self.traces,
            "stages": {},
        }


def merge_snapshots(snapshots: Dict[str, dict]) -> FleetView:
    """Fold ``{peer_id: snapshot}`` into a :class:`FleetView`.

    Peers are processed in sorted peer-id order, which together with
    the per-type semantics (associative counter/bucket sums, total-
    order gauge/exemplar tie-breaks, fixed sketch fold order) makes the
    result a pure function of the snapshot SET — permuting arrival
    order cannot change a byte of the merged output."""
    notes: List[str] = []
    counters: Dict[str, _MergedCounter] = {}
    gauge_obs: Dict[str, list] = {}
    hist_states: Dict[str, dict] = {}
    peers: Dict[str, dict] = {}
    specs: List[str] = []
    ordered = sorted(snapshots.items())
    for peer_id, snap in ordered:
        if snap.get("schema") != SNAPSHOT_SCHEMA:
            notes.append(f"peer {peer_id}: unknown schema "
                         f"{snap.get('schema')!r}, skipped")
            continue
        proc = snap.get("process") or {}
        peers[peer_id] = proc
        snap_unix = float(proc.get("snapshot_unix") or 0.0)
        for name, value in (snap.get("counters") or {}).items():
            c = counters.get(name)
            if c is None:
                c = counters[name] = _MergedCounter(name)
            c.value += value
        for name, g in (snap.get("gauges") or {}).items():
            gauge_obs.setdefault(name, []).append(
                (peer_id, snap_unix, g["value"], int(g.get("calls", 0))))
        for name, state in (snap.get("histograms") or {}).items():
            prev = hist_states.get(name)
            hist_states[name] = (dict(state) if prev is None else
                                 _merge_histogram_states(
                                     prev, state, name, notes))
        for s in snap.get("slo_specs") or []:
            if s not in specs:
                specs.append(s)
    gauges: Dict[str, _MergedGauge] = {}
    for name, obs in gauge_obs.items():
        policy = gauge_merge_policy(name)
        set_obs = [o for o in obs if o[3] > 0]
        calls = sum(o[3] for o in obs)
        if not set_obs:
            gauges[name] = _MergedGauge(name, 0.0, calls, policy)
        elif policy == "sum":
            gauges[name] = _MergedGauge(
                name, sum(o[2] for o in set_obs), calls, policy)
        elif policy == "max":
            gauges[name] = _MergedGauge(
                name, max(o[2] for o in set_obs), calls, policy)
        else:  # "last": newest snapshot wins; tie → greatest peer id
            winner = max(set_obs, key=lambda o: (o[1], o[0]))
            gauges[name] = _MergedGauge(name, winner[2], calls, policy)
    histograms = {name: _MergedHistogram(name, st)
                  for name, st in hist_states.items()}
    reg = MergedRegistry(counters, gauges, histograms)
    sketches = _merge_sketch_maps(
        [(pid, s.get("sketches")) for pid, s in ordered
         if pid in peers], notes)
    traces = _merge_traces(
        [(pid, s.get("traces")) for pid, s in ordered if pid in peers])
    slo = {}
    if specs:
        try:
            slo = _slo.evaluate_specs(specs, reg)
        except Exception as e:  # noqa: BLE001 — view must still build
            notes.append(f"slo re-evaluation failed: "
                         f"{type(e).__name__}: {e}")
    return FleetView(registry=reg, sketches=sketches, traces=traces,
                     slo_specs=specs, slo=slo, peers=peers, notes=notes)


# ---------------------------------------------------------------------------
# Peer discovery: obs_port descriptor files
# ---------------------------------------------------------------------------

def write_obs_descriptor(path, port: int, role: str = "process",
                         pid: Optional[int] = None,
                         start_unix: Optional[float] = None) -> dict:
    """Write the ``<out>/obs_port`` announcement as a JSON descriptor
    ``{port, pid, role, start_unix}`` (one line). Replaces the PR 9
    plain-int file; :func:`read_obs_descriptor` still parses both."""
    desc = {"port": int(port),
            "pid": int(pid if pid is not None else os.getpid()),
            "role": role,
            "start_unix": (time.time() if start_unix is None
                           else float(start_unix))}
    Path(path).write_text(json.dumps(desc) + "\n")
    return desc


def read_obs_descriptor(path) -> dict:
    """Parse an ``obs_port`` announcement file. JSON descriptors return
    as-is (``port`` coerced int); legacy plain-int files return a
    minimal ``{"port": N}`` so pre-descriptor children stay
    discoverable."""
    text = Path(path).read_text().strip()
    try:
        desc = json.loads(text)
    except (ValueError, TypeError):
        desc = None
    if isinstance(desc, dict) and "port" in desc:
        desc["port"] = int(desc["port"])
        return desc
    return {"port": int(text)}


def discover_peers(peer_dirs: Sequence) -> Dict[str, dict]:
    """Scan output directories for ``obs_port`` descriptors: each dir
    itself, plus one level of subdirectories (the replica-harness
    layout — one parent dir, one child dir per replica). Returns
    ``{peer_id: descriptor + url}``; unreadable files are skipped (a
    child racing its own startup writes atomically-enough for JSON one-
    liners, but a garbled read just means "try next interval")."""
    found: Dict[str, dict] = {}
    for d in peer_dirs:
        d = Path(d)
        candidates = [d / "obs_port"]
        if d.is_dir():
            candidates += sorted(c / "obs_port" for c in d.iterdir()
                                 if c.is_dir())
        for f in candidates:
            if not f.is_file():
                continue
            try:
                desc = read_obs_descriptor(f)
            except (OSError, ValueError):
                continue
            desc["url"] = f"http://127.0.0.1:{desc['port']}"
            peer_id = (f"{desc.get('role', 'process')}"
                       f"-{desc.get('pid', f.parent.name)}"
                       f"@{desc['port']}")
            found[peer_id] = desc
    return found


# ---------------------------------------------------------------------------
# Aggregator
# ---------------------------------------------------------------------------

class _PeerState:
    __slots__ = ("peer_id", "url", "snapshot", "last_success_unix",
                 "last_attempt_unix", "last_error", "scrapes", "errors")

    def __init__(self, peer_id: str, url: str):
        self.peer_id = peer_id
        self.url = url
        self.snapshot: Optional[dict] = None
        self.last_success_unix: Optional[float] = None
        self.last_attempt_unix: Optional[float] = None
        self.last_error: Optional[str] = None
        self.scrapes = 0
        self.errors = 0


def _peer_metric_label(peer_id: str) -> str:
    """Sanitize a peer id into a legal dotted-name PART for the
    ``fleet.peer.<label>.*`` gauges (lowercase [a-z0-9_])."""
    out = re.sub(r"[^a-z0-9_]+", "_", peer_id.lower()).strip("_")
    return out or "peer"


class FleetAggregator:
    """Polls peers' ``/snapshotz`` and serves the merged plane.

    - ``peers``: explicit base URLs (``http://127.0.0.1:9100``).
    - ``peer_dirs``: directories re-scanned every poll for ``obs_port``
      descriptors, so children that boot late are picked up.
    - staleness: a peer whose last successful scrape is older than
      ``stale_after_s`` (default 3 poll intervals) is STALE — its last
      snapshot is retained in the merge (final counts of a finished
      child stay in the fleet totals) and ``fleet.peer.<id>.stale`` /
      ``.staleness_seconds`` flag it on the merged ``/metrics``. A dead
      child therefore degrades the fleet plane; it never crashes it.
    - readiness: the aggregator's ``/readyz`` requires >= 1 FRESH peer.

    The aggregator owns a plain :class:`ObservabilityServer` whose
    /metrics, /statusz, /tracez, /distz and /snapshotz routes are
    overridden with merged views (per-process breakdown rides in
    /statusz ``peers``, /distz ``peers`` and trace ``peer`` tags); its
    own ``fleet.*`` telemetry is synthesized as a pseudo-peer snapshot
    from plain internal state — see the module docstring.
    """

    SELF_PEER_ID = "~aggregator-self"  # sorts after peer ids

    def __init__(self, peers: Sequence[str] = (),
                 peer_dirs: Sequence = (),
                 interval_s: float = 2.0,
                 stale_after_s: Optional[float] = None,
                 port: int = 0, host: str = "127.0.0.1",
                 timeout_s: float = 2.0,
                 labels: Optional[Dict[str, str]] = None):
        self.interval_s = float(interval_s)
        self.stale_after_s = (float(stale_after_s)
                              if stale_after_s is not None
                              else 3.0 * self.interval_s)
        self.timeout_s = float(timeout_s)
        self.peer_dirs = [Path(d) for d in peer_dirs]
        self.labels = dict(labels or {})
        self._static_urls = list(peers)
        self._peers: Dict[str, _PeerState] = {}
        self._lock = threading.Lock()
        self._view: Optional[FleetView] = None
        self._scrapes = 0
        self._scrape_errors = 0
        self._start_unix = time.time()
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        self.server = _expo.ObservabilityServer(
            port=port, host=host, role="aggregator", labels=self.labels)
        self.server.add_route("/metrics", self._metrics)
        self.server.add_route("/statusz", self._statusz)
        self.server.add_route("/tracez", self._tracez)
        self.server.add_route("/distz", self._distz)
        self.server.add_route("/snapshotz", self._snapshotz)
        self.server.add_route("/healthz", self._healthz)
        self.server.set_ready_check(self._readiness)
        for url in self._static_urls:
            url = url.rstrip("/")
            self._peers[f"peer@{url}"] = _PeerState(f"peer@{url}", url)

    # -- scraping ----------------------------------------------------------

    def _fetch_snapshot(self, url: str) -> dict:
        with urllib.request.urlopen(url + "/snapshotz",
                                    timeout=self.timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def poll_once(self) -> None:
        """One discovery + scrape pass over every known peer."""
        discovered = discover_peers(self.peer_dirs)
        with self._lock:
            for peer_id, desc in discovered.items():
                if peer_id not in self._peers:
                    self._peers[peer_id] = _PeerState(
                        peer_id, desc["url"])
            states = list(self._peers.values())
        for st in states:
            st.last_attempt_unix = time.time()
            try:
                snap = self._fetch_snapshot(st.url)
            except Exception as e:  # noqa: BLE001 — dead peer degrades
                st.errors += 1
                st.last_error = f"{type(e).__name__}: {e}"
                self._scrape_errors += 1
                continue
            st.scrapes += 1
            st.snapshot = snap
            st.last_success_unix = time.time()
            st.last_error = None
        self._scrapes += 1
        self._rebuild_view()

    def peer_staleness(self) -> Dict[str, dict]:
        """Per-peer freshness: ``stale`` plus seconds since the last
        successful scrape (None before the first one)."""
        now = time.time()
        out = {}
        with self._lock:
            for peer_id, st in sorted(self._peers.items()):
                if st.last_success_unix is None:
                    staleness, stale = None, True
                else:
                    staleness = now - st.last_success_unix
                    stale = staleness > self.stale_after_s
                out[peer_id] = {
                    "url": st.url, "stale": stale,
                    "staleness_seconds": staleness,
                    "scrapes": st.scrapes, "errors": st.errors,
                    "last_error": st.last_error,
                    "has_snapshot": st.snapshot is not None,
                }
        return out

    def _self_snapshot(self) -> dict:
        """The aggregator's own ``fleet.*`` series as a pseudo-peer
        snapshot built from plain state — reserved-prefix telemetry
        without touching the process-global registry (the lint keeps
        every OTHER module out of ``fleet.``)."""
        staleness = self.peer_staleness()
        fresh = sum(1 for s in staleness.values() if not s["stale"])
        gauges = {
            "fleet.peers": {"value": len(staleness), "calls": 1},
            "fleet.peers_fresh": {"value": fresh, "calls": 1},
            "fleet.peers_stale": {"value": len(staleness) - fresh,
                                  "calls": 1},
        }
        for peer_id, s in staleness.items():
            pre = f"fleet.peer.{_peer_metric_label(peer_id)}."
            gauges[pre + "stale"] = {"value": 1.0 if s["stale"] else 0.0,
                                     "calls": 1}
            gauges[pre + "staleness_seconds"] = {
                "value": (s["staleness_seconds"]
                          if s["staleness_seconds"] is not None
                          else -1.0),
                "calls": 1}
        return {
            "schema": SNAPSHOT_SCHEMA,
            "process": {
                "pid": os.getpid(), "role": "aggregator",
                "host": socket.gethostname(),
                "start_unix": self._start_unix,
                "snapshot_unix": time.time(),
                "labels": dict(self.labels),
            },
            "counters": {"fleet.scrape_passes": self._scrapes,
                         "fleet.scrape_errors": self._scrape_errors},
            "gauges": gauges,
            "histograms": {},
            "sketches": {},
            "slo_specs": [],
            "traces": {"sampling_enabled": False, "seen": 0,
                       "kept": {}, "traces": {}},
            "stages": {},
        }

    def _rebuild_view(self) -> None:
        with self._lock:
            snaps = {pid: st.snapshot
                     for pid, st in self._peers.items()
                     if st.snapshot is not None}
        snaps[self.SELF_PEER_ID] = self._self_snapshot()
        view = merge_snapshots(snaps)
        with self._lock:
            self._view = view

    def view(self) -> FleetView:
        """The latest merged view (building one on demand before the
        first poll completes)."""
        with self._lock:
            v = self._view
        if v is None:
            self._rebuild_view()
            with self._lock:
                v = self._view
        return v

    def _readiness(self):
        staleness = self.peer_staleness()
        fresh = sum(1 for s in staleness.values() if not s["stale"])
        return (fresh >= 1,
                f"{fresh}/{len(staleness)} peers fresh")

    # -- merged routes -----------------------------------------------------

    def _metrics(self, accept: str = ""):
        view = self.view()
        if "openmetrics" in accept:
            return (_expo.render_prometheus(registry=view.registry,
                                            include_exemplars=True)
                    + "# EOF\n",
                    "application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8")
        return (_expo.render_prometheus(registry=view.registry),
                "text/plain; version=0.0.4; charset=utf-8")

    def _healthz(self, accept: str = ""):
        ready, reason = self._readiness()
        staleness = self.peer_staleness()
        return (json.dumps({
            "status": "ok",   # liveness: the aggregator itself is up
            "ready": ready,
            "ready_reason": reason,
            "role": "aggregator",
            "peers": len(staleness),
            "peers_stale": sum(1 for s in staleness.values()
                               if s["stale"]),
        }) + "\n", "application/json")

    def _statusz(self, accept: str = ""):
        view = self.view()
        body = {
            "role": "aggregator",
            "interval_s": self.interval_s,
            "stale_after_s": self.stale_after_s,
            "scrape_passes": self._scrapes,
            "scrape_errors": self._scrape_errors,
            "peers": self.peer_staleness(),
            "peer_processes": view.peers,
            "metrics": view.registry.snapshot(),
            "slo": view.slo or None,
            "slo_specs": view.slo_specs,
            "merge_notes": view.notes,
        }
        return (json.dumps(body, indent=2,
                           default=_expo._json_default) + "\n",
                "application/json")

    def _tracez(self, accept: str = ""):
        return (json.dumps(self.view().traces, indent=2,
                           default=_expo._json_default) + "\n",
                "application/json")

    def _distz(self, accept: str = ""):
        view = self.view()
        with self._lock:
            per_peer = {
                pid: st.snapshot.get("sketches")
                for pid, st in sorted(self._peers.items())
                if st.snapshot is not None
                and st.snapshot.get("sketches")}
        body = {"fleet": view.sketches, "peers": per_peer}
        return (json.dumps(body, indent=2,
                           default=_expo._json_default) + "\n",
                "application/json")

    def _snapshotz(self, accept: str = ""):
        snap = self.view().snapshot(role="aggregator",
                                    labels=self.labels,
                                    start_unix=self._start_unix)
        return (json.dumps(snap, default=_expo._json_default) + "\n",
                "application/json")

    # -- lifecycle ---------------------------------------------------------

    def _poll_loop(self) -> None:
        while not self._poll_stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the poller must survive
                self._scrape_errors += 1
            self._poll_stop.wait(self.interval_s)

    def start(self) -> "FleetAggregator":
        self.server.start()
        self._poll_stop.clear()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="fleet-poll", daemon=True)
        self._poll_thread.start()
        return self

    def stop(self) -> None:
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5)
            self._poll_thread = None
        self.server.stop()

    def __enter__(self) -> "FleetAggregator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def port(self) -> Optional[int]:
        return self.server.port

    def summary(self) -> dict:
        staleness = self.peer_staleness()
        return {
            "port": self.port,
            "interval_s": self.interval_s,
            "stale_after_s": self.stale_after_s,
            "scrape_passes": self._scrapes,
            "scrape_errors": self._scrape_errors,
            "peers": {pid: {"stale": s["stale"],
                            "scrapes": s["scrapes"],
                            "errors": s["errors"]}
                      for pid, s in staleness.items()},
        }
