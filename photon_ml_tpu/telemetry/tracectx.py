"""Request-scoped trace context with tail-based sampling — the Dapper
layer over the PR 6/9 telemetry plane.

The per-thread span stacks (spans.py) answer "what is this THREAD
doing"; they cannot follow one request across the hops the serving
front-end routinely makes (event loop -> coalesce group -> dispatch
executor -> scatter-back), and they aggregate — a P99 spike on
``/metrics`` points at no particular request. A :class:`TraceContext`
is the missing identity: minted at ``ServingFrontend`` admission (and
once per λ-grid point in the streamed training drivers), it carries a
process-unique ``trace_id`` and a monotonic event timeline
(admission -> coalesce -> dispatch -> settle) that survives every
thread hop because the context object itself travels with the request.

**Tail-based sampling** (:class:`TraceTail`): keeping every timeline at
serving rates is pointless and unbounded; keeping a uniform sample
loses exactly the requests an operator asks about. The tail keeps, in
bounded rings:

- every trace that finished with a non-``ok`` outcome (sheds, errors,
  cancellations, solver divergence),
- the **slowest decile** — duration >= the P90 of a sliding window of
  recent completions (threshold recomputed every
  ``_THRESHOLD_REFRESH`` records, so steady-state cost is O(1) per
  finish),
- a small **uniform floor** (every ``floor_every``-th trace), so
  ``/tracez`` always shows what *normal* looks like next to the tail.

Kept traces are retrievable live from the ``/tracez`` endpoint
(telemetry/exposition.py), stamped into flight-recorder dumps, and
their ``trace_id``s ride as OpenMetrics exemplars on latency-histogram
buckets (registry.py) — so a ``/metrics`` P99 bucket links directly to
a replayable timeline.

Cost discipline matches the rest of the layer: sampling is DISABLED by
default; ``mint()`` returns one shared no-op context (zero allocation)
until a driver enables it. The serving hot path goes further and
DEFERS materialization entirely — the front-end's scatter settles a
whole coalesced group through :meth:`TraceTail.settle_batch` under one
lock, so an unsampled request's total cost is a deque append, and only
KEPT traces build a timeline dict or mint an id (measured ~1% against
the same < 2% gate as PR 6/9 in the bench ``observability`` extra).
Explicit :class:`TraceContext` objects (``score(..., trace=ctx)``, the
solvers' ``trace_ctx=``) take the full per-request path.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

# Sampling switch — independent of the metrics flag so the bench can
# price it separately, but drivers turn both on together
# (telemetry.enable()).
_enabled = False


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


# Process-unique trace ids: a pid-derived prefix plus a counter. The
# formatting is lazy (``TraceContext.trace_id`` property) so unsampled
# requests never pay for the f-string.
_SEQ = itertools.count(1)
_ID_PREFIX = f"t{os.getpid():x}"


class TraceContext:
    """One request's (or one solve's) identity and timeline.

    ``event(stage)`` appends ``(stage, now)`` — list appends are atomic
    under the GIL, so events may arrive from any thread (the dispatch
    executor stamps ``dispatch`` while the event loop owns the object).
    ``finish(outcome)`` closes the timeline and hands the context to the
    process :class:`TraceTail` for the keep/drop decision. Group-shared
    stages (a coalesced group forms and dispatches at ONE instant) can
    be stamped in bulk via ``finish``'s ``stages`` argument — one call
    per request instead of one per stage, which is what keeps the
    sampled hot path under the overhead gate.
    """

    __slots__ = ("_seq", "_id", "kind", "t0", "start_unix", "events",
                 "annotations", "outcome", "duration_s", "kept")

    #: Timeline cap — a runaway outer loop must not grow one context
    #: without bound; beyond this, events drop (count preserved in the
    #: serialized timeline via ``events_dropped``).
    MAX_EVENTS = 256

    def __init__(self, kind: str):
        self._seq = next(_SEQ)
        self._id = None
        self.kind = kind
        self.t0 = time.perf_counter()
        self.start_unix = time.time()
        self.events: List[Tuple[str, float]] = []
        self.annotations: Optional[dict] = None
        self.outcome: Optional[str] = None
        self.duration_s: Optional[float] = None
        self.kept = False

    @property
    def trace_id(self) -> str:
        tid = self._id
        if tid is None:
            tid = self._id = f"{_ID_PREFIX}-{self._seq:08x}"
        return tid

    def event(self, stage: str) -> None:
        """Append a named timeline point (any thread)."""
        if len(self.events) < self.MAX_EVENTS:
            self.events.append((stage, time.perf_counter()))

    def annotate(self, **kw) -> None:
        """Attach key/value context (model name, rows, λ, ...)."""
        if self.annotations is None:
            self.annotations = {}
        self.annotations.update(kw)

    def finish(self, outcome: str = "ok",
               stages: Optional[Dict[str, float]] = None) -> None:
        """Close the timeline and offer it to the tail sampler.

        ``stages`` merges group-shared ``{stage: perf_counter}`` points
        recorded once per coalesced group (coalesce/dispatch/settle —
        identical for every window-mate) into this request's timeline
        without per-request ``event()`` calls. Idempotent: only the
        first finish records. Sets ``self.kept`` to the tail's verdict
        — exemplar wiring reads it so only resolvable ids are ever
        stamped on a histogram bucket."""
        if self.outcome is not None:
            return
        now = time.perf_counter()
        self.outcome = outcome
        self.duration_s = now - self.t0
        if stages:
            self.events.extend(stages.items())
        self.kept = _TAIL.record(self)

    def snapshot(self) -> dict:
        """Serialized timeline (built only for KEPT traces): stage
        offsets in seconds from mint, sorted by time."""
        events = sorted(self.events, key=lambda e: e[1])
        out = {
            "trace_id": self.trace_id,
            "kind": self.kind,
            "outcome": self.outcome,
            "start_unix": self.start_unix,
            "duration_s": self.duration_s,
            "events": [{"stage": s, "t_s": round(t - self.t0, 9)}
                       for s, t in events],
        }
        if len(self.events) >= self.MAX_EVENTS:
            out["events_dropped"] = True
        if self.annotations:
            out["annotations"] = dict(self.annotations)
        return out


class _NoopTraceContext:
    """Shared do-nothing context — THE disabled fast path. Its
    ``trace_id`` is None so exemplar plumbing short-circuits too."""

    __slots__ = ()
    trace_id = None
    kind = "noop"
    outcome = None
    duration_s = None
    annotations = None
    kept = False
    events: List = []

    def event(self, stage: str) -> None:
        return None

    def annotate(self, **kw) -> None:
        return None

    def finish(self, outcome: str = "ok", stages=None) -> None:
        return None

    def snapshot(self) -> dict:
        return {}


NOOP_CONTEXT = _NoopTraceContext()


def mint(kind: str = "request"):
    """New :class:`TraceContext` (the shared no-op while sampling is
    disabled — zero allocation, same discipline as ``span()``)."""
    if not _enabled:
        return NOOP_CONTEXT
    return TraceContext(kind)


class TraceTail:
    """Bounded tail sampler of finished trace contexts.

    Three keep classes, each a bounded ring (oldest evicted):

    - ``error`` — every non-``ok`` outcome (shed/error/cancelled/...),
    - ``slow`` — duration >= the cached P90 of the last ``window``
      completion durations (the slowest decile; with fewer than
      ``_MIN_WINDOW`` samples everything qualifies, so early traces are
      visible immediately),
    - ``floor`` — every ``floor_every``-th finish regardless (the
      uniform baseline).

    A trace lands in exactly one ring (error > slow > floor priority).
    ``record`` is O(1) amortized: the decile threshold recomputes every
    ``_THRESHOLD_REFRESH`` records from the duration window, not per
    record, and timeline serialization happens only for kept traces.

    ``settle_batch`` is the front-end's hot path: a coalesced group
    settles every deferred request under ONE lock acquisition, and an
    unsampled request's whole cost is a deque append — no context
    object, no per-request lock, no id formatting (ids mint only for
    KEPT traces, which also makes every exemplar resolvable by
    construction).
    """

    _MIN_WINDOW = 20
    _THRESHOLD_REFRESH = 64

    def __init__(self, slow_capacity: int = 64, error_capacity: int = 64,
                 floor_capacity: int = 32, floor_every: int = 64,
                 window: int = 512):
        self.floor_every = max(1, int(floor_every))
        self._window_n = int(window)
        self._lock = threading.Lock()
        self._slow: deque = deque(maxlen=slow_capacity)
        self._error: deque = deque(maxlen=error_capacity)
        self._floor: deque = deque(maxlen=floor_capacity)
        self._durations: deque = deque(maxlen=self._window_n)
        self._threshold: Optional[float] = None
        self._since_refresh = 0
        self._seen = 0
        self._kept = {"error": 0, "slow": 0, "floor": 0}

    def _refresh_threshold(self) -> None:
        # P90 by sort of the (bounded) window — runs every
        # _THRESHOLD_REFRESH records, so the amortized per-finish cost
        # is O(window log window / refresh) ~ a few hundred ns.
        durs = sorted(self._durations)
        self._threshold = durs[int(0.9 * (len(durs) - 1))]
        self._since_refresh = 0

    def _classify(self, d: float, outcome: str):
        """Keep/drop decision (caller holds the lock): updates the
        duration window + cached decile threshold, returns
        ``(ring, class)`` or ``(None, None)`` for a drop."""
        self._seen += 1
        if outcome != "ok":
            # Non-ok finishes keep unconditionally AND stay out of the
            # duration window: a shed finishes microseconds after mint,
            # so under heavy overload its ~0s durations would drag the
            # "P90 of completions" below normal completion latency and
            # classify every ok request slow — the threshold must track
            # COMPLETIONS only.
            return self._error, "error"
        self._durations.append(d)
        self._since_refresh += 1
        enough = len(self._durations) >= self._MIN_WINDOW
        if enough and (self._threshold is None
                       or self._since_refresh
                       >= self._THRESHOLD_REFRESH):
            self._refresh_threshold()
        if not enough or d >= self._threshold:
            return self._slow, "slow"
        if self._seen % self.floor_every == 0:
            return self._floor, "floor"
        return None, None

    def record(self, ctx: TraceContext) -> bool:
        """Classify one finished context; True when its timeline was
        kept (so its trace_id resolves on /tracez)."""
        with self._lock:
            ring, cls = self._classify(ctx.duration_s or 0.0,
                                       ctx.outcome)
            if ring is None:
                return False
            # Serialize INSIDE the keep decision: dropped traces never
            # pay for dict building.
            ring.append(ctx.snapshot())
            self._kept[cls] += 1
            return True

    def settle_batch(self, entries, stages: Dict[str, float],
                     kind: str = "request") -> Dict[int, str]:
        """Batched deferred settle — the serving scatter path. Each
        entry is ``(t_admit, duration_s, outcome, error_name, slot)``
        for a request that never materialized a context; the whole
        group classifies under one lock, and ONLY kept entries build a
        timeline (admission at offset 0 plus the group-shared
        ``stages``) and mint a trace_id. Returns ``{slot: trace_id}``
        for kept ``ok`` entries, which the caller stamps as latency
        exemplars — so a /metrics exemplar always resolves on /tracez.
        """
        out: Dict[int, str] = {}
        kept = []
        with self._lock:
            for t_admit, d, outcome, err, slot in entries:
                ring, cls = self._classify(d, outcome)
                if ring is None:
                    continue
                tid = f"{_ID_PREFIX}-{next(_SEQ):08x}"
                events = [{"stage": "admit", "t_s": 0.0}]
                events += sorted(
                    ({"stage": s, "t_s": round(t - t_admit, 9)}
                     for s, t in stages.items()),
                    key=lambda e: e["t_s"])
                snap = {
                    "trace_id": tid,
                    "kind": kind,
                    "outcome": outcome,
                    "start_unix": None,  # filled below, outside the lock
                    "duration_s": d,
                    "events": events,
                }
                if err is not None:
                    snap["annotations"] = {"error": err}
                ring.append(snap)
                self._kept[cls] += 1
                kept.append((snap, t_admit))
                if outcome == "ok" and slot is not None:
                    out[slot] = tid
        if kept:
            # Wall-clock anchor for the kept few, off the lock: unix
            # start ~ now_unix - (now_perf - t_admit).
            now_unix = time.time()
            now_perf = time.perf_counter()
            for snap, t_admit in kept:
                snap["start_unix"] = now_unix - (now_perf - t_admit)
        return out

    def counters(self) -> dict:
        """Just the bookkeeping scalars (seen/kept/threshold) — the
        metrics.json form; ``snapshot()`` deep-copies every kept
        timeline, which a counters-only reader should not pay for."""
        with self._lock:
            return {
                "seen": self._seen,
                "kept": dict(self._kept),
                "slow_threshold_s": self._threshold,
            }

    def find(self, trace_id: str) -> Optional[dict]:
        """Resolve a trace_id (e.g. from a /metrics exemplar) to its
        kept timeline, or None if it was dropped/evicted."""
        with self._lock:
            for ring in (self._error, self._slow, self._floor):
                for snap in ring:
                    if snap.get("trace_id") == trace_id:
                        return dict(snap)
        return None

    def snapshot(self) -> dict:
        """The /tracez payload: sampler config + counters + the kept
        timelines per class (newest last)."""
        with self._lock:
            return {
                "sampling_enabled": _enabled,
                "seen": self._seen,
                "kept": dict(self._kept),
                "slow_threshold_s": self._threshold,
                "window": len(self._durations),
                "floor_every": self.floor_every,
                "traces": {
                    "error": [dict(s) for s in self._error],
                    "slow": [dict(s) for s in self._slow],
                    "floor": [dict(s) for s in self._floor],
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._slow.clear()
            self._error.clear()
            self._floor.clear()
            self._durations.clear()
            self._threshold = None
            self._since_refresh = 0
            self._seen = 0
            self._kept = {"error": 0, "slow": 0, "floor": 0}


_TAIL = TraceTail()


def trace_tail() -> TraceTail:
    """The process-wide tail sampler (fed by every
    ``TraceContext.finish``; served by ``/tracez``)."""
    return _TAIL
