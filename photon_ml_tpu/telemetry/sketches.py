"""Deterministic, mergeable streaming sketches for distribution
observability (docs/OBSERVABILITY.md §Distributions & drift).

The live plane (telemetry/exposition.py) sees latency, compiles and
traces — but nothing observes the DATA or the MODELS: streamed training
computes no feature/label statistics and serving has no view of score
distributions, which is what actually catches a bad daily retrain in
production GAME deployments (the source paper's setting). These sketches
are the state such statistics accumulate into, designed around two hard
constraints the rest of the repo already lives by:

1. **Zero extra feature passes.** Updates are vectorized numpy over
   columns the decode pass already produced (Snap ML's rule that the
   memory hierarchy must never force another data pass — PAPERS.md).
2. **Bit-stable determinism.** Streamed-training artifacts are
   bitwise-identical across residency/feeder/prefetch configs (PR 5/10
   discipline), so any statistic stamped into metrics.json or a model
   artifact must be too. Every sketch here has a canonical serialized
   form that is a pure function of the sequence of ``update`` payloads —
   and for the quantile and moments sketches, of their MULTISET: merging
   sub-sketches in any order, under any merge tree, yields bitwise-equal
   serialized state (tests/test_sketches.py).

The three sketches:

- :class:`QuantileSketch` — KLL-style bounded-size streaming quantiles,
  with the randomized compactor replaced by a deterministic fixed
  log-bucket store (the DDSketch accuracy model): a value ``v`` lands in
  bucket ``ceil(log_gamma |v|)`` where ``gamma = (1 + a) / (1 - a)`` for
  relative accuracy ``a``. Bucket counts are exact integers, so merge is
  bucket-wise addition — associative, commutative, and bitwise-stable
  across merge trees by construction (where a KLL compactor's state
  depends on compaction history). Rank selection over the cumulative
  counts is EXACT; only the value reported within the selected bucket is
  approximate, with the documented bound ``|est - q_exact| <= a *
  |q_exact|`` (clamped to the exact observed [min, max], so single-value
  and extreme quantiles are exact). The store is structurally bounded by
  the f64 dynamic range: at the default ``a = 0.01`` at most
  ``2 * ceil(log_gamma(1.8e308 / 5e-324)) + 1`` ≈ 72k buckets exist in
  the worst case, and real columns touch a few dozen.
- :class:`MomentsSketch` — count / nnz / min / max / mean / variance.
  Sums accumulate as EXACT dyadic rationals (``fractions.Fraction``;
  every f64 is one), so cross-update accumulation is exactly associative
  and merge-tree-independent — f32/f64 partial sums would reassociate.
  Each ``update`` contributes one vectorized ``np.sum`` of its payload
  (numpy's pairwise algorithm: deterministic for a given payload, and
  ~100x cheaper than a correctly-rounded ``fsum`` — the monitor rides
  the decode hot path), so the per-update float is deterministic too.
- :class:`TopKSketch` — bounded heavy hitters (weighted Misra-Gries)
  for entity IDs. Guarantee: any key with true frequency ``> n/(k+1)``
  is present, and stored counts undercount by at most ``n/(k+1)``
  (``error_bound()``); merging preserves the combined bound (Agarwal et
  al., "Mergeable Summaries"). State is deterministic for a fixed
  ingestion order (which the distribution monitor guarantees by merging
  in shard order) but — unlike the two sketches above — not
  merge-tree-independent; ``state()`` documents this asymmetry.

Drift scoring (:func:`psi`, :func:`ks`) compares two quantile sketches:
PSI over ``bins`` reference-quantile bins (the classic population-
stability-index recipe, eps-smoothed) and a sketch-KS statistic — the
max CDF gap over the union of both sketches' bucket boundaries, exact at
boundaries. Serving uses these against the reference snapshot a trained
model carries (``serving.model.<label>.score_drift_psi`` gauges,
cli/game_scoring_driver.py).

Nothing here touches the telemetry enable flag: sketches are plain data
structures owned by whoever constructs them (the distribution monitor,
data/distmon.py); the no-op-when-disabled contract lives at the call
sites, which simply do not construct a monitor.
"""

from __future__ import annotations

import hashlib
import json
import math
from fractions import Fraction
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = [
    "MomentsSketch",
    "QuantileSketch",
    "TopKSketch",
    "ks",
    "psi",
    "sketch_from_state",
]


def _canonical_json(obj) -> bytes:
    """Canonical bytes of a state dict: sorted keys, no whitespace,
    floats via repr (shortest round-trip — bit-faithful for f64)."""
    return json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


class _SketchBase:
    """Shared serialization contract: ``state()`` is a plain JSON-able
    dict (canonical member order handled at dump time), ``serialize()``
    its canonical bytes, ``digest()`` their sha256 — the unit the
    bitwise-equality tests and the metrics.json ``state_sha256`` use."""

    def state(self) -> dict:  # pragma: no cover - abstract
        raise NotImplementedError

    def serialize(self) -> bytes:
        return _canonical_json(self.state())

    def digest(self) -> str:
        return hashlib.sha256(self.serialize()).hexdigest()


class _BucketStore:
    """Contiguous integer bucket counts over a signed index range
    (``base`` = lowest index ever seen). Updates and merges are one
    vectorized array-add over the union span — no per-bucket python
    loop on the hot path. The span is structurally bounded by the f64
    dynamic range (~71k buckets at 1% accuracy, ~0.6 MB worst case;
    real columns span a few hundred)."""

    __slots__ = ("base", "counts")

    def __init__(self, base: int = 0,
                 counts: Optional[np.ndarray] = None):
        self.base = base
        self.counts = (np.zeros(0, np.int64) if counts is None
                       else np.asarray(counts, np.int64))

    def add_span(self, base: int, counts: np.ndarray) -> None:
        if self.counts.size == 0:
            self.base = base
            self.counts = counts.astype(np.int64, copy=True)
            return
        lo = min(self.base, base)
        hi = max(self.base + self.counts.size, base + counts.size)
        if lo != self.base or hi != self.base + self.counts.size:
            grown = np.zeros(hi - lo, np.int64)
            grown[self.base - lo:self.base - lo + self.counts.size] = \
                self.counts
            self.base, self.counts = lo, grown
        self.counts[base - lo:base - lo + counts.size] += counts

    def total(self) -> int:
        return int(self.counts.sum())

    def items(self) -> List[Tuple[int, int]]:
        """(index, count) for populated buckets, ascending index."""
        nz = np.flatnonzero(self.counts)
        return [(self.base + int(i), int(self.counts[i])) for i in nz]

    def count_le(self, index: int) -> int:
        """Total count in buckets with index <= ``index``."""
        if index < self.base:
            return 0
        return int(self.counts[:index - self.base + 1].sum())

    def count_ge(self, index: int) -> int:
        """Total count in buckets with index >= ``index``."""
        if index >= self.base + self.counts.size:
            return 0
        return int(self.counts[max(0, index - self.base):].sum())


class QuantileSketch(_SketchBase):
    """Deterministic mergeable streaming quantiles (module docstring).

    ``relative_accuracy`` is the one knob: quantile VALUES are within
    that relative error of the exact order statistic (rank selection is
    exact; estimates clamp to the exact observed min/max). Instances
    with different accuracies cannot merge (the bucket grids differ).
    """

    KIND = "quantile"

    def __init__(self, relative_accuracy: float = 0.01):
        if not 0.0 < relative_accuracy < 1.0:
            raise ValueError(
                f"relative_accuracy must be in (0, 1), "
                f"got {relative_accuracy}")
        self.relative_accuracy = float(relative_accuracy)
        self._gamma = (1.0 + self.relative_accuracy) \
            / (1.0 - self.relative_accuracy)
        self._log_gamma = math.log(self._gamma)
        self._inv_log_gamma = 1.0 / self._log_gamma
        self.count = 0
        self._zero = 0
        self._pos = _BucketStore()
        self._neg = _BucketStore()
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    # -- ingest ------------------------------------------------------------

    def _indices(self, mags: np.ndarray) -> np.ndarray:
        # ceil(log_gamma(|v|)): bucket i covers (gamma^(i-1), gamma^i].
        return np.ceil(np.log(mags) * self._inv_log_gamma) \
            .astype(np.int64)

    def update(self, values) -> None:
        """Fold a batch of values in (vectorized; one pass over the
        array, bucket counting via bincount over the payload's index
        span — the monitor rides the decode hot path, so this is
        allocation-lean by design; cost is priced in the bench
        ``distmon`` extra). NaNs are rejected loudly — a NaN
        label/score is a data fault the divergence watchdog family
        owns, not a distribution."""
        v = np.asarray(values, np.float64).ravel()
        if v.size == 0:
            return
        lo = float(v.min())
        hi = float(v.max())
        # NaN/Inf propagate into the min/max scalars, so the validity
        # check costs no extra pass over the payload.
        if not (math.isfinite(lo) and math.isfinite(hi)):
            raise ValueError(
                f"{type(self).__name__} observed non-finite values "
                "(corrupt column?)")
        self.count += int(v.size)
        self._min = lo if self._min is None else min(self._min, lo)
        self._max = hi if self._max is None else max(self._max, hi)
        pos = v[v > 0.0]
        neg = v[v < 0.0]
        self._zero += int(v.size - pos.size - neg.size)
        for store, mags in ((self._pos, pos), (self._neg, -neg)):
            if mags.size == 0:
                continue
            idx = self._indices(mags)
            base = int(idx.min())
            store.add_span(base, np.bincount(idx - base))

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into self (bucket-count addition — associative
        and commutative, so any merge tree over the same sub-sketches
        produces bitwise-identical serialized state)."""
        if other.relative_accuracy != self.relative_accuracy:
            raise ValueError(
                f"cannot merge sketches with different accuracies "
                f"({self.relative_accuracy} vs {other.relative_accuracy})")
        self.count += other.count
        self._zero += other._zero
        for mine, theirs in ((self._pos, other._pos),
                             (self._neg, other._neg)):
            if theirs.counts.size:
                mine.add_span(theirs.base, theirs.counts)
        for v in (other._min,):
            if v is not None:
                self._min = v if self._min is None else min(self._min, v)
        for v in (other._max,):
            if v is not None:
                self._max = v if self._max is None else max(self._max, v)
        return self

    # -- queries -----------------------------------------------------------

    def _rep(self, index: int, negative: bool) -> float:
        # Mid-bucket representative: 2*gamma^i/(gamma+1) is within
        # relative_accuracy of every value in (gamma^(i-1), gamma^i].
        r = 2.0 * math.exp(index * self._log_gamma) / (self._gamma + 1.0)
        return -r if negative else r

    def _ordered_buckets(self) -> List[Tuple[float, int]]:
        """(representative, count) in ascending value order: negatives
        by descending magnitude index, the zero bucket, positives by
        ascending index."""
        out = [(self._rep(i, True), c)
               for i, c in reversed(self._neg.items())]
        if self._zero:
            out.append((0.0, self._zero))
        out.extend((self._rep(i, False), c) for i, c in self._pos.items())
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Value estimate at quantile ``q`` (None while empty): the
        representative of the bucket containing the exact rank
        ``q * (count - 1)``, clamped to the exact [min, max]."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        if self.count == 0:
            return None
        if q == 0.0:
            return self._min
        if q == 1.0:
            return self._max  # extreme quantiles are exact
        target = q * (self.count - 1)
        cum = 0
        val = self._max
        for rep, c in self._ordered_buckets():
            cum += c
            if cum > target:
                val = rep
                break
        return min(max(val, self._min), self._max)

    def cdf(self, x: float) -> float:
        """Fraction of observations <= ``x``. Exact when ``x`` sits on a
        bucket boundary (``gamma^i``), zero, or beyond the observed
        range; otherwise off by at most the mass of one bucket — which
        is what makes the sketch-KS statistic meaningful."""
        if self.count == 0:
            return 0.0
        if self._min is not None and x < self._min:
            return 0.0
        if self._max is not None and x >= self._max:
            return 1.0
        n = 0
        if x >= 0.0:
            n += self._neg.total() + self._zero
            if x > 0.0:
                # Buckets entirely <= x: i with gamma^i <= x.
                ix = math.floor(math.log(x) / self._log_gamma + 1e-12)
                n += self._pos.count_le(ix)
        else:
            # Negative x: count negatives with value <= x, i.e.
            # magnitude >= |x|: buckets i with gamma^(i-1) >= |x|.
            ix = math.ceil(math.log(-x) / self._log_gamma - 1e-12)
            n += self._neg.count_ge(ix + 1)
        return n / self.count

    def boundaries(self) -> List[float]:
        """The populated buckets' upper/lower value boundaries (plus the
        exact min/max) — the evaluation grid for :func:`ks`."""
        out = set()
        for i, _ in self._pos.items():
            out.add(math.exp(i * self._log_gamma))
            out.add(math.exp((i - 1) * self._log_gamma))
        for i, _ in self._neg.items():
            out.add(-math.exp(i * self._log_gamma))
            out.add(-math.exp((i - 1) * self._log_gamma))
        if self._zero:
            out.add(0.0)
        if self._min is not None:
            out.add(self._min)
            out.add(self._max)
        return sorted(out)

    def summary(self) -> dict:
        """Human-readable digest for /distz and metrics.json."""
        qs = {f"p{int(q * 100):02d}": self.quantile(q)
              for q in (0.01, 0.25, 0.50, 0.75, 0.99)}
        return {"count": self.count, "min": self._min, "max": self._max,
                "zero_fraction": (self._zero / self.count
                                  if self.count else None), **qs}

    # -- serialization -----------------------------------------------------

    def state(self) -> dict:
        return {
            "kind": self.KIND,
            "relative_accuracy": self.relative_accuracy,
            "count": self.count,
            "zero": self._zero,
            "pos": [[i, c] for i, c in self._pos.items()],
            "neg": [[i, c] for i, c in self._neg.items()],
            "min": self._min,
            "max": self._max,
        }

    @staticmethod
    def _store_from_pairs(pairs) -> _BucketStore:
        if not pairs:
            return _BucketStore()
        base = min(int(i) for i, _ in pairs)
        hi = max(int(i) for i, _ in pairs)
        counts = np.zeros(hi - base + 1, np.int64)
        for i, c in pairs:
            counts[int(i) - base] = int(c)
        return _BucketStore(base, counts)

    @classmethod
    def from_state(cls, state: dict) -> "QuantileSketch":
        if state.get("kind") != cls.KIND:
            raise ValueError(f"not a quantile-sketch state: "
                             f"{state.get('kind')!r}")
        sk = cls(relative_accuracy=state["relative_accuracy"])
        sk.count = int(state["count"])
        sk._zero = int(state["zero"])
        sk._pos = cls._store_from_pairs(state["pos"])
        sk._neg = cls._store_from_pairs(state["neg"])
        sk._min = state["min"]
        sk._max = state["max"]
        return sk


class MomentsSketch(_SketchBase):
    """Exact streaming moments (module docstring): count, nnz, min, max,
    mean, unbiased variance, L1 mass. Sums are exact dyadic rationals,
    so merge is exactly associative — the serialized state is a pure
    function of the multiset of ``update`` payloads."""

    KIND = "moments"

    def __init__(self):
        self.count = 0
        self.nnz = 0
        self._sum = Fraction(0)
        self._sum_sq = Fraction(0)
        self._sum_abs = Fraction(0)
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def update(self, values) -> None:
        v = np.asarray(values, np.float64).ravel()
        if v.size == 0:
            return
        lo, hi = float(v.min()), float(v.max())
        if not (math.isfinite(lo) and math.isfinite(hi)):
            # NaN/Inf propagate into min/max: no extra validity pass.
            raise ValueError("MomentsSketch observed non-finite values")
        self.count += int(v.size)
        self.nnz += int(np.count_nonzero(v))
        # One vectorized pairwise np.sum per update — deterministic for
        # the payload (fixed algorithm, fixed content) — accumulated
        # EXACTLY across updates/merges as dyadic rationals.
        self._sum += Fraction(float(v.sum()))
        self._sum_sq += Fraction(float(np.dot(v, v)))
        self._sum_abs += Fraction(float(np.abs(v).sum()))
        self._min = lo if self._min is None else min(self._min, lo)
        self._max = hi if self._max is None else max(self._max, hi)

    def merge(self, other: "MomentsSketch") -> "MomentsSketch":
        self.count += other.count
        self.nnz += other.nnz
        self._sum += other._sum
        self._sum_sq += other._sum_sq
        self._sum_abs += other._sum_abs
        if other._min is not None:
            self._min = other._min if self._min is None \
                else min(self._min, other._min)
        if other._max is not None:
            self._max = other._max if self._max is None \
                else max(self._max, other._max)
        return self

    @property
    def mean(self) -> Optional[float]:
        return float(self._sum / self.count) if self.count else None

    @property
    def variance(self) -> Optional[float]:
        """Unbiased (n-1) variance, computed exactly then rounded once."""
        if self.count == 0:
            return None
        n = self.count
        num = self._sum_sq - self._sum * self._sum / n
        var = float(num / max(n - 1, 1))
        return max(var, 0.0)

    def summary(self) -> dict:
        return {"count": self.count, "nnz": self.nnz,
                "mean": self.mean, "variance": self.variance,
                "min": self._min, "max": self._max,
                "sum": float(self._sum) if self.count else None,
                "abs_mean": (float(self._sum_abs / self.count)
                             if self.count else None)}

    def state(self) -> dict:
        def frac(f: Fraction):
            return [str(f.numerator), str(f.denominator)]

        return {"kind": self.KIND, "count": self.count, "nnz": self.nnz,
                "sum": frac(self._sum), "sum_sq": frac(self._sum_sq),
                "sum_abs": frac(self._sum_abs),
                "min": self._min, "max": self._max}

    @classmethod
    def from_state(cls, state: dict) -> "MomentsSketch":
        if state.get("kind") != cls.KIND:
            raise ValueError(f"not a moments-sketch state: "
                             f"{state.get('kind')!r}")
        sk = cls()
        sk.count = int(state["count"])
        sk.nnz = int(state["nnz"])
        sk._sum = Fraction(int(state["sum"][0]), int(state["sum"][1]))
        sk._sum_sq = Fraction(int(state["sum_sq"][0]),
                              int(state["sum_sq"][1]))
        sk._sum_abs = Fraction(int(state["sum_abs"][0]),
                               int(state["sum_abs"][1]))
        sk._min = state["min"]
        sk._max = state["max"]
        return sk


class TopKSketch(_SketchBase):
    """Bounded heavy hitters over string keys (weighted Misra-Gries).

    Holds at most ``k`` counters. Any key with true frequency
    ``> total / (k + 1)`` is guaranteed present; a stored count
    undercounts the true count by at most ``error_bound()`` (the
    classic Misra-Gries bound, preserved under :meth:`merge`).

    Determinism: state is a pure function of the SEQUENCE of updates
    (batch updates fold unique keys in sorted order), which is all the
    distribution monitor needs — it feeds batches in fixed shard order.
    Unlike the quantile/moments sketches the state is NOT merge-tree-
    independent (no bounded heavy-hitter summary is); the guarantee is.
    """

    KIND = "topk"

    def __init__(self, k: int = 16):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.k = int(k)
        self.total = 0
        self.decremented = 0
        self._counts: Dict[str, int] = {}

    def update(self, keys, counts: Optional[Sequence[int]] = None) -> None:
        """Fold keys in (an array of strings, with optional counts).
        Uniques fold in sorted key order, so a batch's effect is
        deterministic regardless of row order within the batch."""
        arr = np.asarray(keys)
        if arr.size == 0:
            return
        if counts is None:
            uniq, cnt = np.unique(arr, return_counts=True)
        else:
            cnt_in = np.asarray(counts, np.int64)
            order = np.argsort(arr, kind="stable")
            uniq, starts = np.unique(arr[order], return_index=True)
            cnt = np.add.reduceat(cnt_in[order], starts)
        for key, c in zip(uniq.tolist(), cnt.tolist()):
            self._add(str(key), int(c))

    def _add(self, key: str, c: int) -> None:
        self.total += c
        d = self._counts
        if key in d:
            d[key] += c
            return
        if len(d) < self.k:
            d[key] = c
            return
        m = min(d.values())
        dec = min(c, m)
        self.decremented += dec
        for other in list(d):
            d[other] -= dec
            if d[other] <= 0:
                del d[other]
        if c > dec:
            d[key] = c - dec
        # else: the new key was fully absorbed by the decrement

    def merge(self, other: "TopKSketch") -> "TopKSketch":
        """Mergeable-summaries combine: add counters, then subtract the
        (k+1)-th largest count and keep the strictly positive rest
        (<= k survivors by construction). Error bounds add."""
        if other.k != self.k:
            raise ValueError(f"cannot merge top-{self.k} with "
                             f"top-{other.k}")
        merged = dict(self._counts)
        for key, c in other._counts.items():
            merged[key] = merged.get(key, 0) + c
        self.total += other.total
        self.decremented += other.decremented
        if len(merged) > self.k:
            ranked = sorted(merged.items(), key=lambda kv: (-kv[1], kv[0]))
            cut = ranked[self.k][1]
            self.decremented += cut
            merged = {key: c - cut for key, c in ranked if c - cut > 0}
        self._counts = merged
        return self

    def error_bound(self) -> int:
        """Max undercount of any stored count (== max count of any
        UNSTORED key): the mass removed by decrements, itself bounded by
        ``total / (k + 1)``."""
        return self.decremented

    def items(self) -> List[Tuple[str, int]]:
        """(key, lower-bound count) sorted by (-count, key)."""
        return sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))

    def summary(self) -> dict:
        return {"k": self.k, "total": self.total,
                "error_bound": self.error_bound(),
                "top": [[k, c] for k, c in self.items()]}

    def state(self) -> dict:
        return {"kind": self.KIND, "k": self.k, "total": self.total,
                "decremented": self.decremented,
                "counts": [[k, c] for k, c in self.items()]}

    @classmethod
    def from_state(cls, state: dict) -> "TopKSketch":
        if state.get("kind") != cls.KIND:
            raise ValueError(f"not a topk-sketch state: "
                             f"{state.get('kind')!r}")
        sk = cls(k=int(state["k"]))
        sk.total = int(state["total"])
        sk.decremented = int(state["decremented"])
        sk._counts = {str(k): int(c) for k, c in state["counts"]}
        return sk


_KINDS = {cls.KIND: cls
          for cls in (QuantileSketch, MomentsSketch, TopKSketch)}


def sketch_from_state(state: dict):
    """Reconstruct any sketch from its ``state()`` dict (the form model
    artifacts and /distz payloads carry)."""
    cls = _KINDS.get(state.get("kind"))
    if cls is None:
        raise ValueError(f"unknown sketch kind {state.get('kind')!r}")
    return cls.from_state(state)


# ---------------------------------------------------------------------------
# Drift scores
# ---------------------------------------------------------------------------

SketchOrState = Union[QuantileSketch, dict]


def _as_quantile_sketch(s: SketchOrState) -> QuantileSketch:
    return s if isinstance(s, QuantileSketch) \
        else QuantileSketch.from_state(s)


def psi(reference: SketchOrState, current: SketchOrState,
        bins: int = 10, eps: float = 1e-4) -> Optional[float]:
    """Population stability index between two quantile sketches: bin
    boundaries are the REFERENCE's ``bins``-quantiles (the classic PSI
    recipe), both distributions' bin fractions come from the sketch
    CDFs, and fractions are eps-smoothed so an empty bin contributes a
    large-but-finite term. Conventional reading: < 0.1 stable, 0.1-0.25
    moderate shift, > 0.25 major shift. None while either side is
    empty."""
    ref = _as_quantile_sketch(reference)
    cur = _as_quantile_sketch(current)
    if ref.count == 0 or cur.count == 0:
        return None
    cuts: List[float] = []
    for j in range(1, bins):
        c = ref.quantile(j / bins)
        if not cuts or c > cuts[-1]:
            cuts.append(c)
    total = 0.0
    prev_r = prev_c = 0.0
    for edge in cuts + [None]:
        r = 1.0 if edge is None else ref.cdf(edge)
        c = 1.0 if edge is None else cur.cdf(edge)
        p = max(r - prev_r, 0.0)
        q = max(c - prev_c, 0.0)
        prev_r, prev_c = r, c
        p = (p + eps) / (1.0 + (len(cuts) + 1) * eps)
        q = (q + eps) / (1.0 + (len(cuts) + 1) * eps)
        total += (p - q) * math.log(p / q)
    return total


def ks(reference: SketchOrState, current: SketchOrState
       ) -> Optional[float]:
    """Sketch-KS statistic: max |CDF_ref - CDF_cur| over the union of
    both sketches' bucket boundaries (where each CDF is exact). In
    [0, 1]; 0 for identical sketches. None while either side is empty."""
    ref = _as_quantile_sketch(reference)
    cur = _as_quantile_sketch(current)
    if ref.count == 0 or cur.count == 0:
        return None
    grid = sorted(set(ref.boundaries()) | set(cur.boundaries()))
    return max((abs(ref.cdf(x) - cur.cdf(x)) for x in grid),
               default=0.0)
