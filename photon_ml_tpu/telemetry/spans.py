"""Span-based pipeline tracing: nestable, thread-aware wall-time stage
attribution, exportable as Chrome trace-event JSON (Perfetto-loadable).

``span("decode")`` opens a named stage on the CURRENT thread's span
stack; nesting subtracts child time from the parent, so
``stage_attribution()`` reports both total and SELF (exclusive) seconds
per stage name — the compute-vs-I/O-vs-wait breakdown that found the
PR-4 feeder/engine gap by hand, now recorded per run. Each thread has
its own stack (a decode span on the prefetch thread never nests into the
consumer's dispatch span), which is exactly how the three-stage
decode -> H2D -> dispatch pipeline reads in Perfetto: one track per
thread, overlap visible.

RULES (enforced by the jaxlint ``telemetry-in-trace`` rule):

- spans must NEVER open inside jitted code — a span in a traced function
  would measure trace time once and nothing thereafter (and a host-time
  read inside a trace is a concretization hazard). Instrument the HOST
  loop that launches device work instead.
- device work is attributed at the dispatch boundary: JAX dispatch is
  async, so a span around ``fn(*args)`` measures enqueue only. The
  honest device number is the span around an EXISTING host-sync point
  (``InFlightWindow``'s ``block_until_ready`` — the ``device_wait``
  stage); never add new syncs just to time something.

Disabled mode (the default) returns one shared no-op context manager —
no allocation, one branch (asserted in tests/test_telemetry.py).
"""

from __future__ import annotations

import importlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

# The registry MODULE (not the ``telemetry.registry()`` accessor the
# package re-exports under the same name) — imported via importlib so
# the binding can't be shadowed by the package attribute.
_reg = importlib.import_module("photon_ml_tpu.telemetry.registry")

#: Raw trace events kept when trace recording is on; aggregation
#: (stage_attribution) is exact regardless — beyond the cap only the raw
#: Perfetto events drop (counted in ``dropped_events``).
MAX_TRACE_EVENTS = 200_000


class _NoopSpan:
    """Shared do-nothing context manager — THE disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NOOP = _NoopSpan()


class Tracer:
    """Aggregates span stage attribution; optionally records raw
    Chrome-trace events. One per process (module singleton below)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._local = threading.local()
        self.record_events = False
        # Optional FlightRecorder (telemetry/recorder.py) fed every
        # completed span. Deliberately NOT cleared by reset(): drivers
        # reset telemetry at startup and install the recorder after —
        # the recorder's lifetime is the driver run's, not the
        # aggregation window's.
        self.flight = None
        self.reset()

    def reset(self) -> None:
        with self._lock:
            # name -> [count, total_s, self_s]
            self._agg: Dict[str, List[float]] = {}
            self._main_agg: Dict[str, List[float]] = {}
            self.events: List[dict] = []
            self.dropped_events = 0
            self.epoch = time.perf_counter()
            self.main_tid = threading.get_ident()

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _record(self, name: str, t0: float, t1: float,
                child_s: float, tid: int) -> None:
        dur = t1 - t0
        self_s = max(0.0, dur - child_s)
        with self._lock:
            for agg in ((self._agg, self._main_agg)
                        if tid == self.main_tid else (self._agg,)):
                slot = agg.get(name)
                if slot is None:
                    slot = agg[name] = [0, 0.0, 0.0]
                slot[0] += 1
                slot[1] += dur
                slot[2] += self_s
            if self.record_events:
                if len(self.events) < MAX_TRACE_EVENTS:
                    self.events.append({
                        "name": name, "tid": tid,
                        "ts": (t0 - self.epoch) * 1e6,
                        "dur": dur * 1e6})
                else:
                    self.dropped_events += 1
        # Flight ring rides OUTSIDE the aggregation lock (it has its
        # own); one attribute load + None check when no recorder is
        # installed, nothing at all while telemetry is disabled (span()
        # never reaches _record then).
        fl = self.flight
        if fl is not None:
            fl.record_span(name, t0, t1, tid)

    # -- reporting ---------------------------------------------------------

    def stage_attribution(self) -> Dict[str, Dict[str, float]]:
        """Per span name: count, total wall seconds, and SELF seconds
        (total minus time inside nested spans) across all threads."""
        with self._lock:
            return {name: {"count": c, "total_s": t, "self_s": s}
                    for name, (c, t, s) in sorted(self._agg.items())}

    def main_thread_covered_seconds(self) -> float:
        """Sum of SELF seconds recorded on the tracer's main thread —
        disjoint by construction (per-thread stack), so dividing by the
        driver's wall time gives the attributed-wall fraction."""
        with self._lock:
            return sum(s for _, _, s in self._main_agg.values())

    def export_chrome_trace(self, path) -> None:
        """Write Chrome trace-event JSON (load in Perfetto / about:tracing
        — see docs/OBSERVABILITY.md). One track per thread; the main
        thread is named so the driver phases are on top."""
        with self._lock:
            events = list(self.events)
            main_tid = self.main_tid
        pid = os.getpid()
        tid_ix, out = thread_track_metadata(
            {e["tid"] for e in events}, main_tid, pid)
        for e in events:
            out.append({"name": e["name"], "ph": "X", "cat": "photon",
                        "pid": pid, "tid": tid_ix[e["tid"]],
                        "ts": e["ts"], "dur": e["dur"]})
        with open(path, "w") as f:
            json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, f)


def thread_track_metadata(tids, main_tid: int, pid: int):
    """Chrome-trace thread tracks shared by ``export_chrome_trace`` and
    the flight recorder's dump (telemetry/recorder.py), so the two
    artifacts always line up in Perfetto: raw thread idents map to
    dense track indices (``tid_ix``) and the returned event list opens
    with one ``thread_name`` metadata record per track (the tracer's
    main thread is ``driver``, others ``worker-<ix>``)."""
    ordered = sorted(tids)
    tid_ix = {t: i for i, t in enumerate(ordered)}
    out = [{"name": "thread_name", "ph": "M", "pid": pid,
            "tid": tid_ix[t],
            "args": {"name": ("driver" if t == main_tid
                              else f"worker-{tid_ix[t]}")}}
           for t in ordered]
    return tid_ix, out


_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


class _Span:
    """One live span: pushed on the current thread's stack at enter,
    recorded (and its duration charged to the parent's child time) at
    exit."""

    __slots__ = ("name", "t0", "child_s")

    def __init__(self, name: str):
        self.name = name
        self.child_s = 0.0

    def __enter__(self):
        _TRACER._stack().append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        stack = _TRACER._stack()
        # Tolerate out-of-order exits (generator spans closed by GC):
        # unwind to this span rather than corrupting the stack.
        while stack and stack[-1] is not self:
            stack.pop()
        if stack:
            stack.pop()
        if stack:
            stack[-1].child_s += t1 - self.t0
        _TRACER._record(self.name, self.t0, t1, self.child_s,
                        threading.get_ident())
        return None


def span(name: str):
    """Open a named pipeline stage (context manager). Nestable and
    thread-aware; a shared no-op when telemetry is disabled. NEVER call
    inside jit-traced code (jaxlint: telemetry-in-trace)."""
    if not _reg._enabled:
        return _NOOP
    return _Span(name)


class _TimedSpan:
    __slots__ = ("_span", "_hist", "_counter")

    def __init__(self, name, hist, counter):
        self._span = _Span(name)
        self._hist = hist
        self._counter = counter

    def __enter__(self):
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        s = self._span
        s.__exit__(*exc)
        if self._hist is not None:
            self._hist.observe(time.perf_counter() - s.t0)
        if self._counter is not None:
            self._counter.inc()
        return None


def timed_span(name: str, histogram=None, counter=None):
    """``span(name)`` that additionally observes its wall duration into
    ``histogram`` and bumps ``counter`` on exit (e.g. per-iteration
    solver timing). Same no-op fast path as ``span`` when disabled."""
    if not _reg._enabled:
        return _NOOP
    return _TimedSpan(name, histogram, counter)


def stage_attribution() -> Dict[str, Dict[str, float]]:
    return _TRACER.stage_attribution()


def export_chrome_trace(path) -> None:
    _TRACER.export_chrome_trace(path)


def attribution_summary(wall_seconds: Optional[float] = None) -> Dict:
    """The metrics.json ``telemetry`` block: registry snapshot + stage
    attribution (+ attributed-wall fraction when the caller's wall time
    is given — driver phase spans partition the run, so the fraction is
    the share of end-to-end wall time the stages explain)."""
    out = {
        "metrics": _reg.registry().snapshot(),
        "stage_attribution": stage_attribution(),
        "dropped_trace_events": _TRACER.dropped_events,
    }
    if wall_seconds is not None:
        covered = _TRACER.main_thread_covered_seconds()
        out["wall_seconds"] = wall_seconds
        out["attributed_wall_seconds"] = covered
        out["attributed_wall_frac"] = (covered / wall_seconds
                                       if wall_seconds > 0 else 0.0)
    return out
