"""Flight recorder: a bounded ring of recent span events and periodic
registry deltas, dumpable as a Perfetto-compatible ``flight.json`` at
fault time.

``--trace-out`` records EVERY span event for a post-mortem you planned;
the flight recorder is for the fault you didn't: a wedged or crashing
serving/training process should leave evidence of what it was doing in
its last seconds without anyone having armed full tracing in advance.
The ring holds the most recent ``max_events`` completed spans (oldest
evicted, eviction counted) plus a registry-counter delta every
``snapshot_interval_s`` — enough to see which stage was hot and which
counters were moving right before the fault, at O(ring) memory forever.

Discipline matches PR 6's spans: when telemetry is disabled nothing
reaches the recorder at all (``span()`` returns the shared no-op, so the
disabled path stays zero-allocation); when telemetry is enabled but no
recorder is installed, the only cost is one attribute load + ``None``
check per completed span (``Tracer._record``). Installation is a driver
decision (``--flight-events``), never a library one.

Dump triggers (all write the same Chrome-trace JSON, loadable in
Perfetto like ``--trace-out``):

- **on demand**: the observability server's ``/debugz/dump`` route;
- **on unhandled driver fault**: both CLI drivers dump
  ``<output-dir>/flight.json`` before re-raising — the span context
  managers have already recorded every stage the exception unwound
  through, so the last events cover the failing stage;
- **on SIGTERM**: :func:`install_sigterm_dump` (drivers install it on
  the main thread; elsewhere it degrades to a no-op) dumps and then
  exits 143 via ``SystemExit`` so ``finally`` blocks still run.
"""

from __future__ import annotations

import importlib
import json
import os
import signal
import threading
import time
from collections import deque
from typing import Dict

# Submodules via importlib — the package shadows ``registry`` with the
# accessor function (see spans.py).
_reg = importlib.import_module("photon_ml_tpu.telemetry.registry")
_spans = importlib.import_module("photon_ml_tpu.telemetry.spans")
_tracectx = importlib.import_module("photon_ml_tpu.telemetry.tracectx")


class FlightRecorder:
    """Bounded in-memory recorder of recent telemetry activity.

    ``record_span`` is called by the tracer for every COMPLETED span
    while installed (install()); appends take one short lock (the same
    cost class as a registry counter inc — spans are per-stage, never
    per-row). Registry deltas piggyback on span completions and on the
    observability server's heartbeat ``tick()``: at most one capture per
    ``snapshot_interval_s``, storing only the counters/gauges whose
    value changed since the previous capture.
    """

    def __init__(self, max_events: int = 4096,
                 snapshot_interval_s: float = 5.0):
        if max_events < 1:
            raise ValueError(f"max_events must be >= 1, got {max_events}")
        self.max_events = int(max_events)
        self.snapshot_interval_s = float(snapshot_interval_s)
        self._ring: deque = deque(maxlen=self.max_events)
        self._lock = threading.Lock()
        self._appended = 0
        self._last_delta = 0.0
        self._prev_values: Dict[str, float] = {}
        self._delta_lock = threading.Lock()
        self.dumps = 0

    # -- recording ---------------------------------------------------------

    def record_span(self, name: str, t0: float, t1: float,
                    tid: int) -> None:
        with self._lock:
            self._ring.append(("span", name, t0, t1, tid))
            self._appended += 1
        if t1 - self._last_delta >= self.snapshot_interval_s:
            self._capture_delta(t1)

    def tick(self) -> None:
        """Heartbeat hook: capture a registry delta if one is due even
        while no spans are closing (an idle-but-alive process still
        leaves a counter trail)."""
        now = time.perf_counter()
        if now - self._last_delta >= self.snapshot_interval_s:
            self._capture_delta(now)

    def _capture_delta(self, now: float) -> None:
        # Non-blocking: if another thread is mid-capture, this span's
        # delta is simply the next one's job.
        if not self._delta_lock.acquire(blocking=False):
            return
        try:
            if now - self._last_delta < self.snapshot_interval_s:
                return
            self._last_delta = now
            counters, gauges, _ = _reg.registry().metrics()
            cur = {name: float(c.value) for name, c in counters.items()}
            cur.update({name: float(g.value)
                        for name, g in gauges.items()})
            changed = {k: v for k, v in cur.items()
                       if self._prev_values.get(k) != v}
            self._prev_values = cur
            if changed:
                with self._lock:
                    self._ring.append(("metrics", now, changed))
                    self._appended += 1
        finally:
            self._delta_lock.release()

    # -- installation ------------------------------------------------------

    def install(self) -> "FlightRecorder":
        """Attach to the process tracer: every completed span (while
        telemetry is enabled) lands in the ring."""
        _spans.tracer().flight = self
        return self

    def uninstall(self) -> None:
        tr = _spans.tracer()
        if tr.flight is self:
            tr.flight = None

    # -- dumping -----------------------------------------------------------

    def dump(self, path=None, reason: str = "manual",
             trace_id: str = None) -> dict:
        """Build (and optionally write) the flight dump: Chrome
        trace-event JSON (``traceEvents``: the ring's spans as ``ph: X``
        slices on per-thread tracks, registry deltas as ``ph: C``
        counter samples — Perfetto renders both) plus a ``flight`` block
        carrying the final registry snapshot, stage attribution, and the
        tail-sampled trace timelines (telemetry/tracectx.py — the dump
        carries the same per-request/per-solve evidence as a live
        ``/tracez`` scrape). ``trace_id`` tags the dump with the
        request/solve the fault belongs to (e.g. a diverged solve's
        context — ``flight.trace_id``). Timestamps share the tracer's
        epoch, so a flight dump and a ``--trace-out`` trace of the same
        run line up."""
        tr = _spans.tracer()
        with self._lock:
            events = list(self._ring)
            appended = self._appended
        pid = os.getpid()
        tid_ix, out = _spans.thread_track_metadata(
            {e[4] for e in events if e[0] == "span"}, tr.main_tid, pid)
        for e in events:
            if e[0] == "span":
                _, name, t0, t1, tid = e
                out.append({"name": name, "ph": "X", "cat": "flight",
                            "pid": pid, "tid": tid_ix[tid],
                            "ts": (t0 - tr.epoch) * 1e6,
                            "dur": (t1 - t0) * 1e6})
            else:
                _, t, changed = e
                out.append({"name": "registry", "ph": "C", "cat": "flight",
                            "pid": pid, "tid": 0,
                            "ts": (t - tr.epoch) * 1e6,
                            "args": changed})
        dump = {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            "flight": {
                "reason": reason,
                "events_in_ring": len(events),
                "events_seen": appended,
                "events_evicted": appended - len(events),
                "ring_capacity": self.max_events,
                "snapshot_interval_s": self.snapshot_interval_s,
                "final_metrics": _reg.registry().snapshot(),
                "stage_attribution": _spans.stage_attribution(),
                "trace_id": trace_id,
                "traces": _tracectx.trace_tail().snapshot(),
            },
        }
        if path is not None:
            with open(path, "w") as f:
                json.dump(dump, f)
        self.dumps += 1
        return dump

    def stats(self) -> dict:
        with self._lock:
            n, appended = len(self._ring), self._appended
        return {
            "events_in_ring": n,
            "events_seen": appended,
            "events_evicted": appended - n,
            "ring_capacity": self.max_events,
            "snapshot_interval_s": self.snapshot_interval_s,
            "dumps": self.dumps,
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._appended = 0
        self._prev_values = {}
        self._last_delta = 0.0


def install_sigterm_dump(recorder: FlightRecorder, path):
    """Dump flight evidence when the process is terminated: installs a
    SIGTERM handler that writes ``path`` then raises ``SystemExit(143)``
    (the conventional 128+SIGTERM code) so the driver's ``finally``
    blocks still run. Returns a zero-arg restore callable. Signal
    handlers can only live on the main thread — elsewhere (a driver run
    inside a worker thread, e.g. under test) this degrades to a no-op
    and returns a no-op restorer."""
    if threading.current_thread() is not threading.main_thread():
        return lambda: None
    prev = signal.getsignal(signal.SIGTERM)

    def _handler(signum, frame):
        try:
            recorder.dump(path, reason="SIGTERM")
        finally:
            raise SystemExit(143)

    signal.signal(signal.SIGTERM, _handler)

    def restore():
        signal.signal(signal.SIGTERM, prev)

    return restore
