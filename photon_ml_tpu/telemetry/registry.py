"""Process-wide metrics registry: counters, gauges, fixed-bucket latency
histograms with interpolated P50/P95/P99.

Every hot path in the system (serving engine, block-stream feeder, device
shard cache, streaming solvers) had grown its own ad-hoc ``_stats`` dict
with inconsistent keys and no latency distributions (the reference ships
first-class trackers — ml/optimization/game/*Tracker.scala — but our
streamed paths predated any shared sink). This registry is the ONE sink:
components keep their per-instance dicts for local introspection and
mirror into named registry metrics; drivers snapshot the registry into a
consistent snake_case ``telemetry.metrics`` block in metrics.json.

Telemetry is DISABLED by default: every mutation (``inc``/``set``/
``observe``) first checks one module-global flag and returns — no lock,
no allocation — so instrumented hot paths cost a function call + a
branch when nobody is looking (measured and asserted in
tests/test_telemetry.py; see docs/OBSERVABILITY.md for the budget). CLI
drivers enable it for their process; libraries never toggle it.

Metric names are dotted snake_case namespaces (``serving.requests``,
``data.shard_cache.hits``); the snapshot schema is part of the
metrics.json contract (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

# One switch for the whole telemetry layer (metrics AND spans — spans.py
# imports this module's accessors). Mutations early-return when off.
_enabled = False


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


#: Default latency buckets: geometric, 10 µs .. 100 s, 5 per decade —
#: ~17% relative resolution, 36 buckets, covering a single bucket
#: dispatch (~100 µs) through a full streamed epoch.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    round(10.0 ** (-5 + k / 5.0), 10) for k in range(36))


class Counter:
    """Monotonic counter. ``inc`` is a no-op while telemetry is off.
    ``calls`` counts inc() invocations (not the summed value) — what the
    bench's disabled-overhead estimate multiplies by the no-op cost."""

    __slots__ = ("name", "_value", "_calls", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._calls = 0
        self._lock = threading.Lock()

    def inc(self, amount: int = 1) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value += amount
            self._calls += 1

    @property
    def value(self) -> int:
        return self._value

    @property
    def calls(self) -> int:
        return self._calls

    def reset(self) -> None:
        with self._lock:
            self._value = 0
            self._calls = 0


class Gauge:
    """Last-write-wins instantaneous value (e.g. resident device bytes)."""

    __slots__ = ("name", "_value", "_calls")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._calls = 0

    def set(self, value) -> None:
        if not _enabled:
            return
        self._value = float(value)
        self._calls += 1

    @property
    def value(self) -> float:
        return self._value

    @property
    def calls(self) -> int:
        return self._calls

    def reset(self) -> None:
        self._value = 0.0
        self._calls = 0


class Histogram:
    """Fixed-bucket histogram with interpolated quantiles.

    Buckets are upper-edge-inclusive (a sample equal to a boundary lands
    in the bucket that boundary closes — Prometheus ``le`` semantics),
    with implicit underflow/overflow buckets beyond the configured
    boundaries. ``quantile(q)`` linearly interpolates inside the bucket
    containing rank ``q * count`` and clamps to the observed [min, max]
    — so a single-sample histogram returns that sample EXACTLY for every
    q, and a histogram whose samples all share one value is exact too;
    otherwise the error is bounded by the bucket width (~17% relative at
    the default buckets). Empty histograms return None.
    """

    __slots__ = ("name", "_bounds", "_bounds_arr", "_counts", "_count",
                 "_sum", "_min", "_max", "_lock", "_exemplars",
                 "exemplars_declared")

    def __init__(self, name: str,
                 buckets: Optional[Sequence[float]] = None,
                 exemplars: bool = False):
        self.name = name
        bounds = tuple(sorted(buckets if buckets is not None
                              else DEFAULT_LATENCY_BUCKETS))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._bounds = bounds
        self._bounds_arr = np.asarray(bounds, dtype=float)
        # counts[i] covers (bounds[i-1], bounds[i]]; counts[len(bounds)]
        # is the overflow bucket (bounds[-1], +inf).
        self._counts = [0] * (len(bounds) + 1)
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._lock = threading.Lock()
        # Last exemplar per bucket: (trace_id, value, unix_ts) or None.
        # Declared histograms (``exemplars=True`` — lint-checked to end
        # in ``_seconds`` by dev_scripts/metric_names.py) preallocate;
        # undeclared ones allocate lazily on the first exemplar, so the
        # common exemplar-free histogram stays two words lighter.
        self.exemplars_declared = bool(exemplars)
        self._exemplars = ([None] * (len(bounds) + 1)
                           if exemplars else None)

    def _set_exemplar(self, i: int, trace_id, v: float) -> None:
        # Caller holds self._lock. trace_id None = no exemplar (the
        # tracectx no-op context's id), so call sites stay branch-free.
        if trace_id is None:
            return
        ex = self._exemplars
        if ex is None:
            ex = self._exemplars = [None] * len(self._counts)
        ex[i] = (trace_id, v, time.time())

    def observe(self, value, n: int = 1, exemplar=None) -> None:
        """Record ``value`` (``n`` times — a coalesced dispatch settles a
        whole group at one latency, so the serving hot path takes the
        lock once per GROUP, not once per request). ``exemplar`` (a
        trace_id string, or None) stamps the landing bucket's exemplar
        slot — the link from a /metrics bucket to a /tracez timeline."""
        if not _enabled:
            return
        v = float(value)
        with self._lock:
            i = bisect.bisect_left(self._bounds, v)
            self._counts[i] += n
            self._count += n
            self._sum += v * n
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            if exemplar is not None:
                self._set_exemplar(i, exemplar, v)

    def observe_many(self, values, exemplars=None) -> None:
        """Vectorized ``observe`` for per-request samples that DIFFER
        within a settled group (queue waits, end-to-end latencies): one
        searchsorted + one lock acquisition for the whole batch instead
        of a locked bisect per sample. ``exemplars`` (optional, aligned
        with ``values``; entries may be None) stamps the LAST sample per
        bucket as that bucket's exemplar."""
        if not _enabled:
            return
        v = np.asarray(values, dtype=float).ravel()
        if v.size == 0:
            return
        idx = np.searchsorted(self._bounds_arr, v, side="left")
        binned = np.bincount(idx, minlength=len(self._counts))
        lo, hi = float(v.min()), float(v.max())
        with self._lock:
            for i in np.nonzero(binned)[0]:
                self._counts[i] += int(binned[i])
            self._count += int(v.size)
            self._sum += float(v.sum())
            if self._min is None or lo < self._min:
                self._min = lo
            if self._max is None or hi > self._max:
                self._max = hi
            if exemplars is not None:
                for i, val, tid in zip(idx, v, exemplars):
                    if tid is not None:
                        self._set_exemplar(int(i), tid, float(val))

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> Optional[float]:
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            if self._count == 0:
                return None
            target = q * self._count  # rank in [0, count]
            cum = 0
            for i, c in enumerate(self._counts):
                if c == 0:
                    continue
                if cum + c >= target:
                    lo = self._bounds[i - 1] if i > 0 else self._min
                    hi = (self._bounds[i] if i < len(self._bounds)
                          else self._max)
                    frac = (target - cum) / c
                    val = lo + frac * (hi - lo)
                    return min(max(val, self._min), self._max)
                cum += c
            return self._max  # q == 1 with float round-off

    def percentiles(self) -> Dict[str, Optional[float]]:
        return {"p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}

    def exposition_state(self) -> Tuple[Tuple[float, ...], list, int, float]:
        """Atomic ``(bounds, cumulative_counts, count, sum)`` for
        Prometheus exposition and SLO math: ``cumulative_counts[i]`` is
        the number of samples ``<= bounds[i]`` (``le`` semantics — the
        bucket layout already matches, so the mapping is a running sum,
        not a re-bin), and the implicit ``+Inf`` bucket equals
        ``count``. One lock acquisition, so a scrape racing ``observe``
        sees a consistent histogram (cumulative counts monotone,
        ``sum``/``count`` from the same instant)."""
        with self._lock:
            counts = list(self._counts)
            count, total = self._count, self._sum
        cum = []
        c = 0
        for v in counts[:-1]:
            c += v
            cum.append(c)
        return self._bounds, cum, count, total

    def snapshot(self) -> Dict:
        with self._lock:
            count, total = self._count, self._sum
            mn, mx = self._min, self._max
        out = {"count": count, "sum": total,
               "mean": (total / count if count else None),
               "min": mn, "max": mx}
        out.update(self.percentiles())
        # Exemplars ride only when stamped (conditional key: the
        # exemplar-free histogram snapshot schema is unchanged).
        ex = self.exemplars()
        if ex:
            out["exemplars"] = {
                str(b): {"trace_id": t, "value": v, "unix_ts": ts}
                for b, (t, v, ts) in ex.items()}
        return out

    def bucket_counts(self) -> Dict:
        """(upper-edge -> count) including the +inf overflow bucket."""
        with self._lock:
            out = {b: c for b, c in zip(self._bounds, self._counts)}
            out["+inf"] = self._counts[-1]
        return out

    def exemplars(self) -> Dict:
        """(upper-edge or "+inf") -> (trace_id, value, unix_ts) for
        buckets that have one. Empty dict when none were ever stamped.
        Advisory data — read under the lock so a concurrent observe
        can't tear a tuple, but exposition pairs these with bucket
        counts from a separate read (an exemplar is a POINTER into
        /tracez, not part of the histogram's consistency contract)."""
        with self._lock:
            ex = self._exemplars
            if ex is None:
                return {}
            out = {b: e for b, e in zip(self._bounds, ex)
                   if e is not None}
            if ex[-1] is not None:
                out["+inf"] = ex[-1]
        return out

    def state(self) -> Dict:
        """Full raw state for federation (telemetry/federation.py): the
        per-bucket RAW counts (not cumulative — bucket-wise addition
        across processes is exact because every process shares the
        fixed ladder), the exact scalars, and per-bucket exemplars
        keyed by bucket INDEX (JSON-stable; the +inf overflow bucket is
        the last index). One lock acquisition, so the exported state is
        internally consistent under concurrent observation."""
        with self._lock:
            ex = {}
            if self._exemplars is not None:
                ex = {str(i): list(e)
                      for i, e in enumerate(self._exemplars)
                      if e is not None}
            return {
                "bounds": list(self._bounds),
                "counts": list(self._counts),
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
                "exemplars": ex,
            }

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self._bounds) + 1)
            self._count = 0
            self._sum = 0.0
            self._min = self._max = None
            if self._exemplars is not None:
                self._exemplars = [None] * len(self._counts)


class MetricsRegistry:
    """Name -> metric store. ``counter``/``gauge``/``histogram`` are
    get-or-create (so module-level handles and late lookups share the
    same object); ``snapshot`` renders the whole registry as the plain
    snake_case dict that lands in metrics.json / BENCH output."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            m = self._counters.get(name)
            if m is None:
                m = self._counters[name] = Counter(name)
            return m

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            m = self._gauges.get(name)
            if m is None:
                m = self._gauges[name] = Gauge(name)
            return m

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None,
                  exemplars: bool = False) -> Histogram:
        with self._lock:
            m = self._histograms.get(name)
            if m is None:
                m = self._histograms[name] = Histogram(
                    name, buckets, exemplars=exemplars)
            return m

    def metrics(self) -> Tuple[Dict[str, Counter], Dict[str, Gauge],
                               Dict[str, Histogram]]:
        """Shallow copies of the three name->metric maps (the exposition
        renderer and flight recorder iterate metric OBJECTS, not the
        plain-value snapshot)."""
        with self._lock:
            return (dict(self._counters), dict(self._gauges),
                    dict(self._histograms))

    def snapshot(self) -> Dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: v.value for k, v in sorted(counters.items())},
            "gauges": {k: v.value for k, v in sorted(gauges.items())},
            "histograms": {k: v.snapshot()
                           for k, v in sorted(histograms.items())},
        }

    def mutation_calls(self) -> int:
        """Total inc()/set()/observe() invocations since the last reset
        — the disabled fast path executes this many no-op calls, so the
        bench multiplies it by the measured no-op cost to bound the
        disabled-telemetry overhead."""
        with self._lock:
            return (sum(c.calls for c in self._counters.values())
                    + sum(g.calls for g in self._gauges.values())
                    + sum(h.count for h in self._histograms.values()))

    def reset(self) -> None:
        """Zero every metric (objects and handles stay valid)."""
        with self._lock:
            metrics = (list(self._counters.values())
                       + list(self._gauges.values())
                       + list(self._histograms.values()))
        for m in metrics:
            m.reset()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry instance."""
    return _REGISTRY
