"""SLO declarations and burn-rate tracking over the metrics registry.

Objectives are declared AGAINST existing metrics — no new
instrumentation: a latency objective reads a registry histogram (e.g.
the front-end's end-to-end ``serving.frontend.request_latency_seconds``)
and an availability/ratio objective reads counters (e.g. shed rate =
``rejected / (admitted + rejected)``).

Burn-rate semantics (the number ``evaluate()`` maintains):

- A latency objective "P<q> <= T" is equivalently the availability
  statement "at most ``1 - q`` of requests may exceed ``T``". The
  histogram's ``le`` buckets give the actual fraction over ``T``
  (linear interpolation inside the bucket containing ``T``; exact when
  ``T`` sits on a bucket bound — pick thresholds inside the configured
  bucket range), and ``burn_rate = frac_over / (1 - q)``: the rate the
  error budget is being consumed relative to the rate the objective
  allows. ``burn_rate <= 1`` is compliant; 2 means burning budget twice
  as fast as allowed.
- A ratio objective "num/den <= R" has ``burn_rate = ratio / R``.

Each objective maintains registry twins (surfaced in ``/metrics``,
``/statusz`` and metrics.json): counters ``slo.<name>.evaluations`` and
``slo.<name>.violations`` (evaluations observed with ``burn_rate > 1``)
and gauge ``slo.<name>.burn_rate``. The tracker also keeps plain-int
locals so its report stays live even while telemetry is disabled.

Declaration syntax (CLI ``--slo``, docs/OBSERVABILITY.md):

- ``[name=]p99:serving.frontend.request_latency_seconds<=50ms``
  (quantile ``p50``/``p95``/``p99``/``p99.9``...; duration suffix
  ``us``/``ms``/``s``, bare numbers are seconds)
- ``[name=]ratio:serving.frontend.rejected/serving.frontend.admitted+``
  ``serving.frontend.rejected<=0.02`` (denominator counters sum)
- ``[name=]value:serving.model.default.score_drift_psi<=0.25`` (a
  registry GAUGE must stay <= the bound; ``burn_rate = value / max``).
  This is what makes COMPUTED gauges — the ``--distmon`` drift scores,
  refreshed by scrape hooks before every evaluation — SLO-able with no
  new alerting code: the same burn/violation counters, /statusz block
  and metrics.json ``slo`` entry as the latency/ratio kinds. A gauge
  that was never set burns nothing (no traffic to judge).

An explicit ``name=`` prefix names the objective's metric family;
otherwise a snake_case name is derived from the spec.
"""

from __future__ import annotations

import bisect
import dataclasses
import importlib
import re
import threading
from typing import Dict, Optional, Sequence, Tuple, Union

# Submodule via importlib — the package shadows ``registry`` with the
# accessor function (see spans.py).
_reg = importlib.import_module("photon_ml_tpu.telemetry.registry")

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_DURATION_RE = re.compile(r"^([0-9]*\.?[0-9]+)(us|ms|s)?$")


@dataclasses.dataclass(frozen=True)
class LatencyObjective:
    """``quantile`` of histogram ``histogram`` must be <= ``threshold_s``
    — tracked in its availability form (fraction over threshold vs the
    ``1 - quantile`` budget)."""

    name: str
    histogram: str
    quantile: float
    threshold_s: float

    def describe(self) -> str:
        return (f"p{self.quantile * 100:g}({self.histogram}) "
                f"<= {self.threshold_s:g}s")


@dataclasses.dataclass(frozen=True)
class RatioObjective:
    """``numerator / sum(denominators)`` (registry counters) must be
    <= ``max_ratio`` (e.g. shed-rate <= 2%)."""

    name: str
    numerator: str
    denominators: Tuple[str, ...]
    max_ratio: float

    def describe(self) -> str:
        return (f"{self.numerator} / "
                f"{' + '.join(self.denominators)} <= {self.max_ratio:g}")


@dataclasses.dataclass(frozen=True)
class ValueObjective:
    """Registry gauge ``gauge`` must stay <= ``max_value`` (e.g. a
    drift score <= 0.25); ``burn_rate = value / max_value``. Judged
    only once the gauge has been set at least once."""

    name: str
    gauge: str
    max_value: float

    def describe(self) -> str:
        return f"{self.gauge} <= {self.max_value:g}"


Objective = Union[LatencyObjective, RatioObjective, ValueObjective]


def _parse_duration_s(text: str) -> float:
    m = _DURATION_RE.match(text.strip())
    if not m:
        raise ValueError(f"bad duration {text!r} "
                         "(expected e.g. 50ms, 200us, 1.5s, 0.05)")
    v = float(m.group(1))
    return v * {"us": 1e-6, "ms": 1e-3, "s": 1.0, None: 1.0}[m.group(2)]


def parse_slo(spec: str) -> Objective:
    """Parse one ``--slo`` declaration (module docstring syntax)."""
    spec = spec.strip()
    name = None
    if "=" in spec.split(":", 1)[0]:
        name, _, spec = spec.partition("=")
        name = name.strip()
        if not _NAME_RE.match(name):
            raise ValueError(
                f"bad SLO name {name!r} (snake_case, [a-z0-9_])")
    kind, sep, rest = spec.partition(":")
    if not sep:
        raise ValueError(f"bad SLO spec {spec!r}: expected "
                         "'p<q>:<histogram><=<duration>' or "
                         "'ratio:<num>/<den>[+<den>...]<=<frac>'")
    lhs, le, rhs = rest.partition("<=")
    if not le:
        raise ValueError(f"bad SLO spec {spec!r}: missing '<='")
    lhs, rhs = lhs.strip(), rhs.strip()
    if kind.startswith("p"):
        try:
            q = float(kind[1:]) / 100.0
        except ValueError:
            raise ValueError(f"bad SLO quantile {kind!r} (e.g. p99)")
        if not 0.0 < q < 1.0:
            raise ValueError(f"SLO quantile must be in (0, 1), got {q}")
        return LatencyObjective(
            name=name or f"p{kind[1:].replace('.', '_')}_"
                         f"{lhs.replace('.', '_')}",
            histogram=lhs, quantile=q,
            threshold_s=_parse_duration_s(rhs))
    if kind == "ratio":
        num, slash, dens = lhs.partition("/")
        if not slash or not dens:
            raise ValueError(
                f"bad ratio SLO {spec!r}: expected num/den[+den...]")
        return RatioObjective(
            name=name or f"ratio_{num.strip().replace('.', '_')}",
            numerator=num.strip(),
            denominators=tuple(d.strip() for d in dens.split("+")),
            max_ratio=float(rhs))
    if kind == "value":
        if not lhs:
            raise ValueError(
                f"bad value SLO {spec!r}: expected value:<gauge><=X")
        return ValueObjective(
            name=name or f"value_{lhs.replace('.', '_')}",
            gauge=lhs, max_value=float(rhs))
    raise ValueError(f"unknown SLO kind {kind!r} (p<q>, ratio or value)")


def _frac_over_threshold(hist: _reg.Histogram,
                         threshold: float) -> Optional[float]:
    """Fraction of observations > ``threshold`` from the histogram's
    cumulative ``le`` buckets (interpolated inside the containing
    bucket; exact at bucket bounds). ``None`` while empty. A threshold
    past the top bound counts the whole overflow bucket as bad — the
    conservative reading, since overflow samples' values are unknown."""
    bounds, cum, count, _ = hist.exposition_state()
    if count == 0:
        return None
    i = bisect.bisect_left(bounds, threshold)
    if i >= len(bounds):
        good = cum[-1]
    else:
        lo = bounds[i - 1] if i > 0 else 0.0
        prev = cum[i - 1] if i > 0 else 0
        in_bucket = cum[i] - prev
        frac = ((threshold - lo) / (bounds[i] - lo)
                if bounds[i] > lo else 1.0)
        good = prev + frac * in_bucket
    return max(0.0, min(1.0, 1.0 - good / count))


def measure_objective(o: Objective, reg) -> Tuple[Optional[float],
                                                  Optional[float]]:
    """(current value, burn rate) of one objective against ``reg`` — any
    registry-shaped object (``counter``/``gauge``/``histogram``
    accessors), so federation can point it at a merged fleet view. Burn
    is ``None`` while the objective has no traffic to judge (no
    observations / zero denominator / never-set gauge): no traffic
    burns no budget."""
    if isinstance(o, LatencyObjective):
        hist = reg.histogram(o.histogram)
        frac_over = _frac_over_threshold(hist, o.threshold_s)
        if frac_over is None:
            return None, None
        return (hist.quantile(o.quantile),
                frac_over / (1.0 - o.quantile))
    if isinstance(o, ValueObjective):
        g = reg.gauge(o.gauge)
        if g.calls == 0:
            return None, None  # never set: nothing to judge
        v = g.value
        return v, (v / o.max_value if o.max_value > 0
                   else float("inf"))
    den = sum(reg.counter(d).value for d in o.denominators)
    if den <= 0:
        return None, None
    ratio = reg.counter(o.numerator).value / den
    return ratio, (ratio / o.max_ratio if o.max_ratio > 0
                   else float("inf"))


def evaluate_specs(specs: Sequence[Union[Objective, str]],
                   reg) -> Dict[str, dict]:
    """Statelessly evaluate SLO specs against an arbitrary registry —
    no registry-twin counters, no evaluation history. This is how the
    fleet aggregator re-judges every peer-declared objective against
    the MERGED registry: because counters sum and histogram buckets add
    exactly, the fleet burn rate is the true whole-fleet number, not an
    average of per-process burns."""
    out = {}
    for spec in specs:
        o = parse_slo(spec) if isinstance(spec, str) else spec
        current, burn = measure_objective(o, reg)
        entry = {
            "kind": ("latency" if isinstance(o, LatencyObjective)
                     else "value" if isinstance(o, ValueObjective)
                     else "ratio"),
            "objective": o.describe(),
            "current": current,
            "burn_rate": burn,
            "compliant": burn is None or burn <= 1.0,
        }
        if isinstance(o, LatencyObjective):
            entry["quantile"] = o.quantile
            entry["threshold_s"] = o.threshold_s
        elif isinstance(o, ValueObjective):
            entry["max_value"] = o.max_value
        else:
            entry["max_ratio"] = o.max_ratio
        out[o.name] = entry
    return out


class SLOTracker:
    """Evaluates a fixed set of objectives against the process registry
    and maintains their burn-rate counters. ``evaluate()`` is called by
    the observability server's ``/statusz`` route and heartbeat, by the
    drivers when writing metrics.json, and by the bench — each call is
    one observation of every objective."""

    def __init__(self, objectives: Sequence[Union[Objective, str]]):
        objs = [parse_slo(o) if isinstance(o, str) else o
                for o in objectives]
        names = [o.name for o in objs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {sorted(names)}")
        self.objectives: Tuple[Objective, ...] = tuple(objs)
        reg = _reg.registry()
        self._handles = {}
        self._local: Dict[str, Dict[str, int]] = {}
        # evaluate() is called from several threads at once (heartbeat
        # ticker + concurrent /statusz handlers + the driver's finish);
        # the registry twins have their own locks, but the plain-int
        # locals need this one so the two published counts agree.
        self._lock = threading.Lock()
        for o in self.objectives:
            pre = f"slo.{o.name}."
            self._handles[o.name] = (reg.counter(pre + "evaluations"),
                                     reg.counter(pre + "violations"),
                                     reg.gauge(pre + "burn_rate"))
            self._local[o.name] = {"evaluations": 0, "violations": 0}

    def _measure(self, o: Objective):
        return measure_objective(o, _reg.registry())

    def evaluate(self) -> Dict[str, dict]:
        out = {}
        for o in self.objectives:
            current, burn = self._measure(o)
            compliant = burn is None or burn <= 1.0
            evals, violations, burn_gauge = self._handles[o.name]
            with self._lock:
                local = self._local[o.name]
                local["evaluations"] += 1
                if not compliant:
                    local["violations"] += 1
                n_evals, n_viol = (local["evaluations"],
                                   local["violations"])
            evals.inc()
            if not compliant:
                violations.inc()
            burn_gauge.set(0.0 if burn is None else burn)
            entry = {
                "kind": ("latency" if isinstance(o, LatencyObjective)
                         else "value" if isinstance(o, ValueObjective)
                         else "ratio"),
                "objective": o.describe(),
                "current": current,
                "burn_rate": burn,
                "compliant": compliant,
                "evaluations": n_evals,
                "violations": n_viol,
            }
            if isinstance(o, LatencyObjective):
                entry["quantile"] = o.quantile
                entry["threshold_s"] = o.threshold_s
            elif isinstance(o, ValueObjective):
                entry["max_value"] = o.max_value
            else:
                entry["max_ratio"] = o.max_ratio
            out[o.name] = entry
        return out
